"""Estimator — the distributed training core.

TPU-native re-design of the reference's training stack:

- ``Estimator.train/evaluate`` facade (reference
  zoo/.../pipeline/estimator/Estimator.scala:65-183),
- ``InternalDistriOptimizer.train`` — the distributed driver
  (Topology.scala:1076-1259).

The reference's per-iteration machinery is two Spark jobs: (1) each task
forward/backwards its partition slice on core-local model replicas; (2)
gradient slices are shuffled to owner tasks, updated, and broadcast back
through the block manager (docs/docs/wp-bigdl.md:148-164).  Here the whole
iteration is ONE jit-compiled SPMD program: the global batch arrives sharded
over the mesh ``data`` axis, XLA partitions the forward/backward per chip,
inserts a reduce-scatter/all-gather (the ``psum``) over ICI for the gradient,
and fuses the optimizer update — donated buffers, so weights update in place
in HBM.

Also re-implemented with exact-state semantics instead of best-effort:

- triggers for validation/checkpoint (ZooTrigger),
- gradient clipping (constant / L2-norm, Topology.scala clipping setters),
- checkpoint + resume including the *data iterator* position,
- the retry-from-checkpoint failure loop (Topology.scala:1171-1253,
  ``bigdl.failure.retryTimes`` default 5).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
from analytics_zoo_tpu.common.safe_pickle import (
    safe_load,
)
import queue
import threading
import time
import weakref
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.engine import (
    ZooContext,
    cast_floats,
    get_zoo_context,
)
from analytics_zoo_tpu.common.triggers import (
    EveryEpoch,
    MaxEpoch,
    TrainingState,
    ZooTrigger,
)
from analytics_zoo_tpu.common.utils import time_it
from analytics_zoo_tpu.feature.dataset import FeatureSet
from analytics_zoo_tpu.metrics import (
    StepMetrics,
    StragglerDetector,
    get_flight_recorder,
    get_health,
    get_registry,
    maybe_start_from_env,
    record_device_memory,
    register_predump_hook,
    span,
)

logger = logging.getLogger("analytics_zoo_tpu")

_SENTINEL = object()  # feeder-exhausted marker


def _process_shard() -> tuple[int, int] | None:
    """(process_index, process_count) under multi-host jax, else None.

    Handed to ``FeatureSet.batches`` so each host materializes only its rows
    of every global batch (per-partition locality, the role of the
    reference's RDD partitioning — FeatureSet.scala:240-289); see
    ``parallel.multihost.process_local_batch_slice``.
    """
    if jax.process_count() > 1:
        return (jax.process_index(), jax.process_count())
    return None


from analytics_zoo_tpu.ops.moe import collect_aux_cost as _collect_aux_cost


def _normalize_grad_clip(grad_clip):
    """Canonical grad-clip spec shared by every train-step builder:
    ``None | ("l2norm", max) | ("const", lo, hi)``; a bare scalar is
    accepted as a max-norm.  Tag AND arity are validated here so a bad
    spec fails at build time, not from inside a jit trace."""
    if grad_clip is None:
        return None
    if not isinstance(grad_clip, (tuple, list)):
        return ("l2norm", float(grad_clip))
    t = tuple(grad_clip)
    if len(t) == 2 and t[0] == "l2norm":
        return ("l2norm", float(t[1]))
    if len(t) == 3 and t[0] == "const":
        return ("const", float(t[1]), float(t[2]))
    raise ValueError(f"unknown grad clip {grad_clip!r}")


def _clip_grads(grads, grad_clip):
    grad_clip = _normalize_grad_clip(grad_clip)
    if grad_clip is None:
        return grads
    if grad_clip[0] == "const":
        _, lo, hi = grad_clip
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, lo, hi), grads)
    _, max_norm = grad_clip
    leaves = jax.tree_util.tree_leaves(grads)
    norm = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2)
                        for g in leaves))
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-12))
    return jax.tree_util.tree_map(lambda g: g * scale, grads)


def _chunk_batches(batch_iter, k: int):
    """Group a batch stream into K-sized chunks for the fused dispatch.

    Yields ``("scan", [b_0..b_{k-1}])`` for every full chunk and
    ``("single", b)`` per leftover batch — the partial tail of an epoch
    (or of a mid-epoch resume window) degrades to the K=1 step, so the
    optimizer sees exactly the same batch sequence as an unfused run.
    """
    chunk = []
    for b in batch_iter:
        chunk.append(b)
        if len(chunk) == k:
            yield ("scan", chunk)
            chunk = []
    for b in chunk:
        yield ("single", b)


def _chunk_batches_dynamic(batch_iter, k_fn):
    """Dynamic-K chunker for the autotune plane (feature/autotune.py):
    the target K is re-read from ``k_fn()`` at every CHUNK boundary, so
    the controller's hill-climb takes effect within one dispatch of a
    decision while any in-flight chunk keeps the size it started with.

    The batch SEQUENCE is untouched — only the grouping changes — and
    per-inner-step RNG folds on the global step index, so the loss
    trajectory is bit-identical for every K schedule this can emit
    (the same contract :func:`_chunk_batches` rides).  K=1 chunks are
    emitted as ``("single", b)`` so they dispatch the plain (non-scan)
    program, exactly like the static K=1 path; a leftover tail degrades
    to singles like the static chunker.
    """
    chunk = []
    k = max(1, int(k_fn()))
    for b in batch_iter:
        if k <= 1:
            yield ("single", b)
            k = max(1, int(k_fn()))
            continue
        chunk.append(b)
        if len(chunk) == k:
            yield ("scan", chunk)
            chunk = []
            k = max(1, int(k_fn()))
    for b in chunk:
        yield ("single", b)


class _DeviceFeeder:
    """Double-buffered host→device infeed.

    A background thread assembles the next host batch and dispatches its
    (async) ``device_put`` while the devices run the current step — the
    host/device overlap SURVEY.md §7 names hard-part #1.  Plays the role of
    the reference's per-partition RDD iterators keeping executors fed
    (FeatureSet.scala:240-289), minus the Spark scheduling gap between
    iterations.

    Under ``ZOO_STEPS_PER_DISPATCH > 1`` the estimator hands it the
    ``_chunk_batches`` stream and a shard_fn that STACKS each full chunk
    into a [K, batch, ...] super-batch (``ZooContext.shard_batch_stacked``)
    — the queue then double-buffers super-batches, composing unchanged
    with ``ZOO_INFEED_DEPTH`` and the PR-4 prefetch plane upstream.
    """

    _END = object()

    def __init__(self, batches, shard_fn, depth: int = 2,
                 heartbeat=None, on_exit=None):
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: BaseException | None = None

        def run():
            try:
                for b in batches:
                    if heartbeat is not None:
                        heartbeat()  # /healthz: the feeder is alive
                    item = shard_fn(b)
                    while not self._stop.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            # keep beating while blocked on a full
                            # queue: waiting for the consumer (e.g.
                            # through a multi-minute first-step compile)
                            # is not being wedged
                            if heartbeat is not None:
                                heartbeat()
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # re-raised on the consumer side
                self._err = e
            finally:
                # on_exit runs ON THIS THREAD, sequenced after every
                # beat above — the estimator uses it to unregister the
                # infeed health component, so a feeder that finished
                # early (small epoch fully buffered) cannot read as
                # stale during a slow step, and no beat can resurrect
                # the component after its unregister
                if on_exit is not None:
                    try:
                        on_exit()
                    except Exception:
                        pass
                while not self._stop.is_set():
                    try:
                        self._q.put(self._END, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(
            target=run, daemon=True, name="zoo-infeed")
        self._thread.start()

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._END:
                if self._err is not None:
                    raise self._err
                return
            yield item

    def stop(self):
        self._stop.set()


def _gather_for_save(tree):
    """Multi-host: replicate plan-sharded device leaves SPMD — every
    process participates — so the single writer's host conversion can
    read the full value (``np.asarray`` on a non-fully-addressable
    ``jax.Array`` raises).  Fully-addressable leaves (every single-host
    array, replicated multi-host state) pass through untouched, so the
    pre-partitioner save path is byte-for-byte unchanged."""
    from jax.sharding import NamedSharding, PartitionSpec

    def fix(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable \
                and isinstance(leaf.sharding, NamedSharding):
            repl = NamedSharding(leaf.sharding.mesh, PartitionSpec())
            # zoolint: disable=raw-jit -- SPMD replicate-identity (one trivial all-gather per leaf shape, deduped by jit's own cache); not a model program the compile plane should meter
            return jax.jit(lambda a: a, out_shardings=repl)(leaf)
        return leaf

    return jax.tree_util.tree_map(fix, tree)


def _async_checkpoint_enabled() -> bool:
    """``ZOO_ASYNC_CHECKPOINT`` env gate, default ON.  ``0`` forces the
    serialization+rename back onto the caller's thread (the pre-overlap
    behavior) — the conservative fallback, and the baseline leg of
    ``bench.py --overlap``'s checkpoint-stall comparison."""
    raw = os.environ.get("ZOO_ASYNC_CHECKPOINT")
    if raw is None:
        return True
    s = str(raw).strip().lower()
    if s in ("", "1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    raise ValueError(
        f"ZOO_ASYNC_CHECKPOINT must be a boolean "
        f"(1/0/true/false/yes/no/on/off), got {raw!r}")


# ---------------------------------------------------------------------------
# Shutdown-ordering fix (ISSUE 16): the SIGTERM flight-dump handler
# (metrics/flight.py, PR 2) and the async checkpoint writer thread
# (PR 14) used to race at process death — the dump could be written
# while the daemon writer was mid-pickle, so the postmortem's final
# ``ckpt`` event said "start" with no complete/error, and the writer
# died silently with the process.  Every live _Checkpointer registers
# here; the flight recorder runs the flush (bounded by
# ZOO_ELASTIC_GRACE_MS) BEFORE snapshotting the ring, so a SIGTERM dump
# records the snapshot as flushed-or-failed, never as a mystery.
# ---------------------------------------------------------------------------

_live_ckpt_lock = threading.Lock()
# keyed by id(): _Checkpointer is a dataclass (eq, no hash), so a
# WeakSet cannot hold it
_live_checkpointers: "weakref.WeakValueDictionary" = (  # guarded-by: _live_ckpt_lock
    weakref.WeakValueDictionary())


def _dump_flush_grace_s() -> float:
    """Lenient runtime read of ZOO_ELASTIC_GRACE_MS (the eager
    validation lives in ZooConfig; this path runs inside a dying
    process and must never raise)."""
    try:
        return max(0.0, int(os.environ.get("ZOO_ELASTIC_GRACE_MS",
                                           "5000")) / 1e3)
    except (TypeError, ValueError):
        return 5.0


def _flush_checkpointers_for_dump() -> None:
    with _live_ckpt_lock:
        cks = list(_live_checkpointers.values())
    for c in cks:
        c._flush_for_dump()


@dataclasses.dataclass
class _Checkpointer:
    """Snapshot (params, opt_state, model state, step/epoch, iterator pos).

    Role of BigDL's ``model.<iter>`` + ``optimMethod.<iter>`` snapshots
    (Topology.scala:245-255), plus data-iterator state the reference never
    checkpointed (its RDD iterators restart from scratch on resume).

    Saves are ASYNC (the orbax-style plan of SURVEY.md §5): the caller's
    thread only dispatches device-side copies of the live buffers (so the
    next step's donation can't touch them), while D2H transfer, pickling
    and the atomic rename happen on a background thread.  At most one save
    is in flight; a newer save (and ``latest``/``list``) waits for it.

    Latency-hiding plane (ISSUE 15): the caller-visible stall is recorded
    per save into ``zoo_ckpt_stall_seconds``; the writer thread runs as
    the ``checkpoint_writer`` health component and records ``ckpt``
    flight events (start/complete/error); each completed snapshot
    atomically updates a ``LATEST`` pointer file AFTER the snapshot's own
    atomic rename, so a kill -9 at any point leaves the pointer naming
    the previous COMPLETE snapshot.  ``ZOO_ASYNC_CHECKPOINT=0`` runs the
    write inline (synchronous fallback) — the stall histogram then
    measures the full gather+serialize+rename.
    """

    path: str
    over_write: bool = True
    keep: int = 3

    LATEST = "LATEST"

    def __post_init__(self):
        self._pending: threading.Thread | None = None
        self._pending_err: BaseException | None = None
        reg = get_registry()
        self._stall_hist = reg.histogram(
            "zoo_ckpt_stall_seconds",
            "train-thread stall per checkpoint save: join of the "
            "previous in-flight write + device-side snapshot dispatch "
            "(the whole gather+serialize+rename when "
            "ZOO_ASYNC_CHECKPOINT=0)")
        self._write_hist = reg.histogram(
            "zoo_ckpt_write_seconds",
            "background D2H gather + serialization + atomic-rename time "
            "per snapshot")
        self._writes = reg.counter(
            "zoo_ckpt_writes_total", "completed checkpoint snapshots")
        with _live_ckpt_lock:
            _live_checkpointers[id(self)] = self
        register_predump_hook(_flush_checkpointers_for_dump)

    def _flush_for_dump(self):
        """Bounded join of the in-flight async write so a flight dump
        (SIGTERM/exit/crash) contains this snapshot's final ``ckpt``
        complete/error event.  Never raises, never unbounded: a wedged
        writer only delays the dump by the grace window."""
        t = self._pending
        if t is not None and t.is_alive():
            t.join(timeout=_dump_flush_grace_s())

    def _wait(self):
        if self._pending is not None:
            self._pending.join()
            self._pending = None
            if self._pending_err is not None:
                err, self._pending_err = self._pending_err, None
                raise err

    FORMAT_VERSION = 1

    def save(self, tag: str, payload: dict) -> str:
        fname = os.path.join(self.path, f"ckpt-{tag}.pkl")
        # Multi-host: exactly one writer.  Every process calls save(),
        # but only process 0 touches the shared checkpoint dir —
        # concurrent writers racing os.replace on shared storage would
        # interleave half-written snapshots.  Plan-sharded leaves
        # (fsdp/zero1) are replicated SPMD FIRST — all processes
        # participate in that collective, THEN non-writers return —
        # so the writer's host gather sees every shard.
        t0 = time.perf_counter()
        shard = _process_shard()
        if shard is not None:
            payload = _gather_for_save(payload)
            if shard[0] != 0:
                return fname
        self._wait()
        os.makedirs(self.path, exist_ok=True)
        # Device-side copies: cheap dispatches; the live arrays stay free
        # to be donated by the next train step.
        snap = jax.tree_util.tree_map(
            lambda a: jnp.copy(a) if isinstance(a, jax.Array) else a,
            payload)

        def write():
            health = get_health()
            flight = get_flight_recorder()
            t_w = time.perf_counter()
            try:
                health.heartbeat("checkpoint_writer")
                flight.record("ckpt", phase="start", tag=str(tag),
                              file=os.path.basename(fname))
                # device arrays → host in ONE batched device_get (was:
                # np.asarray per leaf — a serial D2H sync each); python
                # scalars/strings (step counters, the plan's spec
                # record) stay as-is
                leaves, treedef = jax.tree_util.tree_flatten(snap)
                dev = [i for i, a in enumerate(leaves)
                       if isinstance(a, jax.Array)]
                for i, v in zip(dev,
                                jax.device_get([leaves[i] for i in dev])):
                    leaves[i] = v
                host = jax.tree_util.tree_unflatten(treedef, [
                    a if isinstance(a, (str, bytes, bool, int, float,
                                        np.ndarray)) else np.asarray(a)
                    for a in leaves])
                host["__ckpt_meta__"] = {
                    "format_version": self.FORMAT_VERSION,
                    "saved_unix": time.time(),
                    "jax_version": jax.__version__,
                }
                tmp = fname + ".tmp"
                with open(tmp, "wb") as f:
                    pickle.dump(host, f)
                    # fsync BEFORE the rename: os.replace alone makes
                    # the name durable without the data — after a power
                    # loss the pointer could name a truncated snapshot
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, fname)
                # crash-safe "last complete" pointer: updated only AFTER
                # the snapshot's own atomic rename
                self._write_latest(os.path.basename(fname))
                self._gc()
                dt = time.perf_counter() - t_w
                self._writes.inc()
                self._write_hist.observe(dt)
                health.set_status("checkpoint_writer", True)
                flight.record("ckpt", phase="complete", tag=str(tag),
                              seconds=round(dt, 6))
            except BaseException as e:  # surfaced on the next save/_wait
                health.set_status("checkpoint_writer", False)
                flight.record("ckpt", phase="error", tag=str(tag),
                              error=repr(e))
                self._pending_err = e

        if _async_checkpoint_enabled():
            self._pending = threading.Thread(target=write, daemon=True,
                                             name="zoo-ckpt")
            self._pending.start()
            # the caller-visible stall: previous-write join + snapshot
            # dispatch; the serialization overlaps the next train steps
            self._stall_hist.observe(time.perf_counter() - t0)
        else:
            write()
            self._stall_hist.observe(time.perf_counter() - t0)
            if self._pending_err is not None:
                err, self._pending_err = self._pending_err, None
                raise err
        return fname

    def _write_latest(self, basename: str):
        ptr = os.path.join(self.path, self.LATEST)
        tmp = ptr + ".tmp"
        with open(tmp, "w") as f:
            f.write(basename)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, ptr)
        # fsync the DIRECTORY so both renames (snapshot + pointer) are
        # durable, not just the file contents
        try:
            dfd = os.open(self.path, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:  # e.g. fs without directory fsync support
            pass

    def _gc(self):
        # raw listing: _gc runs ON the writer thread, so it must not _wait
        files = self._list_files()
        for f in files[:-self.keep]:
            try:
                os.remove(f)
            except OSError:
                pass

    def _list_files(self) -> list[str]:
        if not os.path.isdir(self.path):
            return []
        files = [os.path.join(self.path, f) for f in os.listdir(self.path)
                 if f.startswith("ckpt-") and f.endswith(".pkl")]
        return sorted(files, key=os.path.getmtime)

    def list(self) -> list[str]:
        self._wait()  # a half-written snapshot must not be resumed from
        return self._list_files()

    def latest(self) -> dict | None:
        """Reference ``getLatestFile`` (Topology.scala:1511-1528).

        Multi-host: the checkpoint dir must be SHARED storage (the
        reference's HDFS contract).  Process 0 is the only writer
        (:meth:`save`), so before reading, process 0 joins its in-flight
        writer and THEN all processes barrier — guaranteeing every host
        resumes from the same completed snapshot instead of racing the
        os.replace."""
        if _process_shard() is not None:
            self._wait()  # no-op on processes that never write
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("zoo-ckpt-latest")
        files = self.list()
        # prefer the crash-safe LATEST pointer (always names the newest
        # COMPLETE snapshot — a kill -9 mid-write never advanced it);
        # fall back to mtime order for pre-pointer checkpoint dirs
        fname = None
        try:
            with open(os.path.join(self.path, self.LATEST)) as f:
                name = f.read().strip()
            cand = os.path.join(self.path, name)
            if name and os.path.exists(cand):
                fname = cand
        except OSError:
            fname = None
        if fname is None:
            if not files:
                return None
            fname = files[-1]
        elif files and files[-1] != fname:
            # an out-of-band snapshot (dropped in by a restore workflow,
            # never written through save()) can be newer than the pointer
            # target; any file under its final ckpt-*.pkl name is complete
            # (fsync-before-rename), so trusting the newer one is safe
            try:
                if os.path.getmtime(files[-1]) > os.path.getmtime(fname):
                    fname = files[-1]
            except OSError:
                pass
        with open(fname, "rb") as f:
            payload = safe_load(f)
        # schema check: refuse snapshots from a NEWER format (their layout
        # is unknown); pre-versioning (r03) snapshots carry no meta and
        # load as version 0
        meta = payload.pop("__ckpt_meta__", {"format_version": 0})
        if meta.get("format_version", 0) > self.FORMAT_VERSION:
            raise ValueError(
                f"checkpoint {fname} has format_version "
                f"{meta['format_version']} > supported "
                f"{self.FORMAT_VERSION}; upgrade the framework to resume "
                "from it")
        return payload


class Estimator:
    """Train/evaluate a KerasNet-like model on a device mesh.

    Reference: Estimator.scala:65-183 (facade) driving
    InternalDistriOptimizer (Topology.scala:1076-1259).
    """

    def __init__(self, model, optimizer=None, loss=None, metrics=None,
                 model_dir: str | None = None, grad_clip=None,
                 tensorboard=None, checkpoint=None,
                 ctx: ZooContext | None = None, plan=None):
        self.model = model
        # Unified partitioner (parallel/plan.py): a ShardingPlan or a
        # canned-plan name; None defers to ZOO_SHARDING_PLAN / the
        # legacy ZOO_SHARD_OPTIMIZER flag, then plain data parallelism.
        # train(plan=) overrides per fit.
        self.plan = plan
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = list(metrics or [])
        self.grad_clip = grad_clip
        self.ctx = ctx or get_zoo_context()
        self._ckpt = None
        ckpt_path = None
        if checkpoint is not None:
            ckpt_path, over_write = checkpoint
            self._ckpt = _Checkpointer(ckpt_path, over_write)
        elif model_dir:
            self._ckpt = _Checkpointer(model_dir)
        self._writers = None
        if tensorboard is not None:
            log_dir, app_name = tensorboard
            from analytics_zoo_tpu.tensorboard import (
                TrainSummary,
                ValidationSummary,
            )
            self._writers = (
                TrainSummary(log_dir, app_name),
                ValidationSummary(log_dir, app_name),
            )
        # training state
        self.global_step = 0
        self.epoch = 1
        # compiled-step cache, keyed (device_transform, steps_per_dispatch)
        # — fit() and measure_pure_step() share it, so alternating probes
        # and training legs never thrash each other's jit cache
        self._train_step_fns: dict[tuple, Any] = {}
        self._eval_step_fn = None
        self._loss_buffer: list[tuple[int, Any]] = []
        self._opt_state = None  # persists across fit() calls
        self._profiled = False  # one jax.profiler capture per estimator
        # plan="auto" resolution cache: the oracle's choice is stable
        # for one estimator (same model/optimizer/mesh), so it is made
        # once; _auto_plan_record keeps the per-candidate prediction doc
        self._auto_plan = None
        self._auto_plan_record = None
        self.history: list[dict] = []
        # measure_pure_step probe bookkeeping: per-signature first-call
        # warmup time (compile included), so repeated probes report
        # steady state and the compile cost separately
        self._pure_step_warm: dict[tuple, float] = {}
        self.last_probe_warmup_seconds: float | None = None

    # ------------------------------------------------------------------
    # sharding plan (parallel/plan.py — ZOO_SHARDING_PLAN; the old
    # ZOO_SHARD_OPTIMIZER ZeRO-1 path is now the zero1() plan)
    # ------------------------------------------------------------------
    def _resolved_plan(self, override=None, params=None):
        """The effective ShardingPlan: explicit train(plan=) override >
        estimator plan > ZOO_SHARDING_PLAN > legacy ZOO_SHARD_OPTIMIZER
        (zero1) > data_parallel.

        ``"auto"`` (any of those tiers) is resolved HERE, not by
        ``resolve_plan``: the config oracle (analysis/oracle.py) picks
        among the canned plans from predicted per-chip param+opt bytes
        vs the peak table's HBM budget — see :meth:`_choose_auto_plan`.
        The choice is cached per estimator.

        The config tier's dtype policy (``ZOO_DTYPE_POLICY`` /
        ``ZooConfig.dtype_policy``) is overlaid on the result — the
        precision plane rides whatever sharding plan was picked, unless
        the plan already carries explicit ``dtype_rules`` (explicit
        beats environment, the documented precedence)."""
        from analytics_zoo_tpu.parallel.plan import resolve_plan

        requested = override if override is not None else self.plan
        if requested is None:
            requested = getattr(self.ctx.config, "sharding_plan", None)
        if isinstance(requested, str) \
                and requested.strip().lower() == "auto":
            if self._auto_plan is None:
                if params is None:
                    params, _ = self.model.build_params()
                self._auto_plan = self._choose_auto_plan(params)
            return self._apply_kernel_policy(
                self._apply_dtype_policy(self._auto_plan))
        return self._apply_kernel_policy(self._apply_dtype_policy(
            resolve_plan(
                override if override is not None else self.plan,
                self.ctx.config)))

    def _apply_dtype_policy(self, plan):
        """Overlay ``ZooConfig.dtype_policy`` (env ZOO_DTYPE_POLICY)
        onto a resolved plan.  No-ops when no policy is configured,
        when the plan already carries dtype_rules (explicit > env), or
        for policy "auto" — that one is resolved by the oracle's dtype
        sweep inside :meth:`_choose_auto_plan` (it needs the candidate
        predictions, not a blanket overlay)."""
        policy = getattr(self.ctx.config, "dtype_policy", None)
        if not policy or plan.dtype_rules:
            return plan
        if str(policy).strip().lower() == "auto":
            return plan
        from analytics_zoo_tpu.parallel.plan import with_dtype_policy

        return with_dtype_policy(plan, policy)

    def _apply_kernel_policy(self, plan):
        """Overlay the default kernel table (env ZOO_USE_PALLAS /
        ``ZooConfig.use_pallas``) onto a resolved plan — the kernel
        plane's env tier, same precedence contract as
        :meth:`_apply_dtype_policy`: no-op when the knob is off or the
        plan already carries kernel_rules (explicit > env)."""
        if not getattr(self.ctx.config, "use_pallas", False) \
                or plan.kernel_rules:
            return plan
        from analytics_zoo_tpu.parallel.plan import with_kernels

        return with_kernels(plan)

    def _choose_auto_plan(self, params):
        """Ask the config oracle to pick the memory plan: predicted
        per-chip bytes per (plan × remat) candidate (params measured
        from the built tree, optimizer state sized via
        ``jax.eval_shape`` — no allocation; activations estimated as
        one param-tree copy, the usual MLP-ish order of magnitude)
        against the HBM budget, preferring the least-collective-traffic
        least-rematted config that fits.  The full per-candidate
        prediction doc lands in ``_auto_plan_record`` (and the plan
        record / bench artifacts)."""
        from analytics_zoo_tpu.analysis.oracle import ConfigOracle
        from analytics_zoo_tpu.parallel.plan import (
            resolve_plan,
            with_dtype,
            with_remat,
        )

        def tree_bytes(tree):
            total = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                shape = getattr(leaf, "shape", None)
                dtype = getattr(leaf, "dtype", None)
                if shape is None or dtype is None:
                    continue
                total += int(np.prod(shape)) * np.dtype(dtype).itemsize
            return total

        param_bytes = tree_bytes(params)
        opt_bytes = tree_bytes(jax.eval_shape(self.optimizer.init, params))
        oracle = ConfigOracle.from_env()
        # ZOO_DTYPE_POLICY=auto widens the sweep to sharding × remat ×
        # dtype: bf16 candidates get the doubled flops ceiling, the
        # halved activation footprint and the shrunken fsdp gather
        # bytes (analysis/costmodel.py DTYPE_PEAK_FACTORS); f32 stays
        # the tie-break default.
        policy = getattr(self.ctx.config, "dtype_policy", None)
        dtype_options = ((None, "bf16")
                         if policy
                         and str(policy).strip().lower() == "auto"
                         else (None,))
        # ZOO_USE_PALLAS=1 widens the sweep with the kernel dimension:
        # "+kernels" candidates get the fused-kernel compute factor on
        # TPU peaks and tie-break AGAINST kernels everywhere else, so
        # the CPU tier's auto plan declines pallas while recording the
        # declined candidate in the prediction log.
        kernel_options = ((None, "kernels")
                          if getattr(self.ctx.config, "use_pallas", False)
                          else (None,))
        name, doc = oracle.choose_plan(
            param_bytes, opt_bytes, self.ctx.data_parallel_size,
            activation_bytes=param_bytes,
            remat_options=(None, "full"),
            dtype_options=dtype_options,
            kernel_options=kernel_options)
        self._auto_plan_record = doc
        logger.info(
            "plan=auto resolved to %r (remat=%s dtype=%s kernels=%s; "
            "per-chip %s bytes vs %s budget, %s-way)", name,
            doc["chosen_remat"], doc.get("chosen_dtype"),
            doc.get("chosen_kernels"),
            next(c["predicted_chip_bytes"] for c in doc["candidates"]
                 if c["config"] == doc["chosen_config"]),
            doc["hbm_budget_bytes"], doc["n_shards"])
        plan = resolve_plan(name)
        if doc["chosen_remat"]:
            plan = with_remat(plan, doc["chosen_remat"])
        if doc.get("chosen_dtype"):
            plan = with_dtype(plan, doc["chosen_dtype"])
        if doc.get("chosen_kernels"):
            from analytics_zoo_tpu.parallel.plan import with_kernels

            plan = with_kernels(plan)
        return plan

    def _place_opt_state(self, opt_state, plan=None):
        """Optimizer-state placement through the partitioner — the one
        resharding path (a checkpoint's global logical arrays land in
        the CURRENT plan/mesh layout by this device_put, whatever shape
        they were saved under)."""
        plan = plan if plan is not None else self._resolved_plan()
        return plan.place_opt_state(opt_state, self.ctx.mesh)

    def _place_params(self, params, plan=None):
        plan = plan if plan is not None else self._resolved_plan()
        return plan.place_params(params, self.ctx.mesh)

    def _publish_mem_gauges(self, plan, params, opt_state):
        """zoo_mem_* per plan label: measured per-chip param+opt bytes
        of the state just placed, against the cost model's
        ``predict_chip_bytes`` for this plan/mesh."""
        from analytics_zoo_tpu.analysis.costmodel import predict_chip_bytes
        from analytics_zoo_tpu.parallel.plan import (
            per_chip_bytes,
            record_dtype_gauges,
            record_kernel_gauges,
            record_mem_gauges,
        )

        try:
            global_bytes = [
                sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                    for l in jax.tree_util.tree_leaves(t)
                    if hasattr(l, "shape"))
                for t in (params, opt_state)]
            predicted = predict_chip_bytes(
                global_bytes[0], global_bytes[1], plan.name,
                self.ctx.data_parallel_size)
            measured = per_chip_bytes((params, opt_state))
            tag = "" if plan.name == "dp" else f"_{plan.name}"
            record_mem_gauges(f"train_step{tag}",
                              predicted_bytes=predicted,
                              measured_bytes=measured)
            if plan.dtype_rules:
                # Precision plane: per-role leaf counts and the
                # compute-vs-master byte ratio (zoo_dtype_* family)
                record_dtype_gauges(f"train_step{tag}", plan, params)
            if plan.kernel_rules:
                # Kernel plane: per-scope kernel selections and the
                # pallas/fallback routing counters (zoo_kernel_* family)
                record_kernel_gauges(f"train_step{tag}", plan)
        except Exception as e:  # telemetry must never fail a fit
            logger.debug("zoo_mem gauges skipped: %s", e)

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------
    def _train_step_for(self, device_transform=None,
                        steps_per_dispatch: int = 1, plan=None):
        """The (cached) compiled train step for this transform/K/plan
        triple.

        Returning the SAME function object across calls is what makes
        the compiled-step cache effective: a fresh closure per call
        would retrace and recompile an identical program.  Bounded:
        callers that build a fresh transform closure per fit() would
        otherwise pin one compiled program per call forever — oldest
        entries are evicted past 8 (in-flight fns stay alive through the
        caller's local reference)."""
        plan = plan if plan is not None else self._resolved_plan()
        key = (device_transform, int(steps_per_dispatch),
               plan.cache_key())
        fn = self._train_step_fns.get(key)
        if fn is None:
            fn = self._build_train_step(device_transform,
                                        steps_per_dispatch=key[1],
                                        plan=plan)
            while len(self._train_step_fns) >= 8:
                old = next(iter(self._train_step_fns))
                self._train_step_fns.pop(old)
                if old[1] == 1:
                    # the probe's warmth bookkeeping rode on this entry:
                    # a future measure_pure_step re-pays compile, so it
                    # must re-report warmup instead of claiming 0.0
                    self._pure_step_warm = {
                        s: v for s, v in self._pure_step_warm.items()
                        if s[0] is not old[0]}
            self._train_step_fns[key] = fn
        return fn

    def _build_train_step(self, device_transform=None,
                          steps_per_dispatch: int = 1, plan=None):
        """Build the compiled train step — through ``compile_step``,
        the unified partitioner's choke point (parallel/plan.py), so
        every plan's program shares the persistent compile cache, AOT
        warmup, ``zoo_compile_seconds`` and the HLO lint/feature pipe.

        ``steps_per_dispatch=1``: the classic single-step program.
        ``steps_per_dispatch=K>1``: the FUSED program — one donated-carry
        dispatch whose body is ``jax.lax.scan`` over K inner steps of the
        SAME per-step math (shared ``one_step`` closure), consuming a
        [K, batch, ...] super-batch.  Each inner step folds the RNG on
        the GLOBAL step index (``step0 + i``), so the loss trajectory is
        bit-identical to K single dispatches; only the Python→device
        round-trip count changes (1 instead of K).

        The plan's sharding enters twice: inputs are device_put into the
        plan layout by the caller, and the updated params/opt state are
        re-constrained in-graph so donation reuses the sharded buffers
        (an fsdp plan's weights must come back sharded, not
        'helpfully' replicated by XLA).  The math is placement-invariant
        — every plan trains bit-identically.
        """
        from analytics_zoo_tpu.parallel.plan import compile_step

        plan = plan if plan is not None else self._resolved_plan()
        mesh = self.ctx.mesh
        model, loss_fn = self.model, self.loss
        opt, grad_clip = self.optimizer, self.grad_clip
        # Kernel plane: a plan routing optimizer.adam to the fused
        # pallas kernel swaps the transform here — fused_adam's inner
        # chain is built from the SAME optax.adam arguments, so init()
        # state structure, checkpoints and the fallback trajectory are
        # identical; only the TPU lowering changes.  "xla" (or no rule)
        # leaves the original optimizer untouched.
        if plan.kernel_rules \
                and getattr(opt, "name", None) == "adam" \
                and hasattr(opt, "hyperparams") \
                and plan.kernel_for("optimizer.adam") == "fused_adam":
            from analytics_zoo_tpu.ops.pallas.fused_adam import fused_adam

            opt = fused_adam(**opt.hyperparams)
        compute_dtype = self.ctx.compute_dtype
        # Transfer learning (KerasNet.freeze/freeze_up_to): frozen layers'
        # grads AND optimizer updates are masked to zero — updates too, so
        # decoupled weight decay (adamw) cannot drift frozen weights.
        frozen = frozenset(getattr(model, "_frozen", ()) or ())

        def _mask_frozen(tree):
            return {
                k: (jax.tree_util.tree_map(jnp.zeros_like, v)
                    if k in frozen else v)
                for k, v in tree.items()
            }

        def one_step(params, opt_state, state, rng, batch):
            if device_transform is not None:
                # On-device preprocessing (uint8 decode/normalize/augment):
                # fuses into the step, so the host link ships compact dtypes.
                batch = device_transform(batch)

            def loss_of(p):
                # fsdp gather prefetch (plan.prefetch): explicit
                # double-buffered all-gathers, bucket k+1's gather
                # barrier-chained behind bucket k so it issues while k
                # computes; the vjp transposes each gather into the
                # matching bucketed reduce-scatter.  No-op (returns p
                # untouched) for plans without prefetch.
                p = plan.prefetch_params(p, mesh)
                # Params-in-compute mixed precision: master params stay f32
                # (the differentiation variable); the cast is inside the
                # graph so its vjp returns f32 grads.  Loss math is f32.
                # A plan with dtype_rules (the precision plane —
                # mixed_precision()) takes precedence over the context-
                # wide compute dtype: per-leaf roles, same in-graph cast.
                if plan.dtype_rules:
                    pc = plan.cast_params_for_compute(p)
                    xc = cast_floats(batch["x"],
                                     plan.compute_cast_dtype()
                                     or compute_dtype)
                else:
                    pc = cast_floats(p, compute_dtype)
                    xc = cast_floats(batch["x"], compute_dtype)
                preds, new_state = model.forward(
                    pc, xc, state=state, training=True, rng=rng
                )
                preds = cast_floats(preds, jnp.float32)
                l = loss_fn.mean(batch.get("y"), preds, batch.get("w"))
                # Auxiliary losses reported through the layer-state channel
                # (MoE load balancing: each stack stores its pre-weighted
                # contribution under `moe_aux_cost`) join the training
                # loss; eval loss stays the task loss alone.
                l = l + _collect_aux_cost(new_state)
                return l, new_state

            (l, new_state), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(params)
            if compute_dtype is not None or plan.dtype_rules:
                # Keep state dtypes stable across steps (donation and the
                # next trace both require it).
                new_state = jax.tree_util.tree_map(
                    lambda new, old: new.astype(old.dtype), new_state, state
                )
            # With the batch sharded over the `data` axis and params
            # replicated, XLA partitions this program SPMD and inserts the
            # gradient all-reduce (reduce-scatter + all-gather over ICI) —
            # the role of BigDL's AllReduceParameter (Topology.scala:1119).
            if frozen:
                grads = _mask_frozen(grads)
            grads = _clip_grads(grads, grad_clip)
            # ZeRO-2/3: grad_rules pin each gradient to per-chip shards,
            # so XLA lowers the gradient sum as a reduce-scatter and the
            # optimizer update below runs on 1/n of every leaf; plans
            # without grad_rules (dp/zero1/fsdp) leave this to GSPMD.
            grads = plan.constrain_grads(grads, mesh)
            updates, opt_state = opt.update(grads, opt_state, params)
            # Plan layout, in-graph: pinning the optimizer state (zero1/
            # fsdp) makes XLA partition the moment updates — and
            # reduce-scatter the grads feeding them — instead of
            # computing the full update redundantly on every chip;
            # pinning the params (fsdp/tp) keeps the weights stored
            # sharded (gather-on-use) so donation reuses the 1/n
            # buffers.  data_parallel constrains nothing (no-ops).
            opt_state = plan.constrain_opt(opt_state, mesh)
            if frozen:
                updates = _mask_frozen(updates)
            params = optax.apply_updates(params, updates)
            params = plan.constrain_params(params, mesh)
            return params, opt_state, new_state, l

        # per-plan compile labels (dp keeps the historical bare names):
        # zoo_compile_seconds / zoo_hlo_* tell an fsdp program from a dp
        # one at a glance
        tag = "" if plan.name == "dp" else f"_{plan.name}"
        if steps_per_dispatch <= 1:
            def train_step(params, opt_state, state, seed, step, batch):
                # RNG derived in-graph: no per-step host-side key
                # splitting.
                rng = jax.random.fold_in(jax.random.PRNGKey(seed), step)
                return one_step(params, opt_state, state, rng, batch)

            return compile_step(train_step, plan, mesh,
                                donate_argnums=(0, 1, 2),
                                label=f"train_step{tag}",
                                meta={"mesh_shape": dict(mesh.shape),
                                      "steps_per_dispatch": 1})

        k = int(steps_per_dispatch)

        def train_step_scan(params, opt_state, state, seed, step0,
                            stacked):
            key = jax.random.PRNGKey(seed)

            def body(carry, xs):
                p, o, s = carry
                batch_i, i = xs
                # GLOBAL step index: inner step i of this dispatch is
                # global step step0 + i, so the per-step RNG (dropout,
                # augmentation) matches the K=1 run exactly.
                rng = jax.random.fold_in(key, step0 + i)
                p, o, s, l = one_step(p, o, s, rng, batch_i)
                return (p, o, s), l

            (params, opt_state, state), losses = jax.lax.scan(
                body, (params, opt_state, state),
                (stacked, jnp.arange(k, dtype=jnp.int32)))
            return params, opt_state, state, losses

        return compile_step(train_step_scan, plan, mesh,
                            donate_argnums=(0, 1, 2),
                            label=f"train_step_scan{k}{tag}",
                            meta={"mesh_shape": dict(mesh.shape),
                                  "steps_per_dispatch": k})

    def _build_eval_step(self, device_transform=None):
        from analytics_zoo_tpu.parallel.plan import compile_step

        model, loss_fn, metrics = self.model, self.loss, self.metrics
        compute_dtype = self.ctx.compute_dtype
        plan = self._resolved_plan()

        def eval_step(params, state, batch):
            if device_transform is not None:
                batch = device_transform(batch)
            # State stays f32: BN running stats must not be rounded to bf16
            # (the layers upcast internally where needed).  The precision
            # plane casts per dtype role, same as the train step — eval
            # must see the dtypes it trained with.
            if plan.dtype_rules:
                pc = plan.cast_params_for_compute(params)
                xc = cast_floats(batch["x"],
                                 plan.compute_cast_dtype() or compute_dtype)
            else:
                pc = cast_floats(params, compute_dtype)
                xc = cast_floats(batch["x"], compute_dtype)
            preds, _ = model.forward(
                pc, xc, state=state, training=False)
            preds = cast_floats(preds, jnp.float32)
            n_valid = batch.get("n_valid")
            mask = None
            if n_valid is not None:
                b = preds.shape[0] if not isinstance(preds, list) \
                    else preds[0].shape[0]
                mask = (jnp.arange(b) < n_valid).astype(jnp.float32)
            stats = []
            if loss_fn is not None and "y" in batch:
                per = loss_fn(batch["y"], preds)
                if mask is not None:
                    stats.append((jnp.sum(per * mask), jnp.sum(mask)))
                else:
                    stats.append((jnp.sum(per),
                                  jnp.asarray(per.shape[0], jnp.float32)))
            for m in metrics:
                stats.append(m.batch_stats(batch["y"], preds, mask=mask))
            return stats

        # through the choke point too: eval programs get the same
        # compile metering / persistent cache / HLO features as train
        return compile_step(eval_step, plan,
                            self.ctx.mesh, label="eval_step")

    # ------------------------------------------------------------------
    # train (InternalDistriOptimizer.train, Topology.scala:1076-1259)
    # ------------------------------------------------------------------
    def train(self, train_set: FeatureSet, batch_size: int = 32,
              nb_epoch: int | None = None,
              end_trigger: ZooTrigger | None = None,
              checkpoint_trigger: ZooTrigger | None = None,
              validation_set: FeatureSet | None = None,
              validation_trigger: ZooTrigger | None = None,
              seed: int | None = None,
              autotune=None, plan=None, elastic=None):
        """``plan``: a :class:`~analytics_zoo_tpu.parallel.plan.
        ShardingPlan` (or canned-plan name — "dp"/"zero1"/"zero2"/
        "fsdp"/"zero3") laying out params, optimizer state, grads and
        the batch for this fit; ``None`` defers to the estimator's
        plan, then ``ZOO_SHARDING_PLAN`` / the legacy
        ``ZOO_SHARD_OPTIMIZER``, then data parallelism.  A plan changes
        where bytes live (fsdp/zero3: ~1/n param+opt bytes per chip;
        zero2 reduce-scatters grads at zero1's resident state) and
        which collectives XLA inserts, never the math: fsdp/zero3 train
        BIT-identically to dp; zero1/zero2's differently-grouped
        gradient reduction matches to float tolerance (ulp-level —
        BENCH_PARTITION_r10.json / BENCH_MEMORY_r12.json record the
        max |Δ|).  ``"auto"`` asks the config oracle to sweep the
        (plan × remat) space against the HBM budget.  See
        docs/parallelism.md.

        ``autotune``: ``True`` (or ``ZOO_AUTOTUNE=1`` via the config
        tier, which ``None`` defers to) turns on the closed-loop tuner
        (feature/autotune.py): the train set is wrapped in the prefetch
        plane (starting from the configured knobs, or worst-case
        workers=1/depth=1 when prefetch is off) and a controller thread
        resizes it online while ``steps_per_dispatch`` hill-climbs at
        dispatch boundaries — loss trajectory bit-identical throughout.
        Pass an :class:`~analytics_zoo_tpu.feature.autotune.
        AutotuneController` instance to share/tune one across fits;
        ``False`` forces it off regardless of the env.

        ``elastic``: an :class:`~analytics_zoo_tpu.elastic.membership.
        ElasticSession` — the fit becomes one elastic training LEG: at
        every dispatch boundary the session's membership generation is
        polled, and on a change the loop snapshots through the async
        checkpointer (iterator position included), flushes, and raises
        :class:`~analytics_zoo_tpu.elastic.membership.
        GenerationChange` carrying the new (generation, world, members)
        doc — the caller (the elastic worker round loop) rejoins at the
        new world size and resumes from LATEST through the
        partitioner's bit-exact resharding.  ``None`` (default) trains
        exactly as before.  See docs/elastic-training.md."""
        ctx = self.ctx
        dp = ctx.data_parallel_size
        if batch_size % dp != 0:
            # The TFDataset contract (tf_dataset.py:136-143): global batch
            # must divide evenly across model replicas.
            raise ValueError(
                f"batch_size ({batch_size}) must be a multiple of the "
                f"data-parallel size ({dp})"
            )
        if end_trigger is None:
            # Keras semantics: each fit() call trains nb_epoch MORE epochs
            # (relative to the in-process counter).  Checkpoint resume in a
            # fresh process still continues to the absolute target, matching
            # the reference's getFinishedEpoch continuation
            # (Topology.scala:373-386).
            end_trigger = MaxEpoch(
                self.epoch - 1 + (nb_epoch if nb_epoch is not None else 10))
        if checkpoint_trigger is None and self._ckpt is not None:
            checkpoint_trigger = EveryEpoch()
        if validation_set is not None and validation_trigger is None:
            validation_trigger = EveryEpoch()
        seed = ctx.seed if seed is None else seed
        # Closed-loop autotuning (ZOO_AUTOTUNE / autotune=True): resolve
        # the controller BEFORE the prefetch wrap so the pipeline starts
        # at (and is resized from) the controller's state.  autotune
        # unset/off ⇒ controller is None and every path below is the
        # static-knob code, no new threads (the disabled-mode contract).
        controller, own_controller, attached_set = None, False, None
        auto = autotune if autotune is not None else ctx.config.autotune
        if auto:
            from analytics_zoo_tpu.feature.autotune import (
                AutotuneController,
            )
            if isinstance(auto, AutotuneController):
                controller = auto
            else:
                controller = AutotuneController.from_config(ctx.config)
                own_controller = True
        if ctx.config.prefetch_workers or controller is not None:
            # Parallel host data plane (ZOO_PREFETCH_WORKERS): shard
            # loading, host transforms and batch assembly move onto pool
            # threads with ordered delivery, composing with the
            # double-buffered device infeed below — the feeder consumes
            # the prefetched stream instead of the serial generator, and
            # the stream itself is byte-identical (resume included).
            # Under autotune with prefetch off, start from the worst
            # case (workers=1, depth=1) and let the controller grow it —
            # but only when the set HAS host work to hide
            # (worth_prefetching); a resident no-transform array set
            # would pay queue handoffs for nothing, and an explicit
            # ZOO_PREFETCH_WORKERS always wins over that heuristic.
            from analytics_zoo_tpu.feature.prefetch import (
                PrefetchFeatureSet,
                worth_prefetching,
            )
            if isinstance(train_set, PrefetchFeatureSet):
                if controller is not None \
                        and train_set._controller is None:
                    # attach for THIS fit only — detached in the finally
                    # below, so a later train(autotune=False) on the same
                    # FeatureSet cannot resurrect this fit's controller
                    train_set._controller = controller
                    attached_set = train_set
            elif ctx.config.prefetch_workers or \
                    worth_prefetching(train_set):
                train_set = PrefetchFeatureSet(
                    train_set,
                    depth=(ctx.config.prefetch_depth
                           if ctx.config.prefetch_workers else 1),
                    workers=ctx.config.prefetch_workers or 1,
                    controller=controller)

        # Unified partitioner: resolve the plan ONCE per fit; placement,
        # in-graph constraints, the batch sharding and the checkpoint's
        # spec record all derive from it.  Params are built FIRST: a
        # plan="auto" resolution needs their byte sizes to predict each
        # candidate's per-chip footprint.
        params, state = self.model.build_params()
        plan = self._resolved_plan(plan, params=params)
        # Keras continuation semantics: a second fit() on the same estimator
        # keeps optimizer moments and the LR-schedule step count (they live
        # in opt_state), not just the weights.
        opt_state = (self._opt_state if self._opt_state is not None
                     else self.optimizer.init(params))
        repl = ctx.replicated()
        state = jax.device_put(state, repl)
        params = self._place_params(params, plan)
        opt_state = self._place_opt_state(opt_state, plan)
        # Close the MEMORY loop (zoo_mem_* family): measured per-chip
        # param+opt bytes under this plan vs predict_chip_bytes, the
        # way zoo_oracle rel_error closes steps/sec predictions.
        self._publish_mem_gauges(plan, params, opt_state)
        # Checkpoint spec record: the plan's clamped spec trees ride
        # every snapshot, so a resume (any mesh size, any process) can
        # see what layout the state was trained under and reshard
        # through the partitioner — not a strategy-specific heuristic.
        from analytics_zoo_tpu.parallel.plan import serialize_specs
        # report_unused: the once-per-fit audit point — a typo'd rule
        # that matched zero params surfaces as ONE warning here
        param_specs, _ = plan.param_specs(params, ctx.mesh,
                                          report_unused=True)
        self._plan_record = {
            "name": plan.name,
            "mesh": dict(ctx.mesh.shape),
            "param_specs": serialize_specs(param_specs),
            "opt_specs": serialize_specs(
                plan.opt_specs(opt_state, ctx.mesh)),
            # precision contract ("" = no dtype rules): a resume under a
            # DIFFERENT policy fails loudly below instead of silently
            # mixing master widths
            "dtype_policy": plan.dtype_policy_str(),
        }
        if self._auto_plan_record is not None:
            # plan="auto": keep the oracle's per-candidate predictions
            # next to the layout the fit actually ran under
            self._plan_record["auto"] = self._auto_plan_record
        dev_tf = getattr(train_set, "device_transform", None)
        # Fused multi-step dispatch (ZOO_STEPS_PER_DISPATCH): K>1 runs K
        # inner steps per jitted dispatch; the K=1 step is always built
        # too — it serves partial tail chunks.  (K >= 1 is enforced by
        # ZooConfig.__post_init__ — no silent clamping here.)
        k = int(ctx.config.steps_per_dispatch or 1)
        step_fn = self._train_step_for(dev_tf, 1, plan)
        fused_fn = self._train_step_for(dev_tf, k, plan) if k > 1 else None
        if controller is not None:
            # name the K=1 program for the controller's oracle prior:
            # its compile (first dispatch) caches the HLO features the
            # predicted-K jump reads
            tag = "" if plan.name == "dp" else f"_{plan.name}"
            controller.set_feature_label(f"train_step{tag}")
        # Persistent compile plane (ZOO_COMPILE_CACHE): enable before the
        # first trace so this fit's compiles populate / hit the cache.
        from analytics_zoo_tpu.common.compile_cache import (
            maybe_enable_persistent_cache,
        )
        maybe_enable_persistent_cache(ctx.config.compile_cache)

        start_epoch, start_batch = self.epoch, 0
        # resume from checkpoint if present (Topology.scala:1220-1242)
        resumed = self._ckpt.latest() if self._ckpt else None
        if resumed is not None:
            # Elastic resume through the partitioner: the checkpoint
            # stores GLOBAL logical arrays, so resharding onto THIS
            # mesh/plan (saved {data:8}, resuming {data:4}; saved fsdp,
            # resuming dp; ...) is exactly the plan's placement
            # device_put — no layout surgery.
            saved_plan = resumed.get("plan")
            saved_policy = (saved_plan or {}).get("dtype_policy")
            if saved_policy is not None \
                    and saved_policy != plan.dtype_policy_str():
                # Precision contract guard: f32 masters saved under one
                # policy must not be silently re-interpreted under
                # another (pre-precision-plane checkpoints carry no
                # policy key and skip the check).  ZOO_DTYPE_RESUME=cast
                # opts into a DELIBERATE cast-on-resume.
                if os.environ.get("ZOO_DTYPE_RESUME", "").strip().lower() \
                        in ("cast", "force"):
                    logger.warning(
                        "resuming checkpoint trained under dtype policy "
                        "%r into plan %r with policy %r "
                        "(ZOO_DTYPE_RESUME): casting on resume",
                        saved_policy, plan.name, plan.dtype_policy_str())
                else:
                    raise ValueError(
                        f"checkpoint was trained under dtype policy "
                        f"{saved_policy!r} but this fit's plan "
                        f"{plan.name!r} declares "
                        f"{plan.dtype_policy_str()!r}; resume with a "
                        f"matching plan (mixed_precision(), "
                        f"ZOO_DTYPE_POLICY) or set ZOO_DTYPE_RESUME=cast "
                        f"to cast deliberately")
            if saved_plan and (saved_plan.get("name") != plan.name
                               or saved_plan.get("mesh")
                               != dict(ctx.mesh.shape)):
                logger.info(
                    "resharding checkpoint (saved plan=%s mesh=%s) into "
                    "plan=%s mesh=%s through the partitioner",
                    saved_plan.get("name"), saved_plan.get("mesh"),
                    plan.name, dict(ctx.mesh.shape))
            params = self._place_params(resumed["params"], plan)
            opt_state = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(opt_state),
                [jnp.asarray(x) for x in resumed["opt_flat"]],
            )
            opt_state = self._place_opt_state(opt_state, plan)
            state = jax.device_put(resumed["state"], repl)
            self.global_step = int(resumed["global_step"])
            start_epoch = int(resumed["epoch"])
            start_batch = int(resumed["next_batch"])
            seed = int(resumed["seed"])
            logger.info("resumed from checkpoint @ step %d (epoch %d.%d)",
                        self.global_step, start_epoch, start_batch)

        # ZooConfig env tier: ZOO_FAILURE_RETRY_TIMES (reference
        # ``bigdl.failure.retryTimes`` sysprop, Topology.scala:1172)
        retry_times = self.ctx.config.failure_retry_times
        try:
            params, opt_state, state = self._train_with_retries(
                params, opt_state, state, step_fn, fused_fn, k, dev_tf,
                plan, controller, train_set, batch_size, seed,
                start_epoch, start_batch, end_trigger, checkpoint_trigger,
                validation_set, validation_trigger, retry_times, repl,
                elastic)
        finally:
            if attached_set is not None:
                # undo the fit-scoped attachment on the CALLER's set
                attached_set._controller = None
            if own_controller:
                # the controller thread dies with this fit; a caller-
                # provided controller keeps running (shared across fits)
                controller.stop()

        self.model.params = params
        self.model.state = state
        self._opt_state = opt_state
        if self._ckpt is not None:
            # Flush the in-flight async save before returning: the process
            # may exit right after fit(), and a NEW estimator on the same
            # dir must see the final snapshot (not a half-written .tmp).
            # Also surfaces any deferred write error.
            self._ckpt._wait()
        return self

    def _train_with_retries(self, params, opt_state, state, step_fn,
                            fused_fn, k, dev_tf, plan, controller,
                            train_set, batch_size, seed, start_epoch,
                            start_batch, end_trigger, checkpoint_trigger,
                            validation_set, validation_trigger,
                            retry_times, repl, elastic=None):
        # GenerationChange is control flow, not a failure: it must reach
        # the elastic worker's round loop, never the retry path below.
        from analytics_zoo_tpu.elastic.membership import GenerationChange

        retries = 0
        while True:
            try:
                params, opt_state, state = self._train_loop(
                    params, opt_state, state, step_fn, fused_fn, k,
                    dev_tf, plan, controller,
                    train_set, batch_size, seed, start_epoch, start_batch,
                    end_trigger, checkpoint_trigger,
                    validation_set, validation_trigger, elastic,
                )
                break
            except (KeyboardInterrupt, ValueError, TypeError,
                    GenerationChange):
                raise
            except Exception as e:
                # retry-from-checkpoint loop (Topology.scala:1171-1253)
                # — recorded in the flight ring BEFORE the retry, so a
                # postmortem shows every attempt's failure, not just the
                # one that finally escaped
                get_flight_recorder().record_exception(e, where="train")
                retries += 1
                if self._ckpt is None or retries > retry_times:
                    raise
                # Drop device scalars produced by the failed attempt: their
                # conversion would re-raise the device error, and their steps
                # will be replayed from the checkpoint anyway.
                self._loss_buffer = []
                logger.exception(
                    "training failed; retry %d/%d from latest checkpoint",
                    retries, retry_times,
                )
                resumed = self._ckpt.latest()
                if resumed is None:
                    raise
                params = self._place_params(resumed["params"], plan)
                # same plan placement as the initial/resume sites:
                # restoring replicated here would retrigger the OOM the
                # zero1/fsdp layout exists to prevent, mid-retry
                opt_state = self._place_opt_state(
                    jax.tree_util.tree_unflatten(
                        jax.tree_util.tree_structure(opt_state),
                        [jnp.asarray(x) for x in resumed["opt_flat"]],
                    ), plan)
                state = jax.device_put(resumed["state"], repl)
                self.global_step = int(resumed["global_step"])
                start_epoch = int(resumed["epoch"])
                start_batch = int(resumed["next_batch"])
        return params, opt_state, state

    # zoolint: hot-path
    def _train_loop(self, params, opt_state, state, step_fn, fused_fn,
                    steps_per_dispatch, dev_tf, plan, controller,
                    train_set, batch_size, seed, start_epoch, start_batch,
                    end_trigger, checkpoint_trigger, validation_set,
                    validation_trigger, elastic=None):
        ctx = self.ctx
        cfg = ctx.config
        k = steps_per_dispatch
        tstate = TrainingState(epoch=start_epoch,
                               iteration=self.global_step)
        epoch = start_epoch
        # zoolint: disable=host-sync -- host int boxing once per fit, not a device fetch
        seed_arr = np.asarray(seed & 0x7FFFFFFF, np.int32)
        # Profiler knob (ZOO_PROFILE_DIR / ZooConfig.profile_dir): one
        # jax.profiler trace of profile_steps warm steps per fit() — armed
        # ONCE per fit (not per epoch) so it fires even when epochs have
        # fewer steps than the warmup offset.
        prof_dir = cfg.profile_dir
        prof_at = self.global_step + 3 if (
            prof_dir and not self._profiled) else None
        # Observability (metrics/): children resolved once here, so the
        # per-step cost is a handful of observe/inc calls — and on a
        # disabled registry those are the shared no-op singleton.
        step_metrics = StepMetrics()
        # Distributed telemetry plane (ISSUE 2): scrape endpoints opt in
        # via ZOO_METRICS_PORT; the flight recorder arms its crash dump
        # (ZOO_FLIGHT_DIR); the loop and the infeed feeder heartbeat
        # /healthz; steps beyond k x rolling-p50 are flagged stragglers.
        maybe_start_from_env()
        flight = get_flight_recorder().install()
        straggler = StragglerDetector()
        health = get_health()
        # The loop only beats once per COMPLETED step, and the first
        # step includes the XLA compile (routinely minutes on a big
        # model) — the silence budget must cover that, or /healthz
        # would 503 a healthy process through every warmup.
        health.register("train_loop", stale_after=600.0)
        while not end_trigger(tstate):
            epoch_t0 = time.perf_counter()
            n_records = 0
            batch_iter = train_set.batches(
                batch_size, shuffle=True, seed=seed, epoch=epoch,
                drop_last=True, start_batch=start_batch,
                process_shard=_process_shard(),
            )
            loss_dev = None
            bi = start_batch
            # 60s budget: the feeder beats per batch AND while blocked
            # on a full queue, so only a truly stalled input pipeline
            # (the tf.data failure mode) exceeds it.  The feeder THREAD
            # unregisters the component when it exits (on_exit), so the
            # main thread never races a late beat.
            health.register("infeed", stale_after=60.0)
            # batch placement comes from the PLAN (its batch_axes — the
            # data axis for every canned plan; ("dcn", "data") under a
            # hybrid-mesh plan), not a hard-wired DATA_AXIS
            baxes = plan.batch_axes
            shard_single = partial(ctx.shard_batch, axes=baxes)
            chunked = k > 1 or controller is not None
            if chunked:
                # Fused dispatch: the feeder consumes the CHUNKED stream.
                # Full chunks are stacked into a [K, batch, ...]
                # super-batch ON THE FEEDER THREAD (host work overlapping
                # device compute, like every other shard_fn cost) and
                # sharded with axis 1 on the data axis, so each inner
                # scan step sees the same per-chip shards as K=1.
                def shard_item(item, _stack=partial(
                        ctx.shard_batch_stacked, axes=baxes),
                               _single=shard_single):
                    kind, payload = item
                    if kind == "scan":
                        stacked = jax.tree_util.tree_map(
                            lambda *xs: np.stack(xs), *payload)
                        return ("scan", _stack(stacked), len(payload))
                    return ("single", _single(payload), 1)

                # Autotune: chunk sizes follow the controller's K
                # hill-climb, re-read at every chunk boundary; the batch
                # sequence (and so the trajectory) is unchanged.
                feed_src = (_chunk_batches_dynamic(
                    batch_iter, controller.current_k)
                    if controller is not None
                    else _chunk_batches(batch_iter, k))
                shard_fn = shard_item
            else:
                feed_src, shard_fn = batch_iter, shard_single
            feeder = _DeviceFeeder(
                feed_src, shard_fn, depth=cfg.infeed_depth,
                heartbeat=lambda: health.heartbeat("infeed"),
                on_exit=lambda: health.unregister("infeed"))
            prof_active = False
            try:
                feeder_iter = iter(feeder)
                while True:
                    t_iter0 = time.perf_counter()
                    with time_it("zoo.infeed"):
                        sharded = next(feeder_iter, _SENTINEL)
                    t_data = time.perf_counter()
                    if sharded is _SENTINEL:
                        break
                    if prof_at is not None and not prof_active \
                            and not self._profiled \
                            and self.global_step >= prof_at:
                        jax.profiler.start_trace(prof_dir)
                        prof_active = True
                        prof_at = self.global_step  # anchor the stop check
                    # span covers HOST-side dispatch only (the jitted
                    # step is async; device time shows in the
                    # jax.profiler capture, not here) — named to match
                    # zoo_train_step_dispatch_seconds
                    losses = None
                    # zoolint: disable=host-sync -- host int boxing of the step index, not a device fetch
                    step_arr = np.asarray(self.global_step, np.int32)
                    with time_it("zoo.step_dispatch"), \
                            span("zoo.train.step_dispatch"):
                        if chunked:
                            kind, payload, nk = sharded
                            if kind == "scan":
                                # ONE dispatch advances nk inner steps;
                                # losses come back as a [nk] device
                                # array.  Under autotune nk follows the
                                # hill-climb, so the fused program is
                                # looked up per-chunk (a dict hit after
                                # each K's first compile).
                                fn = fused_fn if controller is None \
                                    else self._train_step_for(
                                        dev_tf, nk, plan)
                                params, opt_state, state, losses = \
                                    fn(
                                        params, opt_state, state,
                                        seed_arr, step_arr, payload)
                                loss_dev = losses[nk - 1]
                            else:  # partial tail chunk: K=1 fallback
                                params, opt_state, state, loss_dev = \
                                    step_fn(
                                        params, opt_state, state,
                                        seed_arr, step_arr, payload)
                        else:
                            nk = 1
                            params, opt_state, state, loss_dev = step_fn(
                                params, opt_state, state, seed_arr,
                                step_arr, sharded
                            )
                    t_disp = time.perf_counter()
                    self.global_step += nk
                    if prof_active and self.global_step >= \
                            prof_at + cfg.profile_steps:
                        # zoolint: disable=host-sync -- intentional: the trace must close on a completed step
                        jax.block_until_ready(loss_dev)
                        jax.profiler.stop_trace()
                        prof_active = False
                        self._profiled = True
                        logger.info("profiler trace written to %s", prof_dir)
                    bi += nk
                    n_records += batch_size * nk
                    tstate.iteration = self.global_step
                    tstate.epoch_finished = False
                    if losses is not None and self._writers:
                        # TB gets every inner step's loss, not just the
                        # boundary one: ONE device slice for the first
                        # nk-1 (the flush expands it; the last loss is
                        # buffered as a scalar by _on_iteration) —
                        # per-element indexing here would reintroduce
                        # nk host dispatches per fused step
                        base = self.global_step - nk
                        if nk > 1:
                            self._loss_buffer.append(
                                (base + 1, losses[: nk - 1]))
                    # Callbacks/triggers fire ONCE per dispatch, at the
                    # K-step boundary (docs/performance.md caveat):
                    # checkpoints, validation and loss flushes see
                    # iteration counts in strides of nk.
                    fired = self._on_iteration(
                        tstate, loss_dev, params, opt_state, state,
                        checkpoint_trigger, validation_set,
                        validation_trigger, epoch, bi, seed, batch_size,
                    )
                    params, opt_state, state = fired
                    # step-time breakdown: data-wait (infeed the feeder
                    # failed to hide) / dispatch / full iteration
                    step_s = time.perf_counter() - t_iter0
                    step_metrics.record_step(
                        t_data - t_iter0, t_disp - t_data,
                        step_s, batch_size * nk, steps=nk)
                    if controller is not None:
                        # one measured dispatch feeds the K hill-climb
                        # (full loop-iteration wall time — the quantity
                        # fusion amortizes)
                        controller.observe_dispatch(nk, step_s)
                    health.heartbeat("train_loop")
                    # flight recorder: one structured record per step
                    # (bounded ring — a postmortem shows the FINAL
                    # steps), stragglers flagged against rolling p50
                    flight.record(
                        "step", loop="train", step=self.global_step,
                        epoch=epoch, data_wait_s=round(t_data - t_iter0, 6),
                        dispatch_s=round(t_disp - t_data, 6),
                        step_s=round(step_s, 6),
                        **({"fused_steps": nk} if nk > 1 else {}))
                    # straggler detection on PER-STEP time: a K-step
                    # fused dispatch is ~K x a tail single dispatch by
                    # construction, so comparing raw dispatch times
                    # against one rolling p50 would flag every fused
                    # dispatch in epochs that end with a tail
                    if straggler.observe(step_s / nk):
                        step_metrics.stragglers.inc()
                        flight.record(
                            "straggler", loop="train",
                            step=self.global_step,
                            step_s=round(step_s, 6),
                            per_step_s=round(step_s / nk, 6),
                            rolling_p50_s=round(
                                straggler.rolling_p50(), 6))
                    if elastic is not None:
                        # The STEP BARRIER (ISSUE 16): the membership
                        # ledger's (generation, world, members) doc is
                        # the single source of truth, read once per
                        # dispatch; a generation change snapshots at
                        # this exact boundary and yields the fit.
                        newdoc = elastic.poll()
                        if newdoc is not None:
                            self._elastic_yield(
                                newdoc, params, opt_state, state,
                                tstate, epoch, bi, seed, flight)
            finally:
                feeder.stop()
                if prof_active:
                    # epoch ended (or failed) mid-capture: close the trace
                    jax.profiler.stop_trace()
                    self._profiled = True
                    prof_at = None
            # epoch boundary (the only unconditional host sync per epoch)
            dt = time.perf_counter() - epoch_t0
            if loss_dev is not None:
                # zoolint: disable=host-sync -- deliberate once-per-epoch sync (the comment above is the contract)
                tstate.loss = float(loss_dev)
            self._flush_loss_buffer()
            throughput = n_records / max(dt, 1e-9)
            logger.info(
                "epoch %d done: loss=%.4f, %.1f records/s, step=%d",
                epoch, tstate.loss if tstate.loss is not None else float("nan"),
                throughput, self.global_step,
            )
            self.history.append(
                {"epoch": epoch, "loss": tstate.loss,
                 "throughput": throughput}
            )
            if self._writers:
                self._writers[0].add_scalar(
                    "Throughput", throughput, self.global_step
                )
            step_metrics.record_epoch(epoch, throughput)
            record_device_memory()  # HBM gauges (no-op on CPU backends)
            tstate.epoch_finished = True
            epoch += 1
            tstate.epoch = epoch
            start_batch = 0
            params, opt_state, state = self._on_iteration(
                tstate, loss_dev, params, opt_state, state,
                checkpoint_trigger, validation_set, validation_trigger,
                epoch, 0, seed, batch_size,
            )
        self.epoch = epoch
        health.unregister("train_loop")  # finished on purpose, not wedged
        return params, opt_state, state

    def _flush_loss_buffer(self):
        """Convert buffered device loss scalars and write them to TB.

        Values are flushed well after their step was dispatched, so the
        float() conversions read already-computed results instead of forcing
        a device round-trip per iteration.
        """
        if not self._loss_buffer:
            return
        buf, self._loss_buffer = self._loss_buffer, []
        last = None
        for it, ld in buf:
            arr = np.asarray(ld)
            if arr.ndim == 0:
                vals = [(it, float(arr))]
            else:
                # fused dispatch buffered a [K-1] loss slice under its
                # FIRST inner step's iteration: one device fetch here
                # expands it
                vals = [(it + j, float(v)) for j, v in enumerate(arr)]
            for i, v in vals:
                last = v
                if self._writers:
                    self._writers[0].add_scalar("Loss", v, i)
        return last

    def _on_iteration(self, tstate, loss_dev, params, opt_state, state,
                      checkpoint_trigger, validation_set,
                      validation_trigger, epoch, next_batch, seed,
                      batch_size):
        if loss_dev is not None:
            # Keep the raw device scalar (no sync); loss-based triggers
            # comparing against it only pay the sync when actually used.
            tstate.loss = loss_dev
            if self._writers:
                self._loss_buffer.append((tstate.iteration, loss_dev))
                if len(self._loss_buffer) >= 50:
                    self._flush_loss_buffer()
        if validation_set is not None and validation_trigger is not None \
                and validation_trigger(tstate):
            # NOTE: do NOT attach the live buffers to the model here — the
            # next train step donates them, which would leave model.params
            # pointing at deleted arrays.
            results = self._evaluate_with(params, state, validation_set,
                                          batch_size=batch_size)
            tstate.score = next(
                (v for k, v in results.items() if k != "loss"),
                -results.get("loss", 0.0),
            )
            logger.info("validation @ step %d: %s", tstate.iteration,
                        results)
            if self._writers:
                for k, v in results.items():
                    self._writers[1].add_scalar(k, v, tstate.iteration)
        if checkpoint_trigger is not None and self._ckpt is not None \
                and checkpoint_trigger(tstate):
            opt_flat = jax.tree_util.tree_leaves(opt_state)
            self._ckpt.save(
                f"{tstate.iteration}",
                dict(params=params, state=state, opt_flat=opt_flat,
                     global_step=tstate.iteration, epoch=epoch,
                     next_batch=next_batch, seed=seed,
                     # the plan's spec trees (plain lists — safe_load
                     # clean): what layout this snapshot trained under,
                     # so elastic resume reshards knowingly through the
                     # partitioner
                     plan=getattr(self, "_plan_record", None)),
            )
        return params, opt_state, state

    def _elastic_yield(self, newdoc, params, opt_state, state, tstate,
                       epoch, next_batch, seed, flight):
        """Safe-snapshot at the step barrier and yield the fit to the
        elastic runtime (resume-at-new-world-size entry, ISSUE 16).

        The snapshot carries the exact iterator position
        (epoch/next_batch) and the plan record, so the successor leg —
        same process at a refolded mesh, or a fresh cohort — resumes
        mid-epoch from LATEST through the partitioner with the batch
        schedule (and so the RNG-folded trajectory) unchanged.  The
        flush before the raise makes the snapshot DURABLE before any
        worker acts on the new generation."""
        from analytics_zoo_tpu.elastic.membership import GenerationChange

        if self._ckpt is not None:
            opt_flat = jax.tree_util.tree_leaves(opt_state)
            self._ckpt.save(
                f"{tstate.iteration}",
                dict(params=params, state=state, opt_flat=opt_flat,
                     global_step=tstate.iteration, epoch=epoch,
                     next_batch=next_batch, seed=seed,
                     plan=getattr(self, "_plan_record", None)),
            )
            self._ckpt._wait()
        flight.record(
            "elastic", event="yield", step=tstate.iteration,
            generation=newdoc.get("generation"),
            world=newdoc.get("world"))
        self.epoch = epoch
        raise GenerationChange(newdoc)

    # ------------------------------------------------------------------
    # pure-device step timing (the bench decomposition hook)
    # ------------------------------------------------------------------
    def measure_pure_step(self, batch: dict, n_steps: int = 20,
                          device_transform=None) -> float:
        """Time the compiled train step on a device-resident batch.

        Returns seconds/step.  Uses FRESH device buffers (host round-trip
        copies) so the step's donation can never delete the live
        model/optimizer arrays, and a throwaway warm step so compile and
        transfer cost are excluded.  This is the "pure step" half of the
        bench's e2e-vs-compute decomposition; the difference to e2e is the
        infeed the feeder failed to hide.

        The compiled step is CACHED (keyed on transform + input
        signature, sharing the fit-loop cache), so repeated probes
        measure steady state: only the first call for a signature pays
        compile, and that warmup cost is reported separately in
        ``last_probe_warmup_seconds`` (0.0 on cached re-probes) instead
        of polluting the per-step figure.
        """
        if n_steps < 1:
            raise ValueError("n_steps must be >= 1")
        ctx = self.ctx
        plan = self._resolved_plan()
        step_fn = self._train_step_for(device_transform, 1, plan)
        params, state = self.model.build_params()
        host = jax.tree_util.tree_map(np.asarray, (params, state))
        params = self._place_params(host[0], plan)
        state = jax.device_put(host[1], ctx.replicated())
        opt_state = self._place_opt_state(self.optimizer.init(params),
                                          plan)
        sharded = ctx.shard_batch(batch, axes=plan.batch_axes)
        seed_arr = np.asarray(0, np.int32)
        sig = (device_transform, plan.cache_key(), tuple(
            (path, tuple(leaf.shape), str(leaf.dtype))
            for path, leaf in
            jax.tree_util.tree_flatten_with_path(sharded)[0]))
        t_warm = time.perf_counter()
        params, opt_state, state, loss = step_fn(
            params, opt_state, state, seed_arr, np.asarray(0, np.int32),
            sharded)
        float(loss)  # fetch-forced sync: block_until_ready can return
        #              early on some backends (axon); a dependent-scalar
        #              fetch cannot.
        warm_s = time.perf_counter() - t_warm
        if sig not in self._pure_step_warm:
            # first probe at this signature: warm_s is compile + first
            # step; report it separately so callers can quote cold cost
            self._pure_step_warm[sig] = warm_s
            self.last_probe_warmup_seconds = warm_s
        else:
            self.last_probe_warmup_seconds = 0.0
        t0 = time.perf_counter()
        for i in range(n_steps):
            params, opt_state, state, loss = step_fn(
                params, opt_state, state, seed_arr,
                np.asarray(i + 1, np.int32), sharded)
        float(loss)
        return (time.perf_counter() - t0) / n_steps

    # ------------------------------------------------------------------
    # AOT warmup (the compile plane, common/compile_cache.py)
    # ------------------------------------------------------------------
    def warmup(self, batch: dict, device_transform=None,
               steps_per_dispatch: int | None = None, plan=None) -> dict:
        """Pay XLA compilation for the train step BEFORE the first real
        batch (``.lower().compile()`` through the compile plane).

        ``batch`` is an example host batch dict (``{"x": ..., "y": ...}``,
        leading dim = the GLOBAL batch size fit() will use).
        ``device_transform`` must be the SAME transform the training
        FeatureSet carries (``train_set.device_transform``; the step
        cache is keyed on it) — warming with the default ``None`` while
        fit() uses a transform compiles a program fit never dispatches.
        Compiles the K=1 step and — when ``steps_per_dispatch`` (default: the
        configured ``ZOO_STEPS_PER_DISPATCH``) is > 1 — the fused scan-K
        step too, then runs ONE throwaway dispatch (a full train step on
        the example batch against fresh random-init buffers; results
        discarded, live model state untouched) so the in-process jit
        dispatch cache is warm.  With ``ZOO_COMPILE_CACHE`` set, an AOT
        ``.lower().compile()`` additionally populates the persistent
        cache first — the throwaway dispatch (and every later process
        compiling the same program) then deserializes it instead of
        re-running XLA; without a cache dir the AOT pass is skipped so
        each program compiles exactly once.

        Returns ``{label: seconds_to_ready}`` per program (AOT compile,
        if any, plus the throwaway dispatch); AOT compiles are also
        recorded in ``zoo_compile_seconds``.
        """
        ctx = self.ctx
        from analytics_zoo_tpu.common.compile_cache import (
            maybe_enable_persistent_cache,
        )
        maybe_enable_persistent_cache(ctx.config.compile_cache)
        plan = self._resolved_plan(plan)
        k = steps_per_dispatch if steps_per_dispatch is not None \
            else int(ctx.config.steps_per_dispatch or 1)
        if int(k) < 1:
            # same contract as ZooConfig: misconfigured K fails loudly
            # on every entry point (and before touching the step cache)
            raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
        params, state = self.model.build_params()
        host = jax.tree_util.tree_map(np.asarray, (params, state))
        out = {}
        host_batch = jax.tree_util.tree_map(np.asarray, batch)
        # Multi-host: the batch arg is GLOBAL (the documented contract);
        # fit()'s shard path consumes process-LOCAL rows, so slice ours
        # out — otherwise the warm program's batch dim would be
        # process_count x fit's.
        from analytics_zoo_tpu.feature.dataset import _slice_batch_rows
        host_batch = _slice_batch_rows(host_batch, _process_shard())
        for kk in sorted({1, k}):
            step_fn = self._train_step_for(device_transform, kk, plan)
            # fresh device buffers per variant: the throwaway dispatch
            # donates them, and the live model buffers are never touched.
            # params/opt_state take the SAME plan placement fit() will
            # use: the compiled program specializes on input shardings,
            # so a replicated warm here would compile a program fit
            # never runs.
            params = self._place_params(host[0], plan)
            state = jax.device_put(host[1], ctx.replicated())
            opt_state = self._place_opt_state(
                self.optimizer.init(params), plan)
            if kk == 1:
                sharded = ctx.shard_batch(host_batch,
                                          axes=plan.batch_axes)
            else:
                sharded = ctx.shard_batch_stacked(
                    jax.tree_util.tree_map(
                        lambda x: np.stack([x] * kk), host_batch),
                    axes=plan.batch_axes)
            args = (params, opt_state, state, np.asarray(0, np.int32),
                    np.asarray(0, np.int32), sharded)
            t0 = time.perf_counter()
            # ONE dispatch: the PlannedStep (parallel/plan.py) AOT-
            # lowers through timed_compile on its first call — the
            # persistent cache is populated / hit and the HLO features
            # extracted right here — then the cached executable runs.
            res = step_fn(*args)
            jax.block_until_ready(res[-1])
            out[step_fn.label] = time.perf_counter() - t0
        logger.info("warmup compiled %s", out)
        return out

    # ------------------------------------------------------------------
    # evaluate (Estimator.scala:157-176; KerasNet.evaluate)
    # ------------------------------------------------------------------
    def evaluate(self, val_set: FeatureSet, batch_size: int = 32) -> dict:
        if getattr(self.model, "params", None) is None \
                and self.global_step == 0:
            # Matches model.evaluate-before-fit semantics, but loudly: the
            # metrics below are RANDOM-weight metrics (round-2 verdict
            # Weak #10 — silent before).
            logger.warning(
                "evaluate() called before any training: materializing "
                "fresh random weights; metrics reflect an untrained model")
        params, state = self.model.build_params()
        return self._evaluate_with(params, state, val_set, batch_size)

    def _evaluate_with(self, params, state, val_set: FeatureSet,
                       batch_size: int = 32) -> dict:
        ctx = self.ctx
        dev_tf = getattr(val_set, "device_transform", None)
        if self._eval_step_fn is None or self._eval_step_fn[0] is not dev_tf:
            self._eval_step_fn = (dev_tf, self._build_eval_step(dev_tf))
        accum = None
        for batch in val_set.batches(batch_size, shuffle=False,
                                     drop_last=False,
                                     pad_to_batch=ctx.data_parallel_size,
                                     process_shard=_process_shard()):
            sharded = ctx.shard_batch(batch)
            stats = self._eval_step_fn[1](params, state, sharded)
            host = [[np.asarray(s) for s in group] for group in stats]
            if accum is None:
                accum = host
            else:
                accum = [
                    [a + b for a, b in zip(ga, gb)]
                    for ga, gb in zip(accum, host)
                ]
        results = {}
        idx = 0
        if self.loss is not None:
            num, den = accum[idx]
            results["loss"] = float(num) / max(float(den), 1e-12)
            idx += 1
        for m in self.metrics:
            results[m.name] = m.finalize(accum[idx])
            idx += 1
        return results
