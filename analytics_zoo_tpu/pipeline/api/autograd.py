"""Autograd facade — Variable math, AutoGrad ops, CustomLoss, Lambda,
Parameter.

Reference: pipeline/api/autograd/math.scala (AutoGrad ops :32-363, Variable
operator overloads :365-612), KerasParameter.scala:31-100 (``Parameter``
trainable leaf), CustomLoss.scala (Variable expr → Criterion), Lambda.scala
(Variable expr → layer).  The reference builds BigDL graph nodes and relies
on BigDL's hand-written backward passes.

TPU re-design: a ``Variable`` is a symbolic tensor over the same Node graph
the Keras Model uses (engine.Variable); every op here appends a pure-jnp
``LambdaOp`` node.  Differentiation is ``jax.grad`` through the traced
graph — no per-op backward code at all, which is the whole point of building
on a functional-AD substrate.

Example (reference-style custom loss, autograd/math.scala mean/abs):

    def mean_absolute_error(y_true, y_pred):
        result = AutoGrad.mean(AutoGrad.abs(y_true - y_pred), axis=1)
        return result
    model.compile(optimizer=..., loss=CustomLoss(mean_absolute_error,
                                                 [3], [3]))
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.engine import (
    Layer,
    Node,
    Variable,
)
from analytics_zoo_tpu.pipeline.api.keras.objectives import LossFunction


class LambdaOp(Layer):
    """A pure-jnp op node in the symbolic graph."""

    def __init__(self, fn: Callable, out_shape_fn: Callable, op_name="op",
                 name=None):
        super().__init__(name=name)
        self.fn = fn
        self.out_shape_fn = out_shape_fn
        self.built = True
        self._build_shape = None

    def ensure_built(self, input_shape):
        self._build_shape = input_shape
        return input_shape

    def call(self, params, inputs, state=None, training=False, rng=None):
        if isinstance(inputs, (list, tuple)):
            return self.fn(*inputs)
        return self.fn(inputs)

    def compute_output_shape(self, input_shape):
        return self.out_shape_fn(input_shape)

    def param_count(self):
        return 0


def _apply_op(fn, shape_fn, op_name, *variables):
    """Apply an op: symbolically (Variable inputs → LambdaOp graph node) or
    eagerly (array inputs → call fn directly).  Eager dispatch lets the same
    AutoGrad functions run inside CustomLoss bodies, where arguments are jax
    tracers, matching how the reference's AutoGrad ops are used both in
    Lambda graphs and custom losses."""
    if any(isinstance(v, Variable) for v in variables):
        op = LambdaOp(fn, shape_fn, op_name=op_name)
        return op(list(variables) if len(variables) > 1 else variables[0])
    return fn(*variables)


def _full_shape(v) -> tuple:
    return v.shape if isinstance(v, Variable) else tuple(np.shape(v))


def _broadcast_shapes(a, b):
    """Numpy-style broadcast of symbolic shapes (None = unknown/batch)."""
    out = []
    ra, rb = list(a)[::-1], list(b)[::-1]
    for i in range(max(len(ra), len(rb))):
        da = ra[i] if i < len(ra) else 1
        db = rb[i] if i < len(rb) else 1
        if da is None or db is None:
            out.append(None)
        elif da == 1:
            out.append(db)
        elif db == 1 or da == db:
            out.append(da)
        else:
            raise ValueError(f"cannot broadcast {a} and {b}")
    return tuple(out[::-1])


def _binop(name, fn):
    def op(self, other):
        if isinstance(other, Variable):
            shape = _broadcast_shapes(self.shape, other.shape)
            return _apply_op(fn, lambda s: shape, name, self, other)
        const = other

        def unary(x):
            return fn(x, const)

        return _apply_op(unary, lambda s: s, name, self)

    return op


def _rbinop(name, fn):
    def op(self, other):
        const = other

        def unary(x):
            return fn(const, x)

        return _apply_op(unary, lambda s: s, name, self)

    return op


# -- install operators on the shared symbolic Variable class ---------------
Variable.__add__ = _binop("add", lambda a, b: a + b)
Variable.__radd__ = _rbinop("radd", lambda a, b: a + b)
Variable.__sub__ = _binop("sub", lambda a, b: a - b)
Variable.__rsub__ = _rbinop("rsub", lambda a, b: a - b)
Variable.__mul__ = _binop("mul", lambda a, b: a * b)
Variable.__rmul__ = _rbinop("rmul", lambda a, b: a * b)
Variable.__truediv__ = _binop("div", lambda a, b: a / b)
Variable.__rtruediv__ = _rbinop("rdiv", lambda a, b: a / b)
Variable.__pow__ = _binop("pow", lambda a, b: a ** b)
Variable.__neg__ = lambda self: _apply_op(
    lambda x: -x, lambda s: s, "neg", self
)


def _slice_shape(shape, dim, start, length):
    s = list(shape)
    s[dim] = length
    return tuple(s)


def _variable_slice(self, dim, start_index, length):
    """Reference Variable.slice (autograd/math.scala)."""

    def fn(x):
        idx = [slice(None)] * x.ndim
        idx[dim] = slice(start_index, start_index + length)
        return x[tuple(idx)]

    return _apply_op(fn, lambda s: _slice_shape(s, dim, start_index, length),
                     "slice", self)


def _variable_index_select(self, dim, index):
    """Reference Variable.indexSelect: select one index along dim (dim may
    be negative; batch dim = 0)."""

    def fn(x):
        return jnp.take(x, index, axis=dim)

    def shape_fn(s):
        s = list(s)
        d = dim if dim >= 0 else len(s) + dim
        del s[d]
        return tuple(s)

    return _apply_op(fn, shape_fn, "index_select", self)


def _variable_squeeze(self, dim):
    def fn(x):
        return jnp.squeeze(x, axis=dim)

    def shape_fn(s):
        s = list(s)
        d = dim if dim >= 0 else len(s) + dim
        del s[d]
        return tuple(s)

    return _apply_op(fn, shape_fn, "squeeze", self)


Variable.slice = _variable_slice
Variable.index_select = _variable_index_select
Variable.squeeze = _variable_squeeze


class AutoGrad:
    """Namespace of autograd math ops (reference ``AutoGrad`` object,
    autograd/math.scala:32-363)."""

    @staticmethod
    def abs(x: Variable) -> Variable:
        return _apply_op(jnp.abs, lambda s: s, "abs", x)

    @staticmethod
    def sum(x: Variable, axis=0, keepdims=False) -> Variable:
        return AutoGrad._reduce(jnp.sum, x, axis, keepdims)

    @staticmethod
    def mean(x: Variable, axis=0, keepdims=False) -> Variable:
        return AutoGrad._reduce(jnp.mean, x, axis, keepdims)

    @staticmethod
    def _reduce(fn, x, axis, keepdims):
        def run(v):
            return fn(v, axis=axis, keepdims=keepdims)

        def shape_fn(s):
            s = list(s)
            d = axis if axis >= 0 else len(s) + axis
            if keepdims:
                s[d] = 1
            else:
                del s[d]
            return tuple(s)

        return _apply_op(run, shape_fn, "reduce", x)

    @staticmethod
    def clip(x: Variable, min, max) -> Variable:
        return _apply_op(lambda v: jnp.clip(v, min, max), lambda s: s,
                         "clip", x)

    @staticmethod
    def square(x: Variable) -> Variable:
        return _apply_op(jnp.square, lambda s: s, "square", x)

    @staticmethod
    def sqrt(x: Variable) -> Variable:
        return _apply_op(jnp.sqrt, lambda s: s, "sqrt", x)

    @staticmethod
    def exp(x: Variable) -> Variable:
        return _apply_op(jnp.exp, lambda s: s, "exp", x)

    @staticmethod
    def log(x: Variable) -> Variable:
        return _apply_op(jnp.log, lambda s: s, "log", x)

    @staticmethod
    def pow(x: Variable, a: float) -> Variable:
        return _apply_op(lambda v: v ** a, lambda s: s, "pow", x)

    @staticmethod
    def epsilon() -> float:
        return 1e-7

    @staticmethod
    def maximum(x, y):
        if isinstance(y, Variable):
            return _apply_op(jnp.maximum,
                             lambda s: s, "maximum", x, y)
        return _apply_op(lambda v: jnp.maximum(v, y), lambda s: s,
                         "maximum", x)

    @staticmethod
    def erf(x: Variable) -> Variable:
        return _apply_op(jax.scipy.special.erf, lambda s: s, "erf", x)

    @staticmethod
    def softsign(x: Variable) -> Variable:
        return _apply_op(jax.nn.soft_sign, lambda s: s, "softsign", x)

    @staticmethod
    def softplus(x: Variable) -> Variable:
        return _apply_op(jax.nn.softplus, lambda s: s, "softplus", x)

    @staticmethod
    def l2_normalize(x: Variable, axis=-1) -> Variable:
        def fn(v):
            return v / jnp.clip(
                jnp.linalg.norm(v, axis=axis, keepdims=True), 1e-12
            )

        return _apply_op(fn, lambda s: s, "l2_normalize", x)

    @staticmethod
    def mm(x: Variable, y: Variable, axes=None) -> Variable:
        """Batched matrix multiply contracting ``axes=[ax_of_x, ax_of_y]``
        (reference AutoGrad.mm, autograd/math.scala).  Default contracts
        x's last axis with y's second-to-last (plain matmul)."""

        def fn(a, b):
            if axes is None:
                return jnp.matmul(a, b)
            aa = jnp.moveaxis(a, axes[0], -1)
            bb = jnp.moveaxis(b, axes[1], -1)
            if aa.ndim == 3 and bb.ndim == 3:
                return jnp.einsum("bid,bjd->bij", aa, bb)
            if aa.ndim == 2 and bb.ndim == 2:
                return jnp.einsum("id,jd->ij", aa, bb)
            raise ValueError(
                f"mm supports 2-3D inputs with axes; got {a.shape}, "
                f"{b.shape}"
            )

        def shape_fn(shapes):
            sa, sb = [list(s) for s in shapes]
            if axes is None:
                return tuple(sa[:-1]) + (sb[-1],)
            ax = axes[0] % len(sa)
            ay = axes[1] % len(sb)
            da = [d for i, d in enumerate(sa) if i != ax]
            db = [d for i, d in enumerate(sb) if i != ay]
            if len(sa) == 3:
                return (sa[0], da[1], db[1])
            return (da[0], db[0])

        return _apply_op(fn, shape_fn, "mm", x, y)

    @staticmethod
    def batch_dot(x: Variable, y: Variable, axes=(2, 2),
                  normalize=False) -> Variable:
        """Reference AutoGrad.batchDot: per-sample contraction over ``axes``
        for 3-D inputs (B, I, D)·(B, J, D) → (B, I, J); with
        ``normalize=True`` rows are l2-normalized first (cosine)."""
        ax, ay = axes

        def fn(a, b):
            if a.ndim != 3 or b.ndim != 3:
                raise ValueError(
                    f"batch_dot expects 3-D inputs, got {a.shape}, {b.shape}"
                )
            aa, bb = a, b
            if normalize:
                aa = aa / jnp.clip(
                    jnp.linalg.norm(aa, axis=ax, keepdims=True), 1e-12)
                bb = bb / jnp.clip(
                    jnp.linalg.norm(bb, axis=ay, keepdims=True), 1e-12)
            aa = jnp.moveaxis(aa, ax, -1)
            bb = jnp.moveaxis(bb, ay, -1)
            return jnp.einsum("bid,bjd->bij", aa, bb)

        def shape_fn(shapes):
            sa, sb = [list(s) for s in shapes]
            d_a = [d for i, d in enumerate(sa) if i not in (0, ax % len(sa))]
            d_b = [d for i, d in enumerate(sb) if i not in (0, ay % len(sb))]
            return tuple([sa[0]] + d_a + d_b)

        return _apply_op(fn, shape_fn, "batch_dot", x, y)

    @staticmethod
    def contiguous(x: Variable) -> Variable:
        return x

    @staticmethod
    def expand_dims(x: Variable, axis) -> Variable:
        def shape_fn(s):
            s = list(s)
            d = axis if axis >= 0 else len(s) + 1 + axis
            s.insert(d, 1)
            return tuple(s)

        return _apply_op(lambda v: jnp.expand_dims(v, axis), shape_fn,
                         "expand_dims", x)

    @staticmethod
    def stack(inputs: Sequence[Variable], axis=1) -> Variable:
        def fn(*xs):
            return jnp.stack(xs, axis=axis)

        def shape_fn(shapes):
            s = list(shapes[0])
            s.insert(axis if axis >= 0 else len(s) + 1 + axis, len(inputs))
            return tuple(s)

        return _apply_op(fn, shape_fn, "stack", *inputs)


# convenience module-level aliases (reference exposes both forms)
mean = AutoGrad.mean
abs = AutoGrad.abs  # noqa: A001 - mirrors reference API name
sum = AutoGrad.sum  # noqa: A001
clip = AutoGrad.clip
square = AutoGrad.square
sqrt = AutoGrad.sqrt
exp = AutoGrad.exp
log = AutoGrad.log
maximum = AutoGrad.maximum
l2_normalize = AutoGrad.l2_normalize
mm = AutoGrad.mm
batch_dot = AutoGrad.batch_dot
erf = AutoGrad.erf
epsilon = AutoGrad.epsilon
expand_dims = AutoGrad.expand_dims
stack = AutoGrad.stack


class Parameter(Layer):
    """Trainable leaf tensor (reference KerasParameter.scala:31-100):
    a Variable whose value is learned.  Call it with no inputs in a graph by
    using it as a symbolic source: ``w = Parameter((3, 4))(); y = x + w``."""

    def __init__(self, shape, init_weight=None, init="glorot_uniform",
                 trainable=True, name=None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.shape = tuple(int(s) for s in shape)
        self.init = init
        self.init_weight = init_weight
        self.trainable = trainable

    def build(self, input_shape):
        if self.init_weight is not None:
            from analytics_zoo_tpu.pipeline.api.keras.layers.embedding \
                import _Pretrained

            w = np.asarray(self.init_weight)
            if tuple(w.shape) != self.shape:
                raise ValueError(
                    f"Parameter init_weight shape {w.shape} != declared "
                    f"shape {self.shape}"
                )
            self.add_weight("value", self.shape, _Pretrained(w),
                            trainable=self.trainable)
        else:
            self.add_weight("value", self.shape, self.init,
                            trainable=self.trainable)

    def call(self, params, inputs, state=None, training=False, rng=None):
        if "value" in params:
            return params["value"]
        return state["value"], state

    @property
    def stateful(self):
        return not self.trainable

    def __call__(self, x=None):
        """Symbolic: yields a Variable carrying the parameter value.
        Needs an anchor input only for graph reachability; pass any graph
        Variable or none (the node has no inbound edges)."""
        if x is not None:
            return super().__call__(x)
        self.ensure_built(None)
        var = Variable(None, 0, (None,) + self.shape, name=self.name)
        node = Node(self, [], [var])
        var.node = node
        return var

    def compute_output_shape(self, input_shape):
        return (None,) + self.shape


class Lambda(Layer):
    """Wrap a python function over Variables into a layer (reference
    Lambda.scala / pyzoo autograd.Lambda)."""

    def __init__(self, function: Callable, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.function = function
        self.built = True

    def ensure_built(self, input_shape):
        self._build_shape = input_shape
        return input_shape

    def call(self, params, inputs, state=None, training=False, rng=None):
        if isinstance(inputs, (list, tuple)):
            return self.function(*inputs)
        return self.function(inputs)

    def compute_output_shape(self, input_shape):
        # evaluate the function on dummies to infer the shape
        shapes = input_shape if isinstance(input_shape, list) \
            else [input_shape]
        dummies = [jnp.zeros([1 if d is None else d for d in s])
                   for s in shapes]
        out = jax.eval_shape(
            lambda *xs: self.function(*xs)
            if len(dummies) > 1 else self.function(xs[0]), *dummies
        )
        shape = tuple(out.shape)
        return (None,) + shape[1:]


class CustomLoss(LossFunction):
    """Build a loss from a python function over (y_true, y_pred) Variables
    or plain jnp arrays (reference CustomLoss.scala; pyzoo
    autograd.CustomLoss).

    The reference requires explicit sizeAverage handling and builds a BigDL
    criterion graph; here the function runs under jax tracing directly.
    ``loss_fn(y_true, y_pred)`` may return per-sample or scalar values.
    """

    def __init__(self, loss_fn: Callable, y_pred_shape=None,
                 y_true_shape=None):
        self.user_fn = loss_fn
        super().__init__(self._run, "custom_loss")

    def _run(self, y_true, y_pred):
        out = self.user_fn(y_true, y_pred)
        if isinstance(out, Variable):
            raise TypeError(
                "CustomLoss function must use jnp ops on its array "
                "arguments (it is traced by jax), not symbolic Variables"
            )
        out = jnp.asarray(out)
        if out.ndim == 0:
            return out[None]
        if out.ndim > 1:
            return out.reshape(out.shape[0], -1).mean(axis=-1)
        return out

    def forward(self, y_true, y_pred):
        """Evaluate the loss eagerly (reference CustomLoss.forward)."""
        return float(jnp.mean(self._run(jnp.asarray(y_true),
                                        jnp.asarray(y_pred))))
