"""ONNX exporter — trained zoo models out to the interchange format.

Reference parity: ``NetSaver`` (Net.scala:264+) exports trained zoo models
to external formats (TF pb / keras h5 via a spawned python); here the
exchange format is ONNX and the writer is the same self-contained wire
codec the loader uses (proto.py) — no ``onnx`` package dependency either
direction.

Semantics: the exported graph follows ONNX conventions — NCHW activations
(this framework is NHWC, so conv kernels are transposed to OIHW, SAME
padding becomes explicit ``pads``, and Dense kernels after a Flatten are
row-permuted to the CHW element order), inference mode (dropout dropped,
batch-norm frozen to its moving statistics).  Round-trip fidelity is
CI-tested: ``load_onnx(export_onnx(net))`` must reproduce ``net``'s
forward outputs on transposed inputs.
"""

from __future__ import annotations

import numpy as np

from analytics_zoo_tpu.pipeline.api.onnx.proto import (
    FLOAT,
    Graph,
    Model,
    Node,
    ValueInfo,
    encode_model,
)

# NamedActivation.name -> ONNX op (None = identity, drop the node)
_ACT_OPS = {
    None: None, "linear": None,
    "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
    "softmax": "Softmax", "log_softmax": "LogSoftmax",
    "elu": "Elu", "selu": "Selu", "softplus": "Softplus",
    "softsign": "Softsign",
}


class _Exporter:
    def __init__(self, opset=13):
        self.graph = Graph(name="zoo_export")
        self.opset = opset
        self._n = 0

    def fresh(self, hint):
        self._n += 1
        return f"{hint}_{self._n}"

    def add(self, op, inputs, attrs=None, hint=None):
        out = self.fresh(hint or op.lower())
        self.graph.nodes.append(Node(
            op_type=op, name=out, inputs=list(inputs), outputs=[out],
            attrs=dict(attrs or {})))
        return out

    def init_tensor(self, name, arr):
        self.graph.initializers[name] = np.asarray(arr)
        return name


def _act_name(layer):
    act = getattr(layer, "activation", None)
    # NamedActivation carries .name; a custom callable has none and cannot
    # be exported (its python body is not representable as an ONNX op)
    name = act if act is None else getattr(act, "name", "<custom callable>")
    if name not in _ACT_OPS:
        raise ValueError(
            f"layer {layer.name}: activation {name!r} has no ONNX export")
    return name


def _emit_activation(ex, name, x, spatial=False):
    op = _ACT_OPS[name]
    if op is None:
        return x
    attrs = {}
    if op in ("Softmax", "LogSoftmax"):
        # framework softmax is over NHWC channels: that is axis 1 on a 4D
        # NCHW tensor, -1 elsewhere
        attrs = {"axis": 1 if spatial else -1}
    return ex.add(op, [x], attrs)


def _same_pads(spatial, kernel, strides, dilation=None):
    """Explicit ONNX pads replicating XLA 'SAME' (extra pad at the end):
    [begin_h, begin_w, end_h, end_w]."""
    begins, ends = [], []
    dilation = dilation or (1,) * len(kernel)
    for s, k, st, d in zip(spatial, kernel, strides, dilation):
        eff = (k - 1) * d + 1
        out = -(-s // st)
        total = max((out - 1) * st + eff - s, 0)
        begins.append(total // 2)
        ends.append(total - total // 2)
    return begins + ends


# Each emitter: (ex, layer, params, state, in_names, in_shapes, in_perms)
# -> (out_name, out_shape_nhwc_nobatch, flat_perm_or_None).
# in_shapes are semantic NHWC shapes without the batch dim; flat_perm maps
# ONNX (CHW-order) flat indices to this framework's (HWC-order) ones.


def _export_dense(ex, layer, params, state, ins, shapes, perms):
    if len(shapes[0]) == 3:
        # the graph tensor is NCHW but Dense applies to NHWC's last axis;
        # exporting would need a layout-breaking Transpose — require the
        # model to Flatten/GlobalAveragePool first (as real ones do)
        raise ValueError(
            f"layer {layer.name}: Dense directly on a spatial (H, W, C) "
            f"tensor has no ONNX export; add Flatten or a global pool "
            f"before it")
    kernel = np.asarray(params["kernel"])  # (in, out)
    if perms[0] is not None:
        kernel = kernel[perms[0]]
    w = ex.init_tensor(ex.fresh(f"{layer.name}_w"), kernel)
    if len(shapes[0]) == 1:
        inputs = [ins[0], w]
        if layer.bias:
            inputs.append(ex.init_tensor(ex.fresh(f"{layer.name}_b"),
                                         params["bias"]))
        out = ex.add("Gemm", inputs, hint=layer.name)
    else:
        # ND input: Gemm is rank-2-only per spec; MatMul broadcasts
        # leading dims (Dense applies to the last axis)
        out = ex.add("MatMul", [ins[0], w], hint=layer.name)
        if layer.bias:
            b = ex.init_tensor(ex.fresh(f"{layer.name}_b"), params["bias"])
            out = ex.add("Add", [out, b])
    out = _emit_activation(ex, _act_name(layer), out)
    return out, tuple(shapes[0][:-1]) + (layer.output_dim,), None


def _export_conv2d(ex, layer, params, state, ins, shapes, perms):
    h, w_, _c = shapes[0]
    kernel = np.transpose(np.asarray(params["kernel"]), (3, 2, 0, 1))
    wn = ex.init_tensor(ex.fresh(f"{layer.name}_w"), kernel)
    inputs = [ins[0], wn]
    if layer.bias:
        inputs.append(ex.init_tensor(ex.fresh(f"{layer.name}_b"),
                                     params["bias"]))
    attrs = {"strides": list(layer.subsample),
             "dilations": list(layer.dilation)}
    if layer.border_mode == "same":
        attrs["pads"] = _same_pads((h, w_), layer.kernel_size,
                                   layer.subsample, layer.dilation)
    out = ex.add("Conv", inputs, attrs, hint=layer.name)
    out = _emit_activation(ex, _act_name(layer), out, spatial=True)
    out_shape = tuple(layer.compute_output_shape((None,) + shapes[0]))[1:]
    return out, out_shape, None


def _export_pool2d(ex, layer, params, state, ins, shapes, perms):
    op = "MaxPool" if layer.mode == "max" else "AveragePool"
    attrs = {"kernel_shape": list(layer.pool_size),
             "strides": list(layer.strides)}
    if layer.border_mode == "same":
        attrs["pads"] = _same_pads(shapes[0][:2], layer.pool_size,
                                   layer.strides)
        if op == "AveragePool":
            # our SAME average pool divides by the unpadded window count
            attrs["count_include_pad"] = 0
    out = ex.add(op, [ins[0]], attrs, hint=layer.name)
    out_shape = tuple(layer.compute_output_shape((None,) + shapes[0]))[1:]
    return out, out_shape, None


def _export_globalpool2d(ex, layer, params, state, ins, shapes, perms):
    op = "GlobalMaxPool" if layer.mode == "max" else "GlobalAveragePool"
    pooled = ex.add(op, [ins[0]], hint=layer.name)
    out = ex.add("Flatten", [pooled], {"axis": 1})
    return out, (shapes[0][-1],), None


def _export_bn(ex, layer, params, state, ins, shapes, perms):
    ch = shapes[0][-1]
    vecs = {
        "scale": np.asarray(params.get("gamma", np.ones(ch, np.float32))),
        "bias": np.asarray(params.get("beta", np.zeros(ch, np.float32))),
        "mean": np.asarray(state["moving_mean"]),
        "var": np.asarray(state["moving_var"]),
    }
    if perms[0] is not None:
        # BN after Flatten: the tensor is in ONNX CHW flat order, so the
        # per-feature vectors must be reordered to match; the element
        # order itself is unchanged, so the perm propagates
        vecs = {k: v[perms[0]] for k, v in vecs.items()}
    inputs = [ins[0]] + [
        ex.init_tensor(ex.fresh(f"{layer.name}_{k}"), v)
        for k, v in vecs.items()
    ]
    out = ex.add("BatchNormalization", inputs,
                 {"epsilon": layer.epsilon}, hint=layer.name)
    return out, shapes[0], perms[0]


def _export_flatten(ex, layer, params, state, ins, shapes, perms):
    out = ex.add("Flatten", [ins[0]], {"axis": 1}, hint=layer.name)
    shape = shapes[0]
    n = int(np.prod(shape))
    if len(shape) == 3:  # (H, W, C) -> ONNX flat order is CHW
        perm = np.arange(n).reshape(shape).transpose(2, 0, 1).ravel()
    else:  # already flat: keep whatever element order it arrived in
        perm = perms[0]
    return out, (n,), perm


def _export_dropout(ex, layer, params, state, ins, shapes, perms):
    # inference export: dropout is identity
    return ins[0], shapes[0], perms[0]


def _export_activation(ex, layer, params, state, ins, shapes, perms):
    out = _emit_activation(ex, _act_name(layer), ins[0],
                           spatial=len(shapes[0]) == 3)
    return out, shapes[0], perms[0]


_MERGE_OPS = {"sum": "Sum", "max": "Max", "ave": "Mean", "concat": "Concat"}


def _export_merge(ex, layer, params, state, ins, shapes, perms):
    op = _MERGE_OPS.get(layer.mode)
    if op is None:
        raise ValueError(
            f"layer {layer.name}: merge mode {layer.mode!r} has no ONNX "
            f"export (supported: {sorted(_MERGE_OPS)})")
    if op == "Concat":
        axis = layer.concat_axis
        ndim = len(shapes[0]) + 1  # + batch
        axis = axis % ndim
        if len(shapes[0]) == 3:  # NHWC axis -> NCHW axis
            axis = {0: 0, 1: 2, 2: 3, 3: 1}[axis]
        if any(p is not None for p in perms):
            if len(shapes[0]) != 1 or axis != 1:
                raise ValueError(
                    f"layer {layer.name}: concat of flattened (permuted) "
                    f"tensors only exports along the feature axis")
            # concatenated flat order: each segment keeps its own perm,
            # offset by the features preceding it
            parts, off = [], 0
            for p, s in zip(perms, shapes):
                n = int(np.prod(s))
                parts.append((np.arange(n) if p is None else p) + off)
                off += n
            out_perm = np.concatenate(parts)
        else:
            out_perm = None
        out = ex.add("Concat", ins, {"axis": axis}, hint=layer.name)
        new = list(shapes[0])
        cat_sem = (layer.concat_axis % ndim) - 1
        new[cat_sem] = sum(s[cat_sem] for s in shapes)
        return out, tuple(new), out_perm
    # elementwise modes: permuted inputs must share ONE element order
    out_perm = perms[0]
    for p in perms[1:]:
        same = (p is None and out_perm is None) or (
            p is not None and out_perm is not None
            and np.array_equal(p, out_perm))
        if not same:
            raise ValueError(
                f"layer {layer.name}: elementwise merge of tensors with "
                f"different flat element orders has no ONNX export")
    if op == "Mean":
        out = ex.add("Sum", ins, hint=layer.name)
        # Mean = Sum / n via a scalar initializer (Sum is opset-stable)
        scale = ex.init_tensor(ex.fresh(f"{layer.name}_n"),
                               np.asarray(float(len(ins)), np.float32))
        out = ex.add("Div", [out, scale])
        return out, shapes[0], out_perm
    out = ex.add(op, ins, hint=layer.name)
    return out, shapes[0], out_perm


def _emitters():
    from analytics_zoo_tpu.pipeline.api.keras import layers as L

    return {
        L.Dense: _export_dense,
        L.Convolution2D: _export_conv2d,
        L.MaxPooling2D: _export_pool2d,
        L.AveragePooling2D: _export_pool2d,
        L.GlobalAveragePooling2D: _export_globalpool2d,
        L.GlobalMaxPooling2D: _export_globalpool2d,
        L.BatchNormalization: _export_bn,
        L.Flatten: _export_flatten,
        L.Dropout: _export_dropout,
        L.Activation: _export_activation,
        L.Merge: _export_merge,
    }


def _to_nchw(shape):
    """Semantic (no-batch) NHWC shape -> ONNX value-info shape w/ batch."""
    if len(shape) == 3:
        h, w, c = shape
        return (None, c, h, w)
    return (None,) + tuple(shape)


def export_onnx(net, path: str | None = None, opset: int = 13) -> bytes:
    """Serialize a trained KerasNet (Sequential or graph Model) or ZooModel
    to ONNX bytes; optionally also write them to ``path``.

    The exported graph takes NCHW inputs (transpose NHWC arrays with
    ``x.transpose(0, 3, 1, 2)`` before feeding an ONNX runtime) and runs in
    inference mode.  Raises ValueError naming the first layer whose type
    (or activation / merge mode) has no exporter.
    """
    if hasattr(net, "model") and not hasattr(net, "forward"):  # ZooModel
        net = net.model
    if net.params is None:
        net.build_params()
    params = net.params
    state = net.state if getattr(net, "state", None) else net.init_state()
    emitters = _emitters()
    ex = _Exporter(opset=opset)

    def emit(layer, ins, shapes, perms):
        fn = emitters.get(type(layer))
        if fn is None:
            raise ValueError(
                f"layer {layer.name} ({type(layer).__name__}) has no ONNX "
                f"exporter; supported: "
                f"{sorted(c.__name__ for c in emitters)}")
        return fn(ex, layer, params.get(layer.name, {}),
                  state.get(layer.name, {}), ins, shapes, perms)

    from analytics_zoo_tpu.pipeline.api.keras.topology import (
        Model as GraphModel,
        Sequential,
    )

    if isinstance(net, Sequential):
        shape = net.get_input_shape()[1:]
        ex.graph.inputs.append(ValueInfo("input", _to_nchw(shape), FLOAT))
        name, perm = "input", None
        for layer in net.layers:
            name, shape, perm = emit(layer, [name], [shape], [perm])
        out_name, out_shape = name, shape  # `perm` holds the final order
    elif isinstance(net, GraphModel):
        info: dict[str, tuple] = {}  # variable name -> (onnx, shape, perm)
        for i, v in enumerate(net._graph.inputs):
            nm = f"input_{i}" if len(net._graph.inputs) > 1 else "input"
            ex.graph.inputs.append(
                ValueInfo(nm, _to_nchw(v.shape[1:]), FLOAT))
            info[v.name] = (nm, tuple(v.shape[1:]), None)
        for node in net._graph.nodes:
            if not node.inbound:  # Input node
                continue
            ins, shapes, perms = zip(*(info[v.name] for v in node.inbound))
            out = emit(node.layer, list(ins), list(shapes), list(perms))
            for v in node.outputs:
                info[v.name] = out
        outs = net._graph.outputs
        if len(outs) != 1:
            raise ValueError("multi-output export not supported")
        out_name, out_shape, perm = info[outs[0].name]
    else:
        raise TypeError(f"cannot export {type(net).__name__}")

    if perm is not None:
        # the model ends on a flattened tensor whose ONNX element order is
        # CHW: restore the framework's HWC order so consumers see the same
        # feature vector this framework produces
        inv = ex.init_tensor(ex.fresh("restore_order"),
                             np.argsort(perm).astype(np.int64))
        out_name = ex.add("Gather", [out_name, inv], {"axis": 1})

    ex.graph.outputs.append(
        ValueInfo(out_name, _to_nchw(out_shape), FLOAT))
    data = encode_model(Model(ir_version=8, opset=opset, graph=ex.graph))
    if path:
        with open(path, "wb") as f:
            f.write(data)
    return data
