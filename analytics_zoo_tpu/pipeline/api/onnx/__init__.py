"""ONNX model loader + exporter.

Reference: pyzoo/zoo/pipeline/api/onnx/onnx_loader.py + mapper/*.py (44 op
mappers building a zoo keras graph from an onnx ModelProto); the export
direction plays the role of ``NetSaver`` (Net.scala:264+, zoo model ->
external format).

TPU re-design: the graph is interpreted once at trace time into a single
jit-compiled XLA program (:class:`OnnxNet` is an ordinary zoo Layer), with
float initializers exposed as trainable params so imported models can be
fine-tuned.  The protobuf is parsed/written by the self-contained wire
codec in :mod:`.proto` — the ``onnx`` package is not required either
direction.  :func:`export_onnx` (in :mod:`.export`) serializes a trained
Sequential/Model to ONNX bytes (NCHW, inference mode).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer
from analytics_zoo_tpu.pipeline.api.onnx.mapper import MAPPERS
from analytics_zoo_tpu.pipeline.api.onnx import proto
from analytics_zoo_tpu.pipeline.api.onnx.proto import Model, decode_model


class _Fixed:
    """Picklable initializer returning a captured array."""

    def __init__(self, arr):
        self.arr = np.asarray(arr)

    def __call__(self, rng, shape, dtype):
        return jnp.asarray(self.arr, dtype)


class OnnxNet(Layer):
    """An ONNX graph as a zoo Layer (reference onnx_loader.py OnnxLoader).

    Float initializers become trainable params (set ``trainable=False`` to
    freeze them into state); integer initializers (shapes, axes, indices)
    stay static so shape-consuming ops jit cleanly.  ONNX layouts (NCHW
    convs) are preserved — XLA picks the TPU-internal layout itself.
    """

    def __init__(self, model: Model, trainable=True, name=None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.graph = model.graph
        self.opset = model.opset
        self.trainable = trainable
        self._static = {"__opset__": model.opset}  # + int initializers
        self._learn = {}    # float initializers: params/state
        for iname, arr in self.graph.initializers.items():
            if np.issubdtype(arr.dtype, np.floating):
                self._learn[iname] = arr
            else:
                self._static[iname] = arr
        init_names = set(self.graph.initializers)
        self.input_names = [vi.name for vi in self.graph.inputs
                            if vi.name not in init_names]
        self.output_names = [vi.name for vi in self.graph.outputs]
        unsupported = sorted({
            n.op_type for n in self.graph.nodes
            if n.op_type not in MAPPERS
        })
        if unsupported:
            raise NotImplementedError(
                f"ONNX ops without mappers: {unsupported} "
                f"(supported: {sorted(MAPPERS)})"
            )
        # single-input graphs with a static shape drop straight into
        # Sequential without an explicit input_shape
        if self._input_shape is None and len(self.input_names) == 1:
            vi = next(v for v in self.graph.inputs
                      if v.name == self.input_names[0])
            if vi.shape and all(d is not None for d in vi.shape[1:]):
                self._input_shape = tuple(vi.shape[1:])

    def build(self, input_shape):
        for iname, arr in self._learn.items():
            self.add_weight(iname, arr.shape, _Fixed(arr),
                            trainable=self.trainable)

    def call(self, params, inputs, state=None, training=False, rng=None):
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        assert len(xs) == len(self.input_names), (
            f"expected inputs {self.input_names}, got {len(xs)} arrays"
        )
        env: dict = dict(zip(self.input_names, xs))
        env.update(self._static)
        weights = params if self.trainable else (state or {})
        for name in self._learn:
            env[name] = weights[name]

        for node in self.graph.nodes:
            fn = MAPPERS[node.op_type]
            args = [env[i] if i else None for i in node.inputs]
            out = fn(node.attrs, self._static, *args)
            if isinstance(out, (list, tuple)):
                for oname, o in zip(node.outputs, out):
                    env[oname] = o
            else:
                env[node.outputs[0]] = out

        outs = [env[o] for o in self.output_names]
        result = outs if len(outs) > 1 else outs[0]
        if self.stateful:  # protocol: stateful call returns (out, state)
            return result, state
        return result

    @property
    def stateful(self):
        return not self.trainable

    def init_state(self):
        if self.trainable:
            return super().init_state()
        return {k: jnp.asarray(v) for k, v in self._learn.items()}

    def compute_output_shape(self, input_shape):
        vi = self.graph.outputs[0]
        if vi.shape:
            return tuple(vi.shape)
        raise ValueError("onnx graph output shape unknown")


def load_onnx(path_or_bytes, trainable=True) -> OnnxNet:
    """Load an ONNX model file/bytes into an :class:`OnnxNet` (reference
    onnx_loader.py ``OnnxLoader.load_model`` entry)."""
    if isinstance(path_or_bytes, (bytes, bytearray, memoryview)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    return OnnxNet(decode_model(data), trainable=trainable)


from analytics_zoo_tpu.pipeline.api.onnx.export import (  # noqa: E402
    export_onnx,
)

__all__ = ["OnnxNet", "load_onnx", "export_onnx", "proto", "MAPPERS"]
