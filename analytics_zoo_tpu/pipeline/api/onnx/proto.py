"""Minimal ONNX protobuf wire-format codec (no ``onnx`` dependency).

Reference: pyzoo/zoo/pipeline/api/onnx/onnx_loader.py parses models with the
``onnx`` python package; that package is not available in this environment,
so this module reads (and, for tests, writes) the protobuf wire format
directly using the stable ONNX field numbers (onnx/onnx.proto — field ids
are frozen by protobuf compatibility rules).

Only the subset needed to load inference graphs is modeled: ModelProto,
GraphProto, NodeProto, AttributeProto, TensorProto, ValueInfoProto.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np


# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------

def _read_varint(buf, pos):
    result, shift = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out, value):
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _iter_fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer.
    Length-delimited values come back as memoryview slices."""
    pos, n = 0, len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:  # 64-bit
            val = struct.unpack_from("<q", buf, pos)[0]
            pos += 8
        elif wtype == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = bytes(buf[pos:pos + ln])
            pos += ln
        elif wtype == 5:  # 32-bit
            val = struct.unpack_from("<i", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _field(out: bytearray, fnum, wtype):
    _write_varint(out, (fnum << 3) | wtype)


def _put_bytes(out, fnum, data):
    _field(out, fnum, 2)
    _write_varint(out, len(data))
    out.extend(data)


def _put_varint(out, fnum, value):
    _field(out, fnum, 0)
    _write_varint(out, value)


def _packed_or_repeated_ints(val, wtype):
    if wtype == 2:  # packed
        vals, pos = [], 0
        while pos < len(val):
            v, pos = _read_varint(val, pos)
            vals.append(v)
        return vals
    return [val]


def _unzigzag_signed(v, bits=64):
    """Protobuf int64 fields store negatives as 10-byte two's complement
    varints; fold back into Python ints."""
    if v >= (1 << (bits - 1)):
        v -= 1 << bits
    return v


# ---------------------------------------------------------------------------
# ONNX messages (field numbers from onnx.proto)
# ---------------------------------------------------------------------------

# TensorProto.DataType
FLOAT, UINT8, INT8, INT32, INT64, BOOL, DOUBLE = 1, 2, 3, 6, 7, 9, 11
_DTYPES = {FLOAT: np.float32, UINT8: np.uint8, INT8: np.int8,
           INT32: np.int32, INT64: np.int64, BOOL: np.bool_,
           DOUBLE: np.float64}
_NP2ONNX = {np.dtype(np.float32): FLOAT, np.dtype(np.int64): INT64,
            np.dtype(np.int32): INT32, np.dtype(np.float64): DOUBLE,
            np.dtype(np.bool_): BOOL}

# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


@dataclass
class Tensor:
    name: str = ""
    array: np.ndarray | None = None


@dataclass
class Attribute:
    name: str = ""
    value: object = None


@dataclass
class Node:
    op_type: str = ""
    name: str = ""
    inputs: list = field(default_factory=list)
    outputs: list = field(default_factory=list)
    attrs: dict = field(default_factory=dict)


@dataclass
class ValueInfo:
    name: str = ""
    shape: tuple = ()
    elem_type: int = FLOAT


@dataclass
class Graph:
    name: str = ""
    nodes: list = field(default_factory=list)
    initializers: dict = field(default_factory=dict)  # name -> np.ndarray
    inputs: list = field(default_factory=list)        # ValueInfo
    outputs: list = field(default_factory=list)       # ValueInfo


@dataclass
class Model:
    ir_version: int = 8
    opset: int = 13
    graph: Graph = field(default_factory=Graph)


# -- decoding ---------------------------------------------------------------

def _decode_tensor(buf) -> Tensor:
    dims, dtype, raw = [], FLOAT, None
    f32, i32, i64, f64 = [], [], [], []
    name = ""
    for fnum, wtype, val in _iter_fields(buf):
        if fnum == 1:
            dims.extend(_unzigzag_signed(v)
                        for v in _packed_or_repeated_ints(val, wtype))
        elif fnum == 2:
            dtype = val
        elif fnum == 4:
            if wtype == 2:
                f32.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                f32.append(struct.unpack("<f", struct.pack("<i", val))[0])
        elif fnum == 5:
            i32.extend(_packed_or_repeated_ints(val, wtype))
        elif fnum == 7:
            i64.extend(_unzigzag_signed(v)
                       for v in _packed_or_repeated_ints(val, wtype))
        elif fnum == 8:
            name = val.decode()
        elif fnum == 9:
            raw = val
        elif fnum == 10:
            if wtype == 2:
                f64.extend(struct.unpack(f"<{len(val) // 8}d", val))
            else:
                f64.append(struct.unpack("<d", struct.pack("<q", val))[0])
    np_dtype = _DTYPES.get(dtype)
    if np_dtype is None:
        raise ValueError(f"unsupported tensor dtype {dtype} ({name})")
    if raw is not None:
        arr = np.frombuffer(raw, dtype=np_dtype).copy()
    elif f32:
        arr = np.asarray(f32, dtype=np_dtype)
    elif i64:
        arr = np.asarray(i64, dtype=np_dtype)
    elif i32:
        arr = np.asarray(i32, dtype=np_dtype)
    elif f64:
        arr = np.asarray(f64, dtype=np_dtype)
    else:
        arr = np.zeros(0, dtype=np_dtype)
    return Tensor(name, arr.reshape(dims))


def _decode_attribute(buf) -> Attribute:
    a = Attribute()
    atype = None
    ints, floats, strings = [], [], []
    for fnum, wtype, val in _iter_fields(buf):
        if fnum == 1:
            a.name = val.decode()
        elif fnum == 2:
            a.value = struct.unpack("<f", struct.pack("<i", val))[0] \
                if wtype == 5 else val
        elif fnum == 3:
            a.value = _unzigzag_signed(val)
        elif fnum == 4:
            a.value = val.decode()
        elif fnum == 5:
            a.value = _decode_tensor(val).array
        elif fnum == 7:
            if wtype == 2:
                floats.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                floats.append(
                    struct.unpack("<f", struct.pack("<i", val))[0]
                )
        elif fnum == 8:
            ints.extend(_unzigzag_signed(v)
                        for v in _packed_or_repeated_ints(val, wtype))
        elif fnum == 9:
            strings.append(val.decode())
        elif fnum == 20:
            atype = val
    if atype == ATTR_INTS or (ints and a.value is None):
        a.value = ints
    elif atype == ATTR_FLOATS or (floats and a.value is None):
        a.value = floats
    elif atype == ATTR_STRINGS or (strings and a.value is None):
        a.value = strings
    elif a.value is None:
        # proto3 writers omit zero-valued scalar fields; restore the
        # type's zero default so e.g. Gather axis=0 decodes as 0, not None
        a.value = {ATTR_INT: 0, ATTR_FLOAT: 0.0,
                   ATTR_STRING: ""}.get(atype)
    return a


def _decode_node(buf) -> Node:
    n = Node()
    for fnum, _, val in _iter_fields(buf):
        if fnum == 1:
            n.inputs.append(val.decode())
        elif fnum == 2:
            n.outputs.append(val.decode())
        elif fnum == 3:
            n.name = val.decode()
        elif fnum == 4:
            n.op_type = val.decode()
        elif fnum == 5:
            a = _decode_attribute(val)
            n.attrs[a.name] = a.value
    return n


def _decode_value_info(buf) -> ValueInfo:
    vi = ValueInfo()
    for fnum, _, val in _iter_fields(buf):
        if fnum == 1:
            vi.name = val.decode()
        elif fnum == 2:  # TypeProto
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:  # tensor_type
                    dims = []
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:
                            vi.elem_type = v3
                        elif f3 == 2:  # shape
                            for f4, _, v4 in _iter_fields(v3):
                                if f4 == 1:  # dim
                                    dim_val = None
                                    for f5, _, v5 in _iter_fields(v4):
                                        if f5 == 1:
                                            dim_val = v5
                                    dims.append(dim_val)
                    vi.shape = tuple(dims)
    return vi


def _decode_graph(buf) -> Graph:
    g = Graph()
    for fnum, _, val in _iter_fields(buf):
        if fnum == 1:
            g.nodes.append(_decode_node(val))
        elif fnum == 2:
            g.name = val.decode()
        elif fnum == 5:
            t = _decode_tensor(val)
            g.initializers[t.name] = t.array
        elif fnum == 11:
            g.inputs.append(_decode_value_info(val))
        elif fnum == 12:
            g.outputs.append(_decode_value_info(val))
    return g


def decode_model(data: bytes) -> Model:
    m = Model()
    for fnum, _, val in _iter_fields(memoryview(data)):
        if fnum == 1:
            m.ir_version = val
        elif fnum == 7:
            m.graph = _decode_graph(val)
        elif fnum == 8:  # opset_import
            for f2, _, v2 in _iter_fields(val):
                if f2 == 2:
                    m.opset = _unzigzag_signed(v2)
    return m


# -- encoding (used by the test suite to fabricate models) ------------------

def _encode_tensor(name, arr) -> bytes:
    out = bytearray()
    arr = np.asarray(arr)
    for d in arr.shape:
        _put_varint(out, 1, d)
    _put_varint(out, 2, _NP2ONNX[arr.dtype])
    _put_bytes(out, 8, name.encode())
    _put_bytes(out, 9, np.ascontiguousarray(arr).tobytes())
    return bytes(out)


def _encode_attribute(name, value) -> bytes:
    out = bytearray()
    _put_bytes(out, 1, name.encode())
    if isinstance(value, bool):
        _put_varint(out, 3, int(value))
        _put_varint(out, 20, ATTR_INT)
    elif isinstance(value, int):
        _put_varint(out, 3, value & ((1 << 64) - 1))
        _put_varint(out, 20, ATTR_INT)
    elif isinstance(value, float):
        _field(out, 2, 5)
        out.extend(struct.pack("<f", value))
        _put_varint(out, 20, ATTR_FLOAT)
    elif isinstance(value, str):
        _put_bytes(out, 4, value.encode())
        _put_varint(out, 20, ATTR_STRING)
    elif isinstance(value, np.ndarray):
        _put_bytes(out, 5, _encode_tensor("", value))
        _put_varint(out, 20, ATTR_TENSOR)
    elif isinstance(value, (list, tuple)) and value and \
            isinstance(value[0], float):
        for v in value:
            _field(out, 7, 5)
            out.extend(struct.pack("<f", v))
        _put_varint(out, 20, ATTR_FLOATS)
    elif isinstance(value, (list, tuple)):
        for v in value:
            _put_varint(out, 8, int(v) & ((1 << 64) - 1))
        _put_varint(out, 20, ATTR_INTS)
    else:
        raise TypeError(f"attribute {name}: {type(value)}")
    return bytes(out)


def _encode_node(node: Node) -> bytes:
    out = bytearray()
    for i in node.inputs:
        _put_bytes(out, 1, i.encode())
    for o in node.outputs:
        _put_bytes(out, 2, o.encode())
    if node.name:
        _put_bytes(out, 3, node.name.encode())
    _put_bytes(out, 4, node.op_type.encode())
    for k, v in node.attrs.items():
        _put_bytes(out, 5, _encode_attribute(k, v))
    return bytes(out)


def _encode_value_info(vi: ValueInfo) -> bytes:
    shape = bytearray()
    for d in vi.shape:
        dim = bytearray()
        if d is not None:
            _put_varint(dim, 1, d)
        _put_bytes(shape, 1, bytes(dim))
    ttype = bytearray()
    _put_varint(ttype, 1, vi.elem_type)
    _put_bytes(ttype, 2, bytes(shape))
    tproto = bytearray()
    _put_bytes(tproto, 1, bytes(ttype))
    out = bytearray()
    _put_bytes(out, 1, vi.name.encode())
    _put_bytes(out, 2, bytes(tproto))
    return bytes(out)


def encode_model(model: Model) -> bytes:
    g = bytearray()
    for n in model.graph.nodes:
        _put_bytes(g, 1, _encode_node(n))
    _put_bytes(g, 2, (model.graph.name or "graph").encode())
    for name, arr in model.graph.initializers.items():
        _put_bytes(g, 5, _encode_tensor(name, arr))
    for vi in model.graph.inputs:
        _put_bytes(g, 11, _encode_value_info(vi))
    for vi in model.graph.outputs:
        _put_bytes(g, 12, _encode_value_info(vi))

    out = bytearray()
    _put_varint(out, 1, model.ir_version)
    opset = bytearray()
    _put_varint(opset, 2, model.opset)
    _put_bytes(out, 8, bytes(opset))
    _put_bytes(out, 7, bytes(g))
    return bytes(out)
