"""ONNX op → jax mappers.

Reference: pyzoo/zoo/pipeline/api/onnx/mapper/*.py — 44 OperatorMapper
subclasses converting ONNX nodes to zoo keras layers.  Here each mapper is
a pure function ``fn(attrs, consts, *args) -> output(s)`` over jnp arrays:
the whole graph stays one jit-compiled XLA program, and ONNX's NCHW conv
layout is expressed directly via conv dimension_numbers (XLA re-lays out
for the TPU; no transposes inserted by hand).

``consts`` maps input names to *static* numpy values (initializers and
Constant outputs) for ops whose ONNX inputs are really attributes
(Reshape shape, Slice starts/ends, Pad pads...).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

MAPPERS: dict = {}


def register(*op_types):
    def deco(fn):
        for op in op_types:
            MAPPERS[op] = fn
        return fn
    return deco


def _pair(v, n=2, default=1):
    if v is None:
        return (default,) * n
    v = list(v)
    return tuple(v[:n]) if len(v) >= n else tuple(v) * n


def _conv_padding(attrs, spatial_rank, in_sizes=None, kernel=None,
                  strides=None, dilations=None):
    pads = attrs.get("pads")
    auto = attrs.get("auto_pad", "NOTSET")
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        # explicit per-dim pads so SAME_LOWER's extra-pad-at-the-start
        # convention is honored (jax 'SAME' always pads at the end)
        out = []
        strides = strides or (1,) * spatial_rank
        dilations = dilations or (1,) * spatial_rank
        for size, k, s, d in zip(in_sizes, kernel, strides, dilations):
            eff = (k - 1) * d + 1
            n_out = -(-size // s)  # ceil
            total = max(0, (n_out - 1) * s + eff - size)
            small, big = total // 2, total - total // 2
            out.append((big, small) if auto == "SAME_LOWER"
                       else (small, big))
        return out
    if pads is None:
        return [(0, 0)] * spatial_rank
    # onnx pads = [x1_begin, x2_begin, ..., x1_end, x2_end, ...]
    return [(int(pads[i]), int(pads[i + spatial_rank]))
            for i in range(spatial_rank)]


# ---------------------------------------------------------------------------
# math / activations
# ---------------------------------------------------------------------------

@register("Add")
def _add(attrs, consts, a, b):
    return a + b


@register("Sub")
def _sub(attrs, consts, a, b):
    return a - b


@register("Mul")
def _mul(attrs, consts, a, b):
    return a * b


@register("Div")
def _div(attrs, consts, a, b):
    return a / b


@register("Pow")
def _pow(attrs, consts, a, b):
    return jnp.power(a, b)


@register("Neg")
def _neg(attrs, consts, a):
    return -a


@register("Abs")
def _abs(attrs, consts, a):
    return jnp.abs(a)


@register("Exp")
def _exp(attrs, consts, a):
    return jnp.exp(a)


@register("Log")
def _log(attrs, consts, a):
    return jnp.log(a)


@register("Sqrt")
def _sqrt(attrs, consts, a):
    return jnp.sqrt(a)


@register("Reciprocal")
def _recip(attrs, consts, a):
    return 1.0 / a


@register("Relu")
def _relu(attrs, consts, a):
    return jax.nn.relu(a)


@register("LeakyRelu")
def _leaky(attrs, consts, a):
    return jnp.where(a >= 0, a, attrs.get("alpha", 0.01) * a)


@register("Elu")
def _elu(attrs, consts, a):
    alpha = attrs.get("alpha", 1.0)
    return jnp.where(a >= 0, a, alpha * jnp.expm1(a))


@register("Sigmoid")
def _sigmoid(attrs, consts, a):
    return jax.nn.sigmoid(a)


@register("HardSigmoid")
def _hard_sigmoid(attrs, consts, a):
    alpha = attrs.get("alpha", 0.2)
    beta = attrs.get("beta", 0.5)
    return jnp.clip(alpha * a + beta, 0.0, 1.0)


@register("Tanh")
def _tanh(attrs, consts, a):
    return jnp.tanh(a)


def _softmax_like(fn):
    def mapper(attrs, consts, a):
        opset = consts.get("__opset__", 13)
        if opset >= 13:
            return fn(a, axis=attrs.get("axis", -1))
        # pre-13: coerce to 2D at `axis` (default 1), softmax the trailing
        # flattened block, restore the shape
        axis = attrs.get("axis", 1)
        axis = axis % a.ndim
        lead = int(np.prod(a.shape[:axis])) if axis else 1
        flat = a.reshape(lead, -1)
        return fn(flat, axis=-1).reshape(a.shape)
    return mapper


MAPPERS["Softmax"] = _softmax_like(jax.nn.softmax)
MAPPERS["LogSoftmax"] = _softmax_like(jax.nn.log_softmax)


@register("Softplus")
def _softplus(attrs, consts, a):
    return jax.nn.softplus(a)


@register("Clip")
def _clip(attrs, consts, a, *bounds):
    lo = bounds[0] if len(bounds) > 0 else attrs.get("min")
    hi = bounds[1] if len(bounds) > 1 else attrs.get("max")
    return jnp.clip(a, lo, hi)


@register("Erf")
def _erf(attrs, consts, a):
    return jax.scipy.special.erf(a)


@register("Max")
def _max(attrs, consts, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = jnp.maximum(out, x)
    return out


@register("Min")
def _min(attrs, consts, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = jnp.minimum(out, x)
    return out


@register("Sum")
def _sum(attrs, consts, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


# ---------------------------------------------------------------------------
# linear algebra
# ---------------------------------------------------------------------------

@register("MatMul")
def _matmul(attrs, consts, a, b):
    return a @ b


@register("Gemm")
def _gemm(attrs, consts, a, b, c=None):
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    if attrs.get("transA", 0):
        a = a.T
    if attrs.get("transB", 0):
        b = b.T
    y = alpha * (a @ b)
    if c is not None:
        y = y + beta * c
    return y


# ---------------------------------------------------------------------------
# convolution / pooling (NCHW, per ONNX spec)
# ---------------------------------------------------------------------------

@register("Conv")
def _conv(attrs, consts, x, w, b=None):
    rank = x.ndim - 2
    strides = _pair(attrs.get("strides"), rank)
    dilations = _pair(attrs.get("dilations"), rank)
    groups = int(attrs.get("group", 1))
    dn = {1: ("NCH", "OIH", "NCH"),
          2: ("NCHW", "OIHW", "NCHW"),
          3: ("NCDHW", "OIDHW", "NCDHW")}[rank]
    y = lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=_conv_padding(attrs, rank, x.shape[2:], w.shape[2:],
                              strides, dilations),
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups,
    )
    if b is not None:
        y = y + b.reshape((1, -1) + (1,) * rank)
    return y


@register("ConvTranspose")
def _conv_transpose(attrs, consts, x, w, b=None):
    rank = x.ndim - 2
    if int(attrs.get("group", 1)) != 1:
        raise NotImplementedError("ConvTranspose: group > 1")
    if attrs.get("output_shape") is not None:
        raise NotImplementedError(
            "ConvTranspose: explicit output_shape (use pads/output_padding)"
        )
    strides = _pair(attrs.get("strides"), rank)
    dilations = _pair(attrs.get("dilations"), rank)
    out_pad = _pair(attrs.get("output_padding"), rank, default=0)
    pads = _conv_padding(attrs, rank, x.shape[2:], w.shape[2:], strides,
                         dilations)
    # onnx deconv pads trim the output; conv_transpose takes them as
    # reduced input-side padding.  output_padding extends the end.  The
    # onnx kernel is (in, out, *k) correlation-oriented: flip the spatial
    # dims and run a plain (non-transpose_kernel) IO conv_transpose —
    # verified element-exact against torch conv_transpose2d.
    k = [(ki - 1) * d + 1 for ki, d in zip(w.shape[2:], dilations)]
    padding = [(ki - 1 - lo, ki - 1 - hi + op)
               for ki, (lo, hi), op in zip(k, pads, out_pad)]
    dn = {2: ("NCHW", "IOHW", "NCHW")}[rank]
    w_flipped = jnp.flip(w, axis=tuple(range(2, w.ndim)))
    y = lax.conv_transpose(
        x, w_flipped, strides=strides, padding=padding,
        rhs_dilation=dilations, dimension_numbers=dn,
        transpose_kernel=False,
    )
    if b is not None:
        y = y + b.reshape((1, -1) + (1,) * rank)
    return y


def _pool(x, attrs, reducer, init, is_avg=False):
    rank = x.ndim - 2
    k = tuple(attrs["kernel_shape"])
    strides = _pair(attrs.get("strides"), rank)
    dilations = _pair(attrs.get("dilations"), rank)
    pads = _conv_padding(attrs, rank, x.shape[2:], k, strides, dilations)
    if attrs.get("ceil_mode", 0):
        # extend the end padding so reduce_window emits the ceil-size output
        new = []
        for size, ki, s, d, (lo, hi) in zip(x.shape[2:], k, strides,
                                            dilations, pads):
            eff = (ki - 1) * d + 1
            n_ceil = -(-(size + lo + hi - eff) // s) + 1
            needed = (n_ceil - 1) * s + eff - (size + lo + hi)
            new.append((lo, hi + max(0, needed)))
        pads = new
    full_pads = [(0, 0), (0, 0)] + list(pads)
    window = (1, 1) + k
    strd = (1, 1) + strides
    dil = (1, 1) + dilations
    y = lax.reduce_window(x, init, reducer, window, strd, full_pads,
                          window_dilation=dil)
    if is_avg:
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strd,
                                full_pads, window_dilation=dil)
        if attrs.get("count_include_pad", 0):
            cnt = jnp.full_like(cnt, float(np.prod(k)))
        y = y / cnt
    return y


@register("MaxPool")
def _maxpool(attrs, consts, x):
    return _pool(x, attrs, lax.max, -jnp.inf)


@register("AveragePool")
def _avgpool(attrs, consts, x):
    return _pool(x, attrs, lax.add, 0.0, is_avg=True)


@register("GlobalAveragePool")
def _gap(attrs, consts, x):
    axes = tuple(range(2, x.ndim))
    return jnp.mean(x, axis=axes, keepdims=True)


@register("GlobalMaxPool")
def _gmp(attrs, consts, x):
    axes = tuple(range(2, x.ndim))
    return jnp.max(x, axis=axes, keepdims=True)


@register("BatchNormalization")
def _batchnorm(attrs, consts, x, scale, bias, mean, var):
    eps = attrs.get("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = lax.rsqrt(var + eps)
    return (x - mean.reshape(shape)) * (scale * inv).reshape(shape) \
        + bias.reshape(shape)


@register("InstanceNormalization")
def _instancenorm(attrs, consts, x, scale, bias):
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * lax.rsqrt(var + eps) * scale.reshape(shape) \
        + bias.reshape(shape)


@register("LRN")
def _lrn(attrs, consts, x):
    size = int(attrs["size"])
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    k = attrs.get("bias", 1.0)
    lo = (size - 1) // 2
    sq = jnp.square(x)
    window = lax.reduce_window(
        sq, 0.0, lax.add,
        (1, size) + (1,) * (x.ndim - 2),
        (1,) * x.ndim,
        [(0, 0), (lo, size - 1 - lo)] + [(0, 0)] * (x.ndim - 2),
    )
    return x / jnp.power(k + alpha / size * window, beta)


@register("Dropout", "Identity")
def _identity(attrs, consts, x, *rest):
    return x


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------

@register("Reshape")
def _reshape(attrs, consts, x, shape=None):
    if shape is None:
        shape_vals = attrs.get("shape")  # opset 1
    elif isinstance(shape, np.ndarray):
        shape_vals = [int(s) for s in shape]
    else:
        raise ValueError(
            "Reshape: the shape input must be a graph constant "
            "(initializer/Constant output) — dynamic shapes can't be jitted"
        )
    out_shape = [x.shape[i] if s == 0 else int(s)
                 for i, s in enumerate(shape_vals)]
    return jnp.reshape(x, out_shape)


@register("Flatten")
def _flatten(attrs, consts, x):
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return jnp.reshape(x, (lead, -1))


@register("Transpose")
def _transpose(attrs, consts, x):
    perm = attrs.get("perm")
    if perm is None:
        perm = tuple(reversed(range(x.ndim)))
    return jnp.transpose(x, perm)


@register("Concat")
def _concat(attrs, consts, *xs):
    return jnp.concatenate(xs, axis=attrs.get("axis", 0))


@register("Squeeze")
def _squeeze(attrs, consts, x, axes=None):
    ax = attrs.get("axes")
    if isinstance(axes, np.ndarray):
        ax = [int(a) for a in axes]
    return jnp.squeeze(x, axis=tuple(ax) if ax else None)


@register("Unsqueeze")
def _unsqueeze(attrs, consts, x, axes=None):
    ax = attrs.get("axes")
    if isinstance(axes, np.ndarray):
        ax = [int(a) for a in axes]
    for a in sorted(ax):
        x = jnp.expand_dims(x, a)
    return x


@register("Gather")
def _gather(attrs, consts, x, indices):
    return jnp.take(x, indices.astype(jnp.int32),
                    axis=attrs.get("axis", 0))


@register("Slice")
def _slice(attrs, consts, x, *args):
    if args:  # opset >= 10: starts/ends/axes/steps as const inputs
        vals = [None if a is None else [int(v) for v in np.asarray(a)]
                for a in args]
        starts, ends = vals[0], vals[1]
        axes = vals[2] if len(vals) > 2 and vals[2] is not None \
            else list(range(len(starts)))
        steps = vals[3] if len(vals) > 3 and vals[3] is not None \
            else [1] * len(starts)
    else:  # opset 1: attributes
        starts = attrs["starts"]
        ends = attrs["ends"]
        axes = attrs.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    idx = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        idx[a] = slice(s, None if e >= x.shape[a] and st > 0 else e, st)
    return x[tuple(idx)]


@register("Split")
def _split(attrs, consts, x, split=None):
    axis = attrs.get("axis", 0)
    parts = attrs.get("split")
    if isinstance(split, np.ndarray):
        parts = [int(s) for s in split]
    if parts is None:
        raise ValueError("Split: missing split sizes")
    bounds = np.cumsum(parts)[:-1]
    return list(jnp.split(x, bounds, axis=axis))


@register("Pad")
def _pad(attrs, consts, x, pads=None, value=None):
    p = attrs.get("pads")
    if isinstance(pads, np.ndarray):
        p = [int(v) for v in pads]
    mode = attrs.get("mode", "constant")
    half = len(p) // 2
    widths = [(p[i], p[i + half]) for i in range(half)]
    cval = float(np.asarray(value)) if value is not None \
        else attrs.get("value", 0.0)
    if mode == "constant":
        return jnp.pad(x, widths, constant_values=cval)
    return jnp.pad(x, widths, mode={"reflect": "reflect",
                                    "edge": "edge"}[mode])


@register("Shape")
def _shape(attrs, consts, x):
    return jnp.asarray(x.shape, dtype=jnp.int64)


@register("Cast")
def _cast(attrs, consts, x):
    from analytics_zoo_tpu.pipeline.api.onnx.proto import _DTYPES

    return x.astype(_DTYPES[int(attrs["to"])])


@register("Expand")
def _expand(attrs, consts, x, shape):
    target = [int(s) for s in np.asarray(shape)]
    # onnx Expand: numpy-style right-aligned broadcast; either side may
    # have more dims, and target dims of 1 keep the input size
    ndim = max(x.ndim, len(target))
    xs = (1,) * (ndim - x.ndim) + tuple(x.shape)
    ts = [1] * (ndim - len(target)) + target
    out = [max(t, s) for t, s in zip(ts, xs)]
    return jnp.broadcast_to(x, out)


@register("Tile")
def _tile(attrs, consts, x, repeats):
    return jnp.tile(x, [int(r) for r in np.asarray(repeats)])


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _reduce(fn):
    def mapper(attrs, consts, x, axes_in=None):
        axes = attrs.get("axes")
        if isinstance(axes_in, np.ndarray):
            axes = [int(a) for a in axes_in]
        keep = bool(attrs.get("keepdims", 1))
        ax = tuple(axes) if axes else None
        return fn(x, axis=ax, keepdims=keep)
    return mapper


MAPPERS["ReduceMean"] = _reduce(jnp.mean)
MAPPERS["ReduceSum"] = _reduce(jnp.sum)
MAPPERS["ReduceMax"] = _reduce(jnp.max)
MAPPERS["ReduceMin"] = _reduce(jnp.min)
MAPPERS["ReduceProd"] = _reduce(jnp.prod)


@register("ArgMax")
def _argmax(attrs, consts, x):
    axis = attrs.get("axis", 0)
    keep = bool(attrs.get("keepdims", 1))
    out = jnp.argmax(x, axis=axis)
    return jnp.expand_dims(out, axis) if keep else out


@register("Constant")
def _constant(attrs, consts):
    # returns numpy (not jnp) so the interpreter keeps it static and
    # shape-consuming ops (Reshape/Slice...) can read concrete values
    return np.asarray(attrs["value"])
