"""Keras-2-style layer API.

Reference: pipeline/api/keras2/layers/*.scala and
pyzoo/zoo/pipeline/api/keras2/layers/*.py — a 20-layer API variant that
renames Keras-1 arguments to their Keras-2 forms (``filters``/
``kernel_size``/``strides``/``padding``, ``units``, ``rate``, ``use_bias``,
``kernel_initializer``) and adds the functional merge layers
Maximum/Minimum/Average.

Each class here is a thin adapter over the Keras-1 implementation in
:mod:`analytics_zoo_tpu.pipeline.api.keras.layers` — identical math, new
surface.  Unlike the reference (whose keras2 Conv2D defaults to
``data_format="channels_first"``), everything stays channels-last: that is
the only layout the TPU build supports, and the adapters validate it.
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1


def _check_channels_last(data_format):
    if data_format not in (None, "channels_last"):
        raise ValueError(
            "the TPU build is channels-last only (NHWC); got "
            f"data_format={data_format!r}"
        )


def _check_zero_bias(bias_initializer, use_bias=True):
    if not use_bias:
        return  # no bias exists; any initializer is vacuously fine
    if bias_initializer is None:
        return
    name = (bias_initializer if isinstance(bias_initializer, str)
            else type(bias_initializer).__name__)
    if name.lower() not in ("zero", "zeros"):
        raise ValueError(
            "only zero bias initialization is supported (the keras-1 "
            f"implementation zero-inits bias); got {bias_initializer!r}"
        )


class Dense(k1.Dense):
    """keras2 Dense: ``units``/``use_bias``/``kernel_initializer``
    (reference keras2/layers/Dense.scala)."""

    def __init__(self, units, activation=None, use_bias=True,
                 kernel_initializer="glorot_uniform",
                 bias_initializer="zero", input_shape=None, name=None,
                 **kwargs):
        _check_zero_bias(bias_initializer, use_bias)
        super().__init__(units, init=kernel_initializer,
                         activation=activation, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class Activation(k1.Activation):
    """keras2 Activation (reference keras2/layers/Activation.scala)."""


class Dropout(k1.Dropout):
    """keras2 Dropout: ``rate`` (reference keras2/layers/Dropout.scala)."""

    def __init__(self, rate, input_shape=None, name=None, **kwargs):
        super().__init__(rate, input_shape=input_shape, name=name, **kwargs)


class Flatten(k1.Flatten):
    """keras2 Flatten (reference keras2/layers/Flatten.scala)."""

    def __init__(self, data_format=None, input_shape=None, name=None,
                 **kwargs):
        _check_channels_last(data_format)
        super().__init__(input_shape=input_shape, name=name, **kwargs)


class Conv1D(k1.Convolution1D):
    """keras2 Conv1D: ``filters``/``kernel_size``/``strides``/``padding``
    (reference keras2/layers/Conv1D.scala)."""

    def __init__(self, filters, kernel_size, strides=1, padding="valid",
                 activation=None, use_bias=True,
                 kernel_initializer="glorot_uniform",
                 bias_initializer="zero", input_shape=None, name=None,
                 **kwargs):
        _check_zero_bias(bias_initializer, use_bias)
        super().__init__(filters, kernel_size, subsample_length=strides,
                         border_mode=padding, activation=activation,
                         bias=use_bias, init=kernel_initializer,
                         input_shape=input_shape, name=name, **kwargs)


class Conv2D(k1.Convolution2D):
    """keras2 Conv2D (reference keras2/layers/Conv2D.scala).  NHWC only."""

    def __init__(self, filters, kernel_size, strides=(1, 1),
                 padding="valid", data_format=None, activation=None,
                 use_bias=True, kernel_initializer="glorot_uniform",
                 bias_initializer="zero", input_shape=None, name=None,
                 **kwargs):
        _check_channels_last(data_format)
        _check_zero_bias(bias_initializer, use_bias)
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        super().__init__(filters, kernel_size[0], kernel_size[1],
                         subsample=strides, border_mode=padding,
                         activation=activation, bias=use_bias,
                         init=kernel_initializer, input_shape=input_shape,
                         name=name, **kwargs)


class Cropping1D(k1.Cropping1D):
    """keras2 Cropping1D (reference keras2/layers/Cropping1D.scala)."""


class LocallyConnected1D(k1.LocallyConnected1D):
    """keras2 LocallyConnected1D (reference
    keras2/layers/LocallyConnected1D.scala)."""

    def __init__(self, filters, kernel_size, strides=1, activation=None,
                 use_bias=True, kernel_initializer="glorot_uniform",
                 input_shape=None, name=None, **kwargs):
        super().__init__(filters, kernel_size, subsample_length=strides,
                         activation=activation, bias=use_bias,
                         init=kernel_initializer, input_shape=input_shape,
                         name=name, **kwargs)


class MaxPooling1D(k1.MaxPooling1D):
    """keras2 MaxPooling1D: ``pool_size``/``strides``/``padding``
    (reference keras2/layers/MaxPooling1D.scala)."""

    def __init__(self, pool_size=2, strides=None, padding="valid",
                 input_shape=None, name=None, **kwargs):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, input_shape=input_shape,
                         name=name, **kwargs)


class AveragePooling1D(k1.AveragePooling1D):
    """keras2 AveragePooling1D (reference
    keras2/layers/AveragePooling1D.scala)."""

    def __init__(self, pool_size=2, strides=None, padding="valid",
                 input_shape=None, name=None, **kwargs):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, input_shape=input_shape,
                         name=name, **kwargs)


def _global_pool(base):
    class _G(base):
        def __init__(self, data_format=None, input_shape=None, name=None,
                     **kwargs):
            _check_channels_last(data_format)
            super().__init__(input_shape=input_shape, name=name, **kwargs)

    # both names must point at the module-level alias or pickle (used by
    # KerasNet.save) cannot resolve the factory-local class
    _G.__name__ = base.__name__
    _G.__qualname__ = base.__name__
    return _G


GlobalAveragePooling1D = _global_pool(k1.GlobalAveragePooling1D)
GlobalAveragePooling2D = _global_pool(k1.GlobalAveragePooling2D)
GlobalAveragePooling3D = _global_pool(k1.GlobalAveragePooling3D)
GlobalMaxPooling1D = _global_pool(k1.GlobalMaxPooling1D)
GlobalMaxPooling2D = _global_pool(k1.GlobalMaxPooling2D)
GlobalMaxPooling3D = _global_pool(k1.GlobalMaxPooling3D)


class Softmax(k1.Softmax):
    """keras2 Softmax layer (reference keras2/layers/Softmax.scala)."""


class _FunctionalMerge(k1.Merge):
    """Maximum/Minimum/Average (reference keras2/layers/{Maximum,Minimum,
    Average}.scala): element-wise merges of a list of same-shape inputs."""

    _mode = "max"

    def __init__(self, input_shape=None, name=None, **kwargs):
        super().__init__(mode=self._mode, input_shape=input_shape,
                         name=name, **kwargs)


class Maximum(_FunctionalMerge):
    _mode = "max"


class Minimum(_FunctionalMerge):
    _mode = "min"


class Average(_FunctionalMerge):
    _mode = "ave"


def maximum(inputs, **kwargs):
    """Functional form (reference keras2 merge helpers)."""
    return Maximum(**kwargs)(inputs)


def minimum(inputs, **kwargs):
    return Minimum(**kwargs)(inputs)


def average(inputs, **kwargs):
    return Average(**kwargs)(inputs)
