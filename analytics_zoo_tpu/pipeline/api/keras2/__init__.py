"""Keras-2-style API variant (reference pipeline/api/keras2 — Scala
keras2/layers/*.scala and pyzoo/zoo/pipeline/api/keras2).

Models/topology are shared with the Keras-1 engine; only the layer
constructor surface differs.
"""

from analytics_zoo_tpu.pipeline.api.keras.topology import (  # noqa: F401
    Model,
    Sequential,
)
from analytics_zoo_tpu.pipeline.api.keras2 import layers  # noqa: F401
