"""Tensor-manipulation and scalar-op layers.

Reference: pipeline/api/keras/layers/{AddConstant,MulConstant,Mul,CAdd,CMul,
Scale,Negative,Power,Sqrt,Square,Exp,Log,BinaryThreshold,Threshold,HardShrink,
SoftShrink,HardTanh,RReLU,Softmax,GaussianSampler,GetShape,Expand,Narrow,Max,
SelectTable,SplitTensor,LRN2D,ResizeBilinear}.scala — thin BigDL module
wrappers.  Here each is a pure jnp function (XLA fuses them into neighbouring
matmuls/convs for free); the handful with weights (CAdd/CMul/Scale/Mul) carry
them in the params pytree.

All axis arguments follow the reference's Keras-1 convention: dims count the
batch axis (dim 0 = batch) unless noted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


# ---------------------------------------------------------------------------
# scalar / elementwise math
# ---------------------------------------------------------------------------

class AddConstant(Layer):
    """y = x + constant (reference AddConstant.scala)."""

    def __init__(self, constant_scalar, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.constant = float(constant_scalar)
        self._config = dict(constant_scalar=self.constant)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs + self.constant


class MulConstant(Layer):
    """y = x * constant (reference MulConstant.scala)."""

    def __init__(self, constant_scalar, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.constant = float(constant_scalar)
        self._config = dict(constant_scalar=self.constant)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs * self.constant


class Negative(Layer):
    """y = -x (reference Negative.scala)."""

    def call(self, params, inputs, state=None, training=False, rng=None):
        return -inputs


class Power(Layer):
    """y = (shift + scale * x) ** power (reference Power.scala)."""

    def __init__(self, power, scale=1.0, shift=0.0, input_shape=None,
                 name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.power = float(power)
        self.scale = float(scale)
        self.shift = float(shift)
        self._config = dict(power=power, scale=scale, shift=shift)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.power(self.shift + self.scale * inputs, self.power)


class Sqrt(Layer):
    """Element-wise sqrt (reference Sqrt.scala)."""

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.sqrt(inputs)


class Square(Layer):
    """Element-wise square (reference Square.scala)."""

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.square(inputs)


class Exp(Layer):
    """Element-wise exp (reference Exp.scala)."""

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.exp(inputs)


class Log(Layer):
    """Element-wise natural log (reference Log.scala)."""

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.log(inputs)


# ---------------------------------------------------------------------------
# thresholding activations
# ---------------------------------------------------------------------------

class BinaryThreshold(Layer):
    """1 where x > th else 0 (reference BinaryThreshold.scala)."""

    def __init__(self, th=1e-6, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.th = float(th)
        self._config = dict(th=self.th)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return (inputs > self.th).astype(inputs.dtype)


class Threshold(Layer):
    """x where x > th else v (reference Threshold.scala)."""

    def __init__(self, th=1e-6, v=0.0, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.th = float(th)
        self.v = float(v)
        self._config = dict(th=self.th, v=self.v)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.where(inputs > self.th, inputs, self.v)


class HardShrink(Layer):
    """x where |x| > lambda else 0 (reference HardShrink.scala)."""

    def __init__(self, value=0.5, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.value = float(value)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.where(jnp.abs(inputs) > self.value, inputs, 0.0)


class SoftShrink(Layer):
    """sign(x) * max(|x| - lambda, 0) (reference SoftShrink.scala)."""

    def __init__(self, value=0.5, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.value = float(value)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.sign(inputs) * jnp.maximum(jnp.abs(inputs) - self.value,
                                              0.0)


class HardTanh(Layer):
    """clip(x, min, max) (reference HardTanh.scala)."""

    def __init__(self, min_value=-1.0, max_value=1.0, input_shape=None,
                 name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.clip(inputs, self.min_value, self.max_value)


class RReLU(Layer):
    """Randomized leaky ReLU (reference RReLU.scala): negative slope drawn
    from U(lower, upper) per element in training, fixed to the mean slope at
    inference."""

    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, input_shape=None,
                 name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.lower = float(lower)
        self.upper = float(upper)

    def call(self, params, inputs, state=None, training=False, rng=None):
        if training and rng is not None:
            slope = jax.random.uniform(
                rng, inputs.shape, inputs.dtype, self.lower, self.upper
            )
        else:
            slope = (self.lower + self.upper) / 2.0
        return jnp.where(inputs >= 0, inputs, slope * inputs)


class Softmax(Layer):
    """Softmax over the last axis (reference Softmax.scala; 2D/3D inputs)."""

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jax.nn.softmax(inputs, axis=-1)


# ---------------------------------------------------------------------------
# learnable per-channel affine ops
# ---------------------------------------------------------------------------

class CAdd(Layer):
    """Learnable per-element bias of shape ``size``, broadcast-added
    (reference CAdd.scala).  ``size`` excludes the batch dim."""

    def __init__(self, size, init="zero", input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.size = tuple(int(s) for s in size)
        self.init = init
        self._config = dict(size=self.size)

    def build(self, input_shape):
        self.add_weight("bias", self.size, self.init)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs + params["bias"]


class CMul(Layer):
    """Learnable per-element scale of shape ``size`` (reference CMul.scala)."""

    def __init__(self, size, init="one", input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.size = tuple(int(s) for s in size)
        self.init = init
        self._config = dict(size=self.size)

    def build(self, input_shape):
        self.add_weight("weight", self.size, self.init)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs * params["weight"]


class Scale(Layer):
    """CMul then CAdd with weights of shape ``size`` (reference Scale.scala —
    the caffe Scale layer)."""

    def __init__(self, size, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.size = tuple(int(s) for s in size)
        self._config = dict(size=self.size)

    def build(self, input_shape):
        self.add_weight("weight", self.size, "one")
        self.add_weight("bias", self.size, "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs * params["weight"] + params["bias"]


class Mul(Layer):
    """Single learnable scalar multiplier (reference Mul.scala)."""

    def build(self, input_shape):
        self.add_weight("weight", (1,), "uniform")

    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs * params["weight"]


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

class GaussianSampler(Layer):
    """Reparameterised sampler for VAEs (reference GaussianSampler.scala):
    input is the pair ``[mean, log_variance]``; output
    ``mean + eps * exp(log_var / 2)`` with eps ~ N(0, 1) during training and
    the mean at inference."""

    def call(self, params, inputs, state=None, training=False, rng=None):
        mean, log_var = inputs
        if not training or rng is None:
            return mean
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + eps * jnp.exp(log_var * 0.5)

    def compute_output_shape(self, input_shape):
        return input_shape[0]


# ---------------------------------------------------------------------------
# shape / table ops
# ---------------------------------------------------------------------------

class GetShape(Layer):
    """Returns the (static) input shape as an int array (reference
    GetShape.scala).  Under jit shapes are static, so this is a constant."""

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.asarray(inputs.shape, dtype=jnp.int32)

    def compute_output_shape(self, input_shape):
        return (len(input_shape),)


class Expand(Layer):
    """Broadcast singleton dims up to ``shape`` (reference Expand /
    InternalExpand.scala).  ``shape`` excludes the batch dim."""

    def __init__(self, shape, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.target = tuple(int(s) for s in shape)
        self._config = dict(shape=self.target)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.broadcast_to(inputs,
                                (inputs.shape[0],) + self.target)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self.target


class Narrow(Layer):
    """Slice ``length`` elements from ``offset`` along ``dim`` (reference
    Narrow.scala; dim counts the batch axis, dim >= 1 for per-sample
    slicing; length -1 = to the end)."""

    def __init__(self, dim, offset, length=1, input_shape=None, name=None,
                 **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.dim = int(dim)
        self.offset = int(offset)
        self.length = int(length)
        self._config = dict(dim=dim, offset=offset, length=length)

    def call(self, params, inputs, state=None, training=False, rng=None):
        n = inputs.shape[self.dim]
        length = self.length if self.length != -1 else n - self.offset
        idx = [slice(None)] * inputs.ndim
        idx[self.dim] = slice(self.offset, self.offset + length)
        return inputs[tuple(idx)]

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        n = out[self.dim]
        out[self.dim] = (self.length if self.length != -1
                         else n - self.offset)
        return tuple(out)


class Max(Layer):
    """Max over ``dim`` (reference Max.scala); optionally keeps the dim."""

    def __init__(self, dim, keep_dim=False, input_shape=None, name=None,
                 **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.dim = int(dim)
        self.keep_dim = bool(keep_dim)
        self._config = dict(dim=dim, keep_dim=keep_dim)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.max(inputs, axis=self.dim, keepdims=self.keep_dim)

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        if self.keep_dim:
            out[self.dim] = 1
        else:
            del out[self.dim]
        return tuple(out)


class SelectTable(Layer):
    """Select the ``index``-th tensor from a list input (reference
    SelectTable.scala; zero-based here, matching the python front end)."""

    def __init__(self, index, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.index = int(index)
        self._config = dict(index=self.index)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs[self.index]

    def compute_output_shape(self, input_shape):
        return input_shape[self.index]


class SplitTensor(Layer):
    """Split along ``dim`` into ``num_split`` equal tensors (reference
    SplitTensor.scala); returns a list."""

    def __init__(self, dim, num_split, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.dim = int(dim)
        self.num_split = int(num_split)
        self._config = dict(dim=dim, num_split=num_split)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return list(jnp.split(inputs, self.num_split, axis=self.dim))

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        out[self.dim] = out[self.dim] // self.num_split
        return [tuple(out)] * self.num_split


# ---------------------------------------------------------------------------
# image ops
# ---------------------------------------------------------------------------

class LRN2D(Layer):
    """Across-channel local response normalization over NHWC input
    (reference LRN2D.scala): ``x / (k + alpha/n * sum_{local} x^2)^beta``.

    TPU note: expressed as a depthwise window sum via ``reduce_window`` on
    the channel axis — XLA fuses the whole expression; no transpose to NCHW.
    """

    def __init__(self, alpha=1e-4, k=1.0, beta=0.75, n=5, input_shape=None,
                 name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.alpha = float(alpha)
        self.k = float(k)
        self.beta = float(beta)
        self.n = int(n)
        self._config = dict(alpha=alpha, k=k, beta=beta, n=n)

    def call(self, params, inputs, state=None, training=False, rng=None):
        sq = jnp.square(inputs)
        # window for channel i spans [i-(n-1)//2, i+n//2], the caffe/BigDL
        # convention (differs from torch for even n)
        lo = (self.n - 1) // 2
        window = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            window_dimensions=(1, 1, 1, self.n),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0), (lo, self.n - 1 - lo)),
        )
        return inputs / jnp.power(self.k + self.alpha / self.n * window,
                                  self.beta)


class ResizeBilinear(Layer):
    """Bilinear resize of NHWC images to (out_h, out_w) (reference
    ResizeBilinear.scala, which matches TF1 ``resize_bilinear``).

    TF1 coordinate conventions, reproduced exactly:
    - align_corners=False: ASYMMETRIC mapping ``src = dst * in/out``
      (NOT half-pixel-center; jax.image.resize would be half-pixel, which
      produces different numbers — round-1 advisor finding).
    - align_corners=True: grid endpoints at the image corners,
      ``src = dst * (in-1)/(out-1)``.
    """

    def __init__(self, output_height, output_width, align_corners=False,
                 input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.out_h = int(output_height)
        self.out_w = int(output_width)
        self.align_corners = bool(align_corners)
        self._config = dict(output_height=output_height,
                            output_width=output_width,
                            align_corners=self.align_corners)

    def call(self, params, inputs, state=None, training=False, rng=None):
        h, w = inputs.shape[1], inputs.shape[2]

        def coords(out_n, in_n):
            # Per-axis, like TF1: align_corners needs out_n > 1 (the
            # (in-1)/(out-1) mapping); a singleton axis falls back to the
            # asymmetric mapping on THAT axis only.
            if self.align_corners and out_n > 1:
                return jnp.linspace(0.0, in_n - 1.0, out_n)
            return jnp.minimum(jnp.arange(out_n) * (in_n / out_n),
                               in_n - 1.0)

        ys = coords(self.out_h, h)
        xs = coords(self.out_w, w)
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[None, :, None, None].astype(inputs.dtype)
        wx = (xs - x0)[None, None, :, None].astype(inputs.dtype)
        gy0 = inputs[:, y0]
        gy1 = inputs[:, y1]
        top = gy0[:, :, x0] * (1 - wx) + gy0[:, :, x1] * wx
        bot = gy1[:, :, x0] * (1 - wx) + gy1[:, :, x1] * wx
        return top * (1 - wy) + bot * wy

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.out_h, self.out_w, input_shape[3])


class SpaceToDepth(Layer):
    """NHWC space-to-depth: (B, H, W, C) -> (B, H/b, W/b, b*b*C), TF channel
    order.  Beyond the reference (no Scala counterpart): the MXU-friendly
    rearrangement that turns a strided small-channel stem conv into a dense
    unstrided one (e.g. ResNet's 7x7/s2 on C=3 -> 4x4/s1 on C=12), the
    standard TPU ResNet input optimization."""

    def __init__(self, block_size=2, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name, **kw)
        self.block_size = int(block_size)
        self._config = dict(block_size=self.block_size)

    def call(self, params, inputs, state=None, training=False, rng=None):
        b = self.block_size
        n, h, w, c = inputs.shape
        if h % b or w % b:
            raise ValueError(
                f"spatial dims {(h, w)} not divisible by block {b}")
        x = inputs.reshape(n, h // b, b, w // b, b, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(n, h // b, w // b, b * b * c)

    def compute_output_shape(self, input_shape):
        b = self.block_size
        n, h, w, c = input_shape
        if h % b or w % b:
            raise ValueError(
                f"spatial dims {(h, w)} not divisible by block {b}")
        return (n, h // b, w // b, b * b * c)
