"""Embedding layers.

Reference: pipeline/api/keras/layers/Embedding.scala (LookupTable wrapper,
optional pretrained weights + trainable flag), SparseEmbedding.scala,
WordEmbedding (pretrained GloVe loader in the text pipeline).

TPU notes: embedding lookup is ``jnp.take`` — XLA lowers it to a dynamic
gather that stays on-device; the embedding matrix can be sharded over the
``model`` axis for very large vocabularies (hook left in the parallel pkg).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer, get_initializer


class Embedding(Layer):
    """(batch, seq)[int] -> (batch, seq, output_dim).

    Reference Embedding.scala: ``Embedding(inputDim, outputDim, init,
    weights, trainable)``; zero_based indices.
    """

    def __init__(self, input_dim, output_dim, init="uniform", weights=None,
                 trainable=True, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.init = init
        self.pretrained = weights
        self.trainable = trainable
        self._config = dict(input_dim=input_dim, output_dim=output_dim)

    def build(self, input_shape):
        if self.pretrained is not None:
            w = np.asarray(self.pretrained)
            assert w.shape == (self.input_dim, self.output_dim), (
                f"pretrained weights shape {w.shape} != "
                f"{(self.input_dim, self.output_dim)}"
            )
            init = _Pretrained(w)
        else:
            init = self.init
        self.add_weight("embeddings", (self.input_dim, self.output_dim),
                        init, trainable=self.trainable)

    def call(self, params, inputs, state=None, training=False, rng=None):
        table = params.get("embeddings")
        if table is None:  # non-trainable → lives in state
            table = state["embeddings"]
            out = jnp.take(table, inputs.astype(jnp.int32), axis=0)
            return out, state
        return jnp.take(table, inputs.astype(jnp.int32), axis=0)

    @property
    def stateful(self):
        return not self.trainable

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class _Pretrained:
    """Picklable initializer that returns fixed pretrained weights."""

    def __init__(self, w):
        self.w = np.asarray(w)

    def __call__(self, rng, shape, dtype):
        return jnp.asarray(self.w, dtype)
