"""Embedding layers.

Reference: pipeline/api/keras/layers/Embedding.scala (LookupTable wrapper,
optional pretrained weights + trainable flag), SparseEmbedding.scala,
WordEmbedding (pretrained GloVe loader in the text pipeline).

TPU notes: embedding lookup is ``jnp.take`` — XLA lowers it to a dynamic
gather that stays on-device; the embedding matrix can be sharded over the
``model`` axis for very large vocabularies (hook left in the parallel pkg).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer, get_initializer


class Embedding(Layer):
    """(batch, seq)[int] -> (batch, seq, output_dim).

    Reference Embedding.scala: ``Embedding(inputDim, outputDim, init,
    weights, trainable)``; zero_based indices.
    """

    def __init__(self, input_dim, output_dim, init="uniform", weights=None,
                 trainable=True, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.init = init
        self.pretrained = weights
        self.trainable = trainable
        self._config = dict(input_dim=input_dim, output_dim=output_dim)

    def build(self, input_shape):
        if self.pretrained is not None:
            w = np.asarray(self.pretrained)
            assert w.shape == (self.input_dim, self.output_dim), (
                f"pretrained weights shape {w.shape} != "
                f"{(self.input_dim, self.output_dim)}"
            )
            init = _Pretrained(w)
        else:
            init = self.init
        self.add_weight("embeddings", (self.input_dim, self.output_dim),
                        init, trainable=self.trainable)

    def call(self, params, inputs, state=None, training=False, rng=None):
        table = params.get("embeddings")
        if table is None:  # non-trainable → lives in state
            table = state["embeddings"]
            out = jnp.take(table, inputs.astype(jnp.int32), axis=0)
            return out, state
        return jnp.take(table, inputs.astype(jnp.int32), axis=0)

    @property
    def stateful(self):
        return not self.trainable

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class _Pretrained:
    """Picklable initializer that returns fixed pretrained weights."""

    def __init__(self, w):
        self.w = np.asarray(w)

    def __call__(self, rng, shape, dtype):
        return jnp.asarray(self.w, dtype)


class SparseEmbedding(Embedding):
    """Reference SparseEmbedding.scala: an Embedding whose backward produces
    sparse gradient updates.  Under XLA the gradient of ``jnp.take`` is
    already a scatter-add touching only the looked-up rows, so the dense
    Embedding lowering gives the same behavior; kept as a distinct class for
    API parity.
    """


class WordEmbedding(Embedding):
    """Frozen pretrained word embeddings (reference WordEmbedding.scala):
    loads GloVe-format text vectors, maps them through ``word_index``, and
    is non-trainable.

    ``WordEmbedding(embedding_file, word_index, input_length)``; index 0 is
    reserved for padding/unknown (zero vector), matching the reference's
    1-based word ids with a zero row.
    """

    # single-entry parse cache keyed by (path, mtime) so get_word_index()
    # followed by the constructor reads a multi-GB GloVe file once, not
    # twice — size 1 keeps retention bounded
    _vector_cache: dict = {}
    _VECTOR_CACHE_SIZE = 1

    def __init__(self, embedding_file, word_index=None, trainable=False,
                 input_length=None, input_shape=None, name=None, **kwargs):
        vectors, dim = self._load_vectors(embedding_file)
        if word_index is None:
            word_index = {w: i + 1 for i, w in enumerate(sorted(vectors))}
        self.word_index = dict(word_index)
        vocab = max(self.word_index.values()) + 1
        table = np.zeros((vocab, dim), dtype=np.float32)
        hit = 0
        for word, idx in self.word_index.items():
            vec = vectors.get(word)
            if vec is not None and 0 <= idx < vocab:
                table[idx] = vec
                hit += 1
        if input_shape is None and input_length is not None:
            input_shape = (int(input_length),)
        super().__init__(vocab, dim, weights=table, trainable=trainable,
                         input_shape=input_shape, name=name, **kwargs)
        self.n_pretrained = hit

    @staticmethod
    def _load_vectors(path):
        """Parse GloVe/word2vec ``word v1 v2 ...`` text files.

        Robust to the quirks of real embedding dumps: word2vec/fastText
        header lines (``<count> <dim>``) are skipped, and words containing
        spaces (e.g. ``. . .`` in glove.840B) are handled by splitting the
        float suffix off from the right.
        """
        key = None
        try:
            import os as _os

            key = (path, _os.stat(path).st_mtime_ns)
            cached = WordEmbedding._vector_cache.get(key)
            if cached is not None:
                return cached
        except OSError:
            pass

        def float_suffix_len(parts):
            # float-parseable tokens counted from the right; everything
            # before them is the (possibly multi-token) word.
            n = 0
            for tok in reversed(parts[1:]):
                try:
                    float(tok)
                    n += 1
                except ValueError:
                    break
            return n

        vectors, dim = {}, None
        pending = []  # buffered (parts, n_float) until dim is decided
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(" ")
                if len(parts) < 2:
                    continue
                if lineno == 0 and len(parts) == 2:
                    try:  # word2vec header "<vocab> <dim>"
                        int(parts[0]), int(parts[1])
                        continue
                    except ValueError:
                        pass
                n_float = float_suffix_len(parts)
                if n_float == 0:
                    continue
                if dim is None:
                    # A multi-token word whose tail happens to parse as a
                    # float inflates n_float, never deflates it — so the
                    # minimum over a few lines is the true dim.
                    pending.append((parts, n_float))
                    if len(pending) < 10:
                        continue
                    dim = min(n for _, n in pending)
                    rows, pending = pending, []
                else:
                    rows = [(parts, n_float)]
                for p, n in rows:
                    if n < dim:
                        continue
                    vectors[" ".join(p[:-dim])] = np.asarray(
                        p[-dim:], dtype=np.float32
                    )
        if dim is None and pending:  # short file: fewer than 10 data lines
            dim = min(n for _, n in pending)
            for p, n in pending:
                if n >= dim:
                    vectors[" ".join(p[:-dim])] = np.asarray(
                        p[-dim:], dtype=np.float32
                    )
        if dim is None:
            raise ValueError(f"no vectors found in {path}")
        if key is not None:
            cache = WordEmbedding._vector_cache
            while len(cache) >= WordEmbedding._VECTOR_CACHE_SIZE:
                cache.pop(next(iter(cache)))
            cache[key] = (vectors, dim)
        return vectors, dim

    @staticmethod
    def get_word_index(embedding_file):
        """word -> id (1-based) for every word in the embedding file."""
        vectors, _ = WordEmbedding._load_vectors(embedding_file)
        return {w: i + 1 for i, w in enumerate(sorted(vectors))}
