"""Merge layers (reference pipeline/api/keras/layers/Merge.scala and keras2
Maximum/Minimum/Average): combine a list of inputs by
sum/mul/max/min/ave/concat/dot/cosine.
"""

from __future__ import annotations

import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


class Merge(Layer):
    """Reference Merge.scala: modes sum, mul, max, min, ave, concat, dot,
    cosine."""

    def __init__(self, layers=None, mode="sum", concat_axis=-1,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.mode = mode
        self.concat_axis = concat_axis
        self._config = dict(mode=mode, concat_axis=concat_axis)

    def call(self, params, inputs, state=None, training=False, rng=None):
        xs = list(inputs)
        m = self.mode
        if m == "sum":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if m == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if m == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if m == "min":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.minimum(out, x)
            return out
        if m == "ave":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out / len(xs)
        if m == "concat":
            return jnp.concatenate(xs, axis=self.concat_axis)
        if m == "dot":
            a, b = xs
            return jnp.sum(a * b, axis=-1, keepdims=True)
        if m == "cosine":
            a, b = xs
            an = a / jnp.clip(jnp.linalg.norm(a, axis=-1, keepdims=True),
                              1e-7)
            bn = b / jnp.clip(jnp.linalg.norm(b, axis=-1, keepdims=True),
                              1e-7)
            return jnp.sum(an * bn, axis=-1, keepdims=True)
        raise ValueError(f"unknown merge mode {self.mode!r}")

    def compute_output_shape(self, input_shapes):
        shapes = list(input_shapes)
        if self.mode in ("sum", "mul", "max", "min", "ave"):
            return shapes[0]
        if self.mode == "concat":
            base = list(shapes[0])
            ax = self.concat_axis
            if ax < 0:
                ax += len(base)
            total = 0
            for s in shapes:
                if s[ax] is None:
                    total = None
                    break
                total += s[ax]
            base[ax] = total
            return tuple(base)
        if self.mode in ("dot", "cosine"):
            return (shapes[0][0], 1)
        raise ValueError(self.mode)
