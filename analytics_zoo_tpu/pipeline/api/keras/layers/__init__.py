"""Layer namespace — mirrors reference
pyzoo/zoo/pipeline/api/keras/layers/__init__.py (120 Keras-1 layers)."""

from analytics_zoo_tpu.pipeline.api.keras.engine import (  # noqa: F401
    Input,
    InputLayer,
    Layer,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.core import (  # noqa: F401
    Activation,
    Dense,
    Dropout,
    ExpandDim,
    Flatten,
    GaussianDropout,
    GaussianNoise,
    Highway,
    Identity,
    Masking,
    MaxoutDense,
    Permute,
    RepeatVector,
    Reshape,
    Select,
    SparseDense,
    SpatialDropout1D,
    SpatialDropout2D,
    SpatialDropout3D,
    Squeeze,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import (  # noqa: F401
    AtrousConvolution1D,
    AtrousConvolution2D,
    Convolution1D,
    Convolution2D,
    Convolution3D,
    Cropping1D,
    Cropping2D,
    Cropping3D,
    Deconvolution2D,
    DepthwiseConvolution2D,
    LocallyConnected1D,
    LocallyConnected2D,
    SeparableConvolution2D,
    ShareConvolution2D,
    UpSampling1D,
    UpSampling2D,
    UpSampling3D,
    ZeroPadding1D,
    ZeroPadding2D,
    ZeroPadding3D,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.embedding import (  # noqa: F401
    Embedding,
    SparseEmbedding,
    WordEmbedding,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.merge import (  # noqa: F401
    Merge,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.normalization import (  # noqa: F401
    BatchNormalization,
    LayerNormalization,
    WithinChannelLRN2D,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.advanced import (  # noqa: F401
    ELU,
    LeakyReLU,
    ParametricSoftPlus,
    PReLU,
    SReLU,
    ThresholdedReLU,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.recurrent import (  # noqa: F401
    GRU,
    LSTM,
    Bidirectional,
    ConvLSTM2D,
    ConvLSTM3D,
    SimpleRNN,
    TimeDistributed,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.self_attention import (  # noqa: F401
    BERT,
    TransformerLayer,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.pooling import (  # noqa: F401
    AveragePooling1D,
    AveragePooling2D,
    AveragePooling3D,
    GlobalAveragePooling1D,
    GlobalAveragePooling2D,
    GlobalAveragePooling3D,
    GlobalMaxPooling1D,
    GlobalMaxPooling2D,
    GlobalMaxPooling3D,
    MaxPooling1D,
    MaxPooling2D,
    MaxPooling3D,
)

from analytics_zoo_tpu.pipeline.api.keras.layers.tensor_ops import (  # noqa: F401
    LRN2D,
    AddConstant,
    BinaryThreshold,
    CAdd,
    CMul,
    Exp,
    Expand,
    GaussianSampler,
    GetShape,
    HardShrink,
    HardTanh,
    Log,
    Max,
    Mul,
    MulConstant,
    Narrow,
    Negative,
    Power,
    ResizeBilinear,
    RReLU,
    Scale,
    SelectTable,
    Softmax,
    SoftShrink,
    SpaceToDepth,
    SplitTensor,
    Sqrt,
    Square,
    Threshold,
)

# Keras-2-style aliases (reference keras2 package provides these names).
Conv1D = Convolution1D
Conv2D = Convolution2D
Conv3D = Convolution3D
SeparableConv2D = SeparableConvolution2D
DepthwiseConv2D = DepthwiseConvolution2D
Conv2DTranspose = Deconvolution2D
