"""Recurrent layers — LSTM/GRU/SimpleRNN/ConvLSTM2D + Bidirectional +
TimeDistributed.

Reference: pipeline/api/keras/layers/{LSTM,GRU,SimpleRNN,ConvLSTM2D,
Bidirectional,TimeDistributed}.scala (BigDL ``Recurrent`` wrappers running a
per-timestep JVM loop over MKL kernels).

TPU re-design: the time loop is ``lax.scan`` — a single fused XLA while-loop
whose body is one batched MXU matmul per gate block (all 4 LSTM gates in one
(in+units, 4*units) matmul), no per-step dispatch.  Hidden state stays in
registers/HBM across steps; weights are loop-invariant so XLA hoists them.
"""

from __future__ import annotations

import copy

import jax
import jax.numpy as jnp
from jax import lax

from analytics_zoo_tpu.ops.activations import get_activation
from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


class _RNNBase(Layer):
    units_per_gate = 1  # number of stacked gate blocks in the fused kernel

    def __init__(self, output_dim, activation="tanh",
                 inner_activation="hard_sigmoid", return_sequences=False,
                 go_backwards=False, init="glorot_uniform",
                 inner_init="orthogonal", input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.output_dim = int(output_dim)
        self.activation = get_activation(activation)
        self.inner_activation = get_activation(inner_activation)
        self.return_sequences = bool(return_sequences)
        self.go_backwards = bool(go_backwards)
        self.init = init
        self.inner_init = inner_init
        self._config = dict(output_dim=output_dim,
                            return_sequences=return_sequences)

    def build(self, input_shape):
        in_dim = int(input_shape[-1])
        g = self.units_per_gate
        self.add_weight("kernel", (in_dim, g * self.output_dim), self.init)
        self.add_weight("recurrent_kernel",
                        (self.output_dim, g * self.output_dim),
                        self.inner_init)
        self.add_weight("bias", (g * self.output_dim,), "zero")

    def initial_carry(self, batch):
        return jnp.zeros((batch, self.output_dim))

    def step(self, params, carry, x_t):
        raise NotImplementedError

    def call(self, params, inputs, state=None, training=False, rng=None):
        # (B, T, F) -> scan over T
        x = jnp.swapaxes(inputs, 0, 1)  # (T, B, F)
        if self.go_backwards:
            x = x[::-1]
        carry = self.initial_carry(inputs.shape[0])

        def body(carry, x_t):
            new_carry, out = self.step(params, carry, x_t)
            return new_carry, out if self.return_sequences else None

        final, seq = lax.scan(body, carry, x)
        if self.return_sequences:
            out = jnp.swapaxes(seq, 0, 1)
            if self.go_backwards:
                out = out[:, ::-1]
            return out
        return self._final_output(final)

    def _final_output(self, carry):
        return carry

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (input_shape[0], input_shape[1], self.output_dim)
        return (input_shape[0], self.output_dim)


class SimpleRNN(_RNNBase):
    """Reference SimpleRNN.scala."""

    units_per_gate = 1

    def step(self, params, carry, x_t):
        h = self.activation(
            x_t @ params["kernel"] + carry @ params["recurrent_kernel"]
            + params["bias"]
        )
        return h, h


class LSTM(_RNNBase):
    """Reference LSTM.scala; gate order i, f, c, o (fused in one matmul)."""

    units_per_gate = 4

    def initial_carry(self, batch):
        z = jnp.zeros((batch, self.output_dim))
        return (z, z)  # (h, c)

    def step(self, params, carry, x_t):
        h, c = carry
        z = (x_t @ params["kernel"] + h @ params["recurrent_kernel"]
             + params["bias"])
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = self.inner_activation(i)
        f = self.inner_activation(f)
        g = self.activation(g)
        o = self.inner_activation(o)
        c = f * c + i * g
        h = o * self.activation(c)
        return (h, c), h

    def _final_output(self, carry):
        return carry[0]


class GRU(_RNNBase):
    """Reference GRU.scala; gate order z, r, h."""

    units_per_gate = 3

    def step(self, params, carry, x_t):
        h = carry
        d = self.output_dim
        xz = x_t @ params["kernel"]
        hz = h @ params["recurrent_kernel"]
        b = params["bias"]
        z = self.inner_activation(xz[:, :d] + hz[:, :d] + b[:d])
        r = self.inner_activation(xz[:, d:2 * d] + hz[:, d:2 * d]
                                  + b[d:2 * d])
        hh = self.activation(xz[:, 2 * d:] + r * hz[:, 2 * d:] + b[2 * d:])
        new_h = z * h + (1.0 - z) * hh
        return new_h, new_h


class Bidirectional(Layer):
    """Wraps an RNN layer into forward+backward passes (reference
    Bidirectional.scala; merge modes concat/sum/mul/ave)."""

    def __init__(self, layer: _RNNBase, merge_mode="concat",
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape or layer._input_shape,
                         name=name, **kwargs)
        assert isinstance(layer, _RNNBase), "Bidirectional wraps RNN layers"
        self.forward_layer = layer
        self.backward_layer = copy.deepcopy(layer)
        self.backward_layer.go_backwards = not layer.go_backwards
        self.forward_layer.name = f"{self.name}_fwd"
        self.backward_layer.name = f"{self.name}_bwd"
        self.forward_layer._auto_named = False
        self.backward_layer._auto_named = False
        self.merge_mode = merge_mode

    def build(self, input_shape):
        self.forward_layer.ensure_built(input_shape)
        self.backward_layer.ensure_built(input_shape)

    def init_params(self, rng):
        return {
            "fwd": self.forward_layer.init_params(jax.random.fold_in(rng, 0)),
            "bwd": self.backward_layer.init_params(
                jax.random.fold_in(rng, 1)),
        }

    def call(self, params, inputs, state=None, training=False, rng=None):
        a = self.forward_layer.call(params["fwd"], inputs,
                                    training=training, rng=rng)
        b = self.backward_layer.call(params["bwd"], inputs,
                                     training=training, rng=rng)
        if self.merge_mode == "concat":
            return jnp.concatenate([a, b], axis=-1)
        if self.merge_mode == "sum":
            return a + b
        if self.merge_mode == "mul":
            return a * b
        if self.merge_mode == "ave":
            return (a + b) / 2.0
        raise ValueError(f"merge_mode {self.merge_mode!r}")

    def compute_output_shape(self, input_shape):
        shape = self.forward_layer.compute_output_shape(input_shape)
        if self.merge_mode == "concat":
            return tuple(shape[:-1]) + (shape[-1] * 2,)
        return shape


class TimeDistributed(Layer):
    """Applies a layer to every timestep by folding time into batch —
    one big batched op instead of a per-step loop (reference
    TimeDistributed.scala)."""

    def __init__(self, layer: Layer, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape or layer._input_shape,
                         name=name, **kwargs)
        self.inner = layer
        self.inner.name = f"{self.name}_inner"
        self.inner._auto_named = False

    def build(self, input_shape):
        self.inner.ensure_built(tuple(input_shape[1:]))

    def init_params(self, rng):
        return {"inner": self.inner.init_params(rng)}

    def init_state(self):
        s = self.inner.init_state()
        return {"inner": s} if s else {}

    @property
    def stateful(self):
        return self.inner.stateful

    def call(self, params, inputs, state=None, training=False, rng=None):
        b, t = inputs.shape[0], inputs.shape[1]
        flat = inputs.reshape((b * t,) + inputs.shape[2:])
        out, new_state = self.inner.apply(
            params["inner"], flat,
            state=(state or {}).get("inner"),
            training=training, rng=rng,
        )
        out = out.reshape((b, t) + out.shape[1:])
        if self.stateful:
            return out, {"inner": new_state}
        return out

    def compute_output_shape(self, input_shape):
        inner_shape = self.inner.compute_output_shape(
            (input_shape[0],) + tuple(input_shape[2:])
        )
        return (input_shape[0], input_shape[1]) + tuple(inner_shape[1:])


class _ConvLSTMND(Layer):
    """Rank-parameterized convolutional LSTM (reference ConvLSTM2D.scala /
    ConvLSTM3D.scala): the four gates are one fused N-d convolution, scanned
    over time with ``lax.scan``.  Channels-last layouts (NHWC / NDHWC)."""

    rank: int = 2

    def __init__(self, nb_filter, nb_kernel, return_sequences=False,
                 border_mode="same", subsample=None,
                 inner_activation="hard_sigmoid", activation="tanh",
                 go_backwards=False, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.nb_filter = int(nb_filter)
        self.nb_kernel = int(nb_kernel)
        self.return_sequences = return_sequences
        self.border_mode = border_mode
        if subsample is None:
            subsample = (1,) * self.rank
        self.subsample = tuple(
            subsample if isinstance(subsample, (list, tuple))
            else (subsample,) * self.rank
        )
        self.activation = get_activation(activation)
        self.inner_activation = get_activation(inner_activation)
        self.go_backwards = go_backwards

    def build(self, input_shape):
        # input (without batch): (T, *spatial, C)
        in_ch = int(input_shape[-1])
        k = (self.nb_kernel,) * self.rank
        self.add_weight("kernel", k + (in_ch, 4 * self.nb_filter))
        self.add_weight("recurrent_kernel",
                        k + (self.nb_filter, 4 * self.nb_filter))
        self.add_weight("bias", (4 * self.nb_filter,), "zero")

    def _out_spatial(self, spatial):
        from analytics_zoo_tpu.pipeline.api.keras.layers.conv import (
            _conv_out_dim,
        )

        return tuple(
            _conv_out_dim(s, self.nb_kernel, st, self.border_mode)
            for s, st in zip(spatial, self.subsample)
        )

    def _conv(self, x, w, strides=None, padding="SAME"):
        from analytics_zoo_tpu.pipeline.api.keras.layers.conv import _DIMNUMS

        return lax.conv_general_dilated(
            x, w, window_strides=strides or (1,) * self.rank,
            padding=padding, dimension_numbers=_DIMNUMS[self.rank],
        )

    def call(self, params, inputs, state=None, training=False, rng=None):
        # inputs: (B, T, *spatial, C); the input conv applies
        # border_mode+stride, the recurrent conv is SAME/stride-1 over the
        # (already strided) hidden state — reference ConvLSTM semantics.
        x = jnp.swapaxes(inputs, 0, 1)
        if self.go_backwards:
            x = x[::-1]
        b = inputs.shape[0]
        out_spatial = self._out_spatial(inputs.shape[2:2 + self.rank])
        h0 = jnp.zeros((b,) + out_spatial + (self.nb_filter,),
                       inputs.dtype)
        c0 = jnp.zeros_like(h0)

        def body(carry, x_t):
            h, c = carry
            z = (self._conv(x_t, params["kernel"], self.subsample,
                            self.border_mode.upper())
                 + self._conv(h, params["recurrent_kernel"])
                 + params["bias"])
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i = self.inner_activation(i)
            f = self.inner_activation(f)
            g = self.activation(g)
            o = self.inner_activation(o)
            c = f * c + i * g
            h = o * self.activation(c)
            return (h, c), (h if self.return_sequences else None)

        (h, _), seq = lax.scan(body, (h0, c0), x)
        if self.return_sequences:
            out = jnp.swapaxes(seq, 0, 1)
            if self.go_backwards:
                out = out[:, ::-1]
            return out
        return h

    def compute_output_shape(self, input_shape):
        b, t = input_shape[:2]
        out_spatial = self._out_spatial(input_shape[2:2 + self.rank])
        if self.return_sequences:
            return (b, t) + out_spatial + (self.nb_filter,)
        return (b,) + out_spatial + (self.nb_filter,)


class ConvLSTM2D(_ConvLSTMND):
    """Convolutional LSTM over NHWC frames (reference ConvLSTM2D.scala)."""
    rank = 2


class ConvLSTM3D(_ConvLSTMND):
    """Volumetric convolutional LSTM over NDHWC volumes (reference
    ConvLSTM3D.scala / InternalConvLSTM3D)."""
    rank = 3
