"""Normalization layers.

Reference: pipeline/api/keras/layers/BatchNormalization.scala (BigDL
SpatialBatchNormalization wrapper), LayerNorm inside TransformerLayer.scala
(reference has no standalone LayerNormalization layer; exposed here because
the transformer stack needs it as a first-class piece).

TPU notes: with the batch sharded over the ``data`` mesh axis, the batch-stat
reductions below become *global* cross-replica means — XLA inserts the psum —
so this is synchronized BatchNorm across the whole mesh by construction.  The
reference could only do per-worker BN (its sync happened at gradient
aggregation only); sync-BN is what the resnet example's
``EngineRef.getCoreNumber`` replication approximated.
"""

from __future__ import annotations

import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


class BatchNormalization(Layer):
    """Channels-last batch norm over all non-channel axes.

    Reference BatchNormalization.scala (momentum/epsilon defaults match:
    momentum=0.99, epsilon=1e-3).
    """

    def __init__(self, epsilon=1e-3, momentum=0.99, beta_init="zero",
                 gamma_init="one", scale=True, center=True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.epsilon = float(epsilon)
        self.momentum = float(momentum)
        self.scale = scale
        self.center = center
        self.beta_init = beta_init
        self.gamma_init = gamma_init
        self._config = dict(epsilon=epsilon, momentum=momentum)

    def build(self, input_shape):
        ch = int(input_shape[-1])
        if self.scale:
            self.add_weight("gamma", (ch,), self.gamma_init)
        if self.center:
            self.add_weight("beta", (ch,), self.beta_init)
        self.add_state("moving_mean", (ch,), "zero")
        self.add_state("moving_var", (ch,), "one")

    def call(self, params, inputs, state=None, training=False, rng=None):
        axes = tuple(range(inputs.ndim - 1))
        state = state or self.init_state()
        # Batch statistics in f32 regardless of the compute dtype (bf16
        # mean/var over large batches loses precision and would pollute the
        # f32 running stats) — but the f32 convert fuses into the reduction,
        # so the activation tensor itself is only ever read/written in the
        # compute dtype.  The normalize is folded into one per-channel
        # scale/offset multiply-add so each BN costs a single elementwise
        # pass over the activations (the HBM-bound cost that dominates
        # ResNet step time on TPU).
        if training:
            x32 = inputs.astype(jnp.float32)
            # Sharded batch ⇒ these are global-mesh reductions (sync BN).
            mean = jnp.mean(x32, axis=axes)
            var = jnp.var(x32, axis=axes)
            m = self.momentum
            new_state = {
                "moving_mean": m * jnp.asarray(state["moving_mean"],
                                               jnp.float32)
                + (1 - m) * mean,
                "moving_var": m * jnp.asarray(state["moving_var"],
                                              jnp.float32)
                + (1 - m) * var,
            }
        else:
            mean = jnp.asarray(state["moving_mean"], jnp.float32)
            var = jnp.asarray(state["moving_var"], jnp.float32)
            new_state = state
        inv = jnp.reciprocal(jnp.sqrt(var + self.epsilon))
        # Fold gamma/beta into the per-channel affine: y = x*scale + offset.
        scale = inv
        if self.scale:
            scale = scale * params["gamma"].astype(jnp.float32)
        offset = -mean * scale
        if self.center:
            offset = offset + params["beta"].astype(jnp.float32)
        y = inputs * scale.astype(inputs.dtype) + offset.astype(inputs.dtype)
        return y, new_state

    @property
    def stateful(self):
        return True


class LayerNormalization(Layer):
    """Layer norm over the last axis (reference: the internal ``LayerNorm``
    used by TransformerLayer.scala / BERT.scala ``gelu``+LN blocks)."""

    def __init__(self, epsilon=1e-5, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.epsilon = float(epsilon)

    def build(self, input_shape):
        d = int(input_shape[-1])
        self.add_weight("gamma", (d,), "one")
        self.add_weight("beta", (d,), "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        # Stats in f32 under bf16 compute (converts fuse into the reduction);
        # the elementwise normalize stays in the compute dtype.
        x32 = inputs.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        inv = jax_rsqrt(var + self.epsilon)
        y = (inputs - mean.astype(inputs.dtype)) * inv.astype(inputs.dtype)
        return y * params["gamma"] + params["beta"]


def jax_rsqrt(x):
    return jnp.reciprocal(jnp.sqrt(x))


class WithinChannelLRN2D(Layer):
    """Local response normalization within channels (reference
    WithinChannelLRN2D.scala), NHWC."""

    def __init__(self, size=5, alpha=1.0, beta=0.75, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.size = int(size)
        self.alpha = float(alpha)
        self.beta = float(beta)

    def call(self, params, inputs, state=None, training=False, rng=None):
        from jax import lax

        sq = inputs * inputs
        window = (1, self.size, self.size, 1)
        summed = lax.reduce_window(
            sq, 0.0, lax.add, window, (1, 1, 1, 1), "SAME"
        )
        norm = (1.0 + self.alpha * summed / (self.size * self.size)) \
            ** self.beta
        return inputs / norm
