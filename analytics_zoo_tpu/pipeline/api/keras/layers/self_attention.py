"""Transformer layers — TransformerLayer (GPT-style decoder stack) and BERT.

Reference: pipeline/api/keras/layers/TransformerLayer.scala:56 (embedding +
position embedding + n_block blocks; ``multiHeadSelfAttention`` :137 builds
the full O(L²) attention via Conv1D projections) and BERT.scala:66 (adds
token-type embeddings and an additive attention mask; pooler on [CLS]).

TPU re-design: projections are single fused (D, 3D) matmuls on the MXU;
attention routes through :func:`analytics_zoo_tpu.ops.attention.
dot_product_attention` so the Pallas flash kernel / ring-attention (seq-axis
sharded) variants swap in without touching this layer.  Long-context support
(absent in the reference, SURVEY.md §5) is a mesh-axis concern handled in
``analytics_zoo_tpu.parallel``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from analytics_zoo_tpu.ops.attention import (
    dot_product_attention,
    merge_heads,
    split_heads,
)
from analytics_zoo_tpu.pipeline.api.keras.engine import (
    Layer,
    get_initializer,
)


def _dense_init(rng, shape, std):
    return std * jax.random.normal(rng, shape)


class _TransformerCore(Layer):
    """Shared block stack for TransformerLayer and BERT."""

    def __init__(self, n_block, n_head, hidden_size, intermediate_size=None,
                 hidden_drop=0.1, attn_drop=0.1, initializer_range=0.02,
                 bidirectional=False, activation="gelu", remat=False,
                 moe_experts=0, moe_top_k=2, moe_capacity_factor=1.25,
                 moe_aux_weight=0.01, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.n_block = int(n_block)
        self.n_head = int(n_head)
        self.hidden_size = int(hidden_size)
        self.intermediate_size = int(intermediate_size or 4 * hidden_size)
        self.hidden_drop = float(hidden_drop)
        self.attn_drop = float(attn_drop)
        self.initializer_range = float(initializer_range)
        self.bidirectional = bool(bidirectional)
        # moe_experts > 0 swaps every block's dense feed-forward for a
        # routed mixture of experts (ops.moe.routed_ffn: GShard top-k +
        # capacity, dense-dispatch so the GSPMD train step shards the
        # expert dim over the mesh `expert` axis).  The layer becomes
        # stateful: its per-step state carries the load-balancing aux loss
        # (raw + pre-weighted) and the capacity drop fraction — the
        # estimator adds every `moe_aux_cost` state leaf to the training
        # loss, so expert collapse is penalized out of the box.
        self.moe_experts = int(moe_experts)
        self.moe_top_k = int(moe_top_k)
        self.moe_capacity_factor = float(moe_capacity_factor)
        self.moe_aux_weight = float(moe_aux_weight)
        if self.moe_experts and self.moe_top_k > self.moe_experts:
            raise ValueError(
                f"moe_top_k={moe_top_k} > moe_experts={moe_experts}")
        # remat: recompute each block's activations in the backward pass
        # (jax.checkpoint) — live memory drops from O(n_block) to O(1)
        # block activations for ~1/3 more FLOPs, the standard trade for
        # training deep stacks near the HBM limit.  Accepts True/"full"
        # (recompute everything), "dots" (save matmul outputs —
        # checkpoint_dots_with_no_batch_dims: less recompute, more
        # memory), or "attn" (save only the per-block attention context
        # via checkpoint_name — the backward re-derives the cheap
        # projections but not the flash-attention forward).  The best
        # point is hardware-dependent; the transformer bench sweeps it.
        if remat in (False, None):
            self.remat = None
        elif remat in (True, "full"):
            self.remat = "full"
        elif remat in ("dots", "attn"):
            self.remat = str(remat)
        else:
            raise ValueError(
                f"remat must be bool, 'full', 'dots' or 'attn'; "
                f"got {remat!r}")
        from analytics_zoo_tpu.ops.activations import get_activation

        self.act = get_activation(activation)

    # -- param construction (nested; overrides the flat-spec default) ------
    def _block_params(self, rng):
        d, m = self.hidden_size, self.intermediate_size
        std = self.initializer_range
        ks = jax.random.split(rng, 6)
        p = {
            "qkv_kernel": _dense_init(ks[0], (d, 3 * d), std),
            "qkv_bias": jnp.zeros((3 * d,)),
            "proj_kernel": _dense_init(ks[1], (d, d), std),
            "proj_bias": jnp.zeros((d,)),
            "ln1_gamma": jnp.ones((d,)), "ln1_beta": jnp.zeros((d,)),
            "ln2_gamma": jnp.ones((d,)), "ln2_beta": jnp.zeros((d,)),
        }
        if self.moe_experts:
            e = self.moe_experts
            p.update({
                "moe_gate": _dense_init(ks[2], (d, e), std),
                "moe_w1": _dense_init(ks[3], (e, d, m), std),
                "moe_b1": jnp.zeros((e, m)),
                "moe_w2": _dense_init(ks[4], (e, m, d), std),
                "moe_b2": jnp.zeros((d,)),
            })
        else:
            p.update({
                "fc_kernel": _dense_init(ks[2], (d, m), std),
                "fc_bias": jnp.zeros((m,)),
                "out_kernel": _dense_init(ks[3], (m, d), std),
                "out_bias": jnp.zeros((d,)),
            })
        return p

    @property
    def stateful(self):
        # MoE stacks report their aux loss / drop fraction through the
        # layer-state channel; the estimator adds every `moe_aux_cost`
        # leaf to the training loss
        return self.moe_experts > 0

    def init_state(self):
        if not self.moe_experts:
            return {}
        return {"moe_aux_loss": jnp.zeros((), jnp.float32),
                "moe_aux_cost": jnp.zeros((), jnp.float32),
                "moe_drop_fraction": jnp.zeros((), jnp.float32)}

    def _moe_state(self, aux, drop):
        return {"moe_aux_loss": aux,
                "moe_aux_cost": self.moe_aux_weight * aux,
                "moe_drop_fraction": drop}

    def _per_block_param_count(self):
        d, m = self.hidden_size, self.intermediate_size
        attn = 3 * d * d + 3 * d + d * d + d + 4 * d  # qkv + proj + 2 LN
        if self.moe_experts:
            e = self.moe_experts
            return attn + d * e + e * (2 * d * m + m) + d
        return attn + 2 * d * m + m + d

    @staticmethod
    def _ln(x, gamma, beta, eps=1e-5):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jnp.reciprocal(jnp.sqrt(var + eps)) * gamma \
            + beta

    def _drop(self, x, p, training, rng, salt):
        if not training or p <= 0.0 or rng is None:
            return x
        key = jax.random.fold_in(rng, salt)
        keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
        return jnp.where(keep, x / (1.0 - p), 0.0)

    def _run_blocks(self, blocks, h, mask, training, rng):
        return self._run_blocks_aux(blocks, h, mask, training, rng)[0]

    def _run_blocks_aux(self, blocks, h, mask, training, rng):
        """Run the stack; also return (mean aux loss, mean drop fraction)
        over the MoE blocks (zeros for a dense stack).

        The remat policy is PLAN-resolved: a ``remat_rules`` entry on
        the sharding plan being compiled (matched against this layer's
        name) overrides the per-layer ``remat=`` flag, which stays the
        trace-time default — so activation checkpointing is memory-plan
        configuration, with one jax.checkpoint site (``apply_remat``)."""
        from analytics_zoo_tpu.parallel.plan import (
            apply_remat,
            resolve_remat,
        )

        policy = resolve_remat(getattr(self, "name", None) or "blocks",
                               default=self.remat)
        body = apply_remat(self._block_forward_aux, policy,
                           static_argnums=(3,))
        aux = jnp.zeros((), jnp.float32)
        drop = jnp.zeros((), jnp.float32)
        n_moe = 0
        for bi, bp in enumerate(blocks):
            brng = jax.random.fold_in(rng, bi) if rng is not None else None
            h, a, dr = body(bp, h, mask, training, brng)
            if "moe_gate" in bp:  # static: params structure is traced once
                n_moe += 1
                aux = aux + a
                drop = drop + dr
        if n_moe:
            aux, drop = aux / n_moe, drop / n_moe
        return h, aux, drop

    def _block_forward(self, bp, h, mask, training, brng):
        # single-output view kept for pipeline-parallel stage builders
        # (parallel/pipeline.py), which carry dense blocks only
        return self._block_forward_aux(bp, h, mask, training, brng)[0]

    def _block_forward_aux(self, bp, h, mask, training, brng):
        qkv = h @ bp["qkv_kernel"] + bp["qkv_bias"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = split_heads(q, self.n_head)
        k = split_heads(k, self.n_head)
        v = split_heads(v, self.n_head)
        a = dot_product_attention(
            q, k, v, mask=mask,
            dropout_p=self.attn_drop if training else 0.0,
            rng=(jax.random.fold_in(brng, 3)
                 if brng is not None else None),
            causal=not self.bidirectional,
        )
        a = checkpoint_name(a, "attn_context")
        a = merge_heads(a) @ bp["proj_kernel"] + bp["proj_bias"]
        a = self._drop(a, self.hidden_drop, training, brng, 1)
        h = self._ln(h + a, bp["ln1_gamma"], bp["ln1_beta"])
        aux = jnp.zeros((), jnp.float32)
        drop = jnp.zeros((), jnp.float32)
        if "moe_gate" in bp:
            from analytics_zoo_tpu.ops.moe import routed_ffn

            # routed FFN behind the residual: an over-capacity token's
            # zero expert output degrades to identity, never to a zeroed
            # activation (tests/test_moe_layer.py pins this)
            f, aux, drop = routed_ffn(
                h, bp["moe_gate"], bp["moe_w1"], bp["moe_b1"],
                bp["moe_w2"], bp["moe_b2"], top_k=self.moe_top_k,
                capacity_factor=self.moe_capacity_factor,
                activation=self.act)
        else:
            f = self.act(h @ bp["fc_kernel"] + bp["fc_bias"])
            f = f @ bp["out_kernel"] + bp["out_bias"]
        f = self._drop(f, self.hidden_drop, training, brng, 2)
        return self._ln(h + f, bp["ln2_gamma"], bp["ln2_beta"]), aux, drop


class TransformerLayer(_TransformerCore):
    """GPT-style stack (reference TransformerLayer.scala:56).

    Inputs: ``[tokens, positions]`` int arrays of shape (B, L)
    (matching the reference's two-input contract), output (B, L, D).
    """

    def __init__(self, vocab, seq_len, n_block=12, n_head=12,
                 hidden_size=768, embedding_drop=0.1, **kwargs):
        super().__init__(n_block=n_block, n_head=n_head,
                         hidden_size=hidden_size, **kwargs)
        self.vocab = int(vocab)
        self.seq_len = int(seq_len)
        self.embedding_drop = float(embedding_drop)

    @classmethod
    def init_with_default_params(cls, vocab, seq_len, n_block=12, n_head=12,
                                 hidden_size=768, **kwargs):
        """Reference companion-object constructor."""
        return cls(vocab, seq_len, n_block, n_head, hidden_size, **kwargs)

    def build(self, input_shape):
        pass  # params are nested; built in init_params

    def init_params(self, rng):
        std = self.initializer_range
        ks = jax.random.split(rng, 2 + self.n_block)
        return {
            "tok_embed": _dense_init(ks[0], (self.vocab, self.hidden_size),
                                     std),
            "pos_embed": _dense_init(ks[1],
                                     (self.seq_len, self.hidden_size), std),
            "blocks": [self._block_params(ks[2 + i])
                       for i in range(self.n_block)],
        }

    def param_count(self):
        d, v = self.hidden_size, self.vocab
        per_block = self._per_block_param_count()
        return v * d + self.seq_len * d + self.n_block * per_block

    def call(self, params, inputs, state=None, training=False, rng=None):
        if isinstance(inputs, (list, tuple)):
            tokens, positions = inputs[0], inputs[1]
        else:
            tokens = inputs
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape
            )
        h = jnp.take(params["tok_embed"], tokens.astype(jnp.int32), axis=0)
        h = h + jnp.take(params["pos_embed"], positions.astype(jnp.int32),
                         axis=0)
        h = self._drop(h, self.embedding_drop, training, rng, 0)
        out, aux, drop = self._run_blocks_aux(params["blocks"], h, None,
                                              training, rng)
        if self.moe_experts:
            return out, self._moe_state(aux, drop)
        return out

    def compute_output_shape(self, input_shape):
        if isinstance(input_shape, list):
            input_shape = input_shape[0]
        return tuple(input_shape) + (self.hidden_size,)


class BERT(_TransformerCore):
    """BERT encoder (reference BERT.scala:66).

    Inputs: ``[token_ids, token_type_ids, position_ids, attention_mask]``
    (the reference's four-input contract); outputs ``[sequence_output,
    pooled_output]``.
    """

    def __init__(self, vocab=40990, hidden_size=768, n_block=12, n_head=12,
                 seq_len=512, intermediate_size=3072, hidden_p_drop=0.1,
                 attn_p_drop=0.1, type_vocab=2, **kwargs):
        super().__init__(n_block=n_block, n_head=n_head,
                         hidden_size=hidden_size,
                         intermediate_size=intermediate_size,
                         hidden_drop=hidden_p_drop, attn_drop=attn_p_drop,
                         bidirectional=True, **kwargs)
        self.vocab = int(vocab)
        self.seq_len = int(seq_len)
        self.type_vocab = int(type_vocab)

    def build(self, input_shape):
        pass

    def init_params(self, rng):
        std = self.initializer_range
        d = self.hidden_size
        ks = jax.random.split(rng, 4 + self.n_block)
        return {
            "tok_embed": _dense_init(ks[0], (self.vocab, d), std),
            "pos_embed": _dense_init(ks[1], (self.seq_len, d), std),
            "type_embed": _dense_init(ks[2], (self.type_vocab, d), std),
            "embed_ln_gamma": jnp.ones((d,)),
            "embed_ln_beta": jnp.zeros((d,)),
            "pooler_kernel": _dense_init(ks[3], (d, d), std),
            "pooler_bias": jnp.zeros((d,)),
            "blocks": [self._block_params(ks[4 + i])
                       for i in range(self.n_block)],
        }

    def param_count(self):
        d = self.hidden_size
        per_block = self._per_block_param_count()
        return ((self.vocab + self.seq_len + self.type_vocab) * d + 2 * d
                + d * d + d + self.n_block * per_block)

    def call(self, params, inputs, state=None, training=False, rng=None):
        tokens, token_types, positions, attn_mask = (
            list(inputs) + [None] * (4 - len(inputs))
            if isinstance(inputs, (list, tuple)) else [inputs, None, None,
                                                       None]
        )
        b, l = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(l), (b, l))
        h = jnp.take(params["tok_embed"], tokens.astype(jnp.int32), axis=0)
        h = h + jnp.take(params["pos_embed"], positions.astype(jnp.int32),
                         axis=0)
        if token_types is not None:
            h = h + jnp.take(params["type_embed"],
                             token_types.astype(jnp.int32), axis=0)
        h = self._ln(h, params["embed_ln_gamma"], params["embed_ln_beta"])
        h = self._drop(h, self.hidden_drop, training, rng, 0)
        mask = None
        if attn_mask is not None:
            # additive mask: (B, L) 1/0 -> (B, 1, 1, L) 0/-1e9
            # (reference BERT.scala attention-mask preprocessing)
            mask = (1.0 - attn_mask[:, None, None, :].astype(h.dtype)) \
                * jnp.finfo(h.dtype).min
        seq, aux, drop = self._run_blocks_aux(params["blocks"], h, mask,
                                              training, rng)
        pooled = jnp.tanh(
            seq[:, 0] @ params["pooler_kernel"] + params["pooler_bias"]
        )
        if self.moe_experts:
            return [seq, pooled], self._moe_state(aux, drop)
        return [seq, pooled]

    def compute_output_shape(self, input_shape):
        shape = input_shape[0] if isinstance(input_shape, list) \
            else input_shape
        b, l = shape[0], shape[1]
        return [(b, l, self.hidden_size), (b, self.hidden_size)]
