"""Advanced activation layers — reference
pipeline/api/keras/layers/{LeakyReLU,ELU,PReLU,SReLU,ThresholdedReLU,
ParametricSoftPlus}.scala.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


class LeakyReLU(Layer):
    def __init__(self, alpha=0.3, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.alpha = float(alpha)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.where(inputs >= 0, inputs, self.alpha * inputs)


class ELU(Layer):
    def __init__(self, alpha=1.0, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.alpha = float(alpha)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.where(inputs >= 0, inputs,
                         self.alpha * jnp.expm1(inputs))


class ThresholdedReLU(Layer):
    def __init__(self, theta=1.0, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.theta = float(theta)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.where(inputs > self.theta, inputs, 0.0)


class PReLU(Layer):
    """Per-channel learnable leak (reference PReLU.scala)."""

    def build(self, input_shape):
        self.add_weight("alpha", (int(input_shape[-1]),), 0.25)

    def call(self, params, inputs, state=None, training=False, rng=None):
        a = params["alpha"]
        return jnp.where(inputs >= 0, inputs, a * inputs)


class ParametricSoftPlus(Layer):
    """alpha * softplus(beta * x) with learnable alpha/beta (reference
    ParametricSoftPlus.scala)."""

    def __init__(self, alpha_init=0.2, beta_init=5.0, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.alpha_init = float(alpha_init)
        self.beta_init = float(beta_init)

    def build(self, input_shape):
        ch = int(input_shape[-1])
        self.add_weight("alpha", (ch,), self.alpha_init)
        self.add_weight("beta", (ch,), self.beta_init)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return params["alpha"] * jax.nn.softplus(params["beta"] * inputs)


class SReLU(Layer):
    """S-shaped ReLU with four learnable per-channel params (reference
    SReLU.scala)."""

    def build(self, input_shape):
        ch = int(input_shape[-1])
        self.add_weight("t_left", (ch,), "zero")
        self.add_weight("a_left", (ch,), "glorot_uniform")
        self.add_weight("t_right", (ch,), "glorot_uniform")
        self.add_weight("a_right", (ch,), "one")

    def call(self, params, inputs, state=None, training=False, rng=None):
        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        y_left = tl + al * (inputs - tl)
        y_right = tr + ar * (inputs - tr)
        return jnp.where(
            inputs < tl, y_left, jnp.where(inputs > tr, y_right, inputs)
        )
