"""Core layers: Dense, Activation, Dropout, Flatten, Reshape, Permute,
RepeatVector, Masking, Highway, GaussianNoise/Dropout, SpatialDropout.

Reference: pipeline/api/keras/layers/{Dense,Activation,Dropout,Flatten,
Reshape,Permute,RepeatVector,Masking,Highway,GaussianNoise,GaussianDropout,
SpatialDropout1D/2D/3D}.scala — BigDL module wrappers with
``computeOutputShape``.  Here each is a pure function over a params pytree;
dropout takes an explicit rng (threaded by the graph executor) so a whole
training step stays reproducible and jit-pure.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops.activations import get_activation
from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


class Dense(Layer):
    """Fully connected: ``y = act(x @ W + b)``.

    Reference keras/layers (Dense.scala); kernel shaped (in, out) so the
    batched matmul maps straight onto the MXU.  Applies to the last axis for
    >2D inputs (Keras-1 semantics).
    """

    def __init__(self, output_dim, init="glorot_uniform", activation=None,
                 bias=True, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.output_dim = int(output_dim)
        self.init = init
        self.activation = get_activation(activation)
        self.bias = bias
        self._config = dict(output_dim=output_dim, init=init, bias=bias)

    def build(self, input_shape):
        in_dim = int(input_shape[-1])
        self.add_weight("kernel", (in_dim, self.output_dim), self.init)
        if self.bias:
            self.add_weight("bias", (self.output_dim,), "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        y = inputs @ params["kernel"]
        if self.bias:
            y = y + params["bias"]
        return self.activation(y)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class Activation(Layer):
    def __init__(self, activation, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.activation = get_activation(activation)
        self._config = dict(activation=str(activation))

    def call(self, params, inputs, state=None, training=False, rng=None):
        return self.activation(inputs)


class Dropout(Layer):
    """Inverted dropout; identity at inference (reference Dropout.scala)."""

    def __init__(self, p, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.p = float(p)
        self._config = dict(p=p)

    def call(self, params, inputs, state=None, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return inputs
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, inputs.shape)
        return jnp.where(mask, inputs / keep, 0.0)


class _SpatialDropoutND(Dropout):
    """Drops whole (channels-last) feature maps — one Bernoulli draw per
    (sample, channel), broadcast over the spatial dims (reference
    SpatialDropout1D/2D/3D.scala)."""

    def call(self, params, inputs, state=None, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return inputs
        keep = 1.0 - self.p
        shape = ((inputs.shape[0],) + (1,) * (inputs.ndim - 2)
                 + (inputs.shape[-1],))
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, inputs / keep, 0.0)


class SpatialDropout1D(_SpatialDropoutND):
    """(B, steps, C) feature-map dropout."""


class SpatialDropout2D(_SpatialDropoutND):
    """NHWC feature-map dropout."""


class GaussianNoise(Layer):
    def __init__(self, sigma, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.sigma = float(sigma)

    def call(self, params, inputs, state=None, training=False, rng=None):
        if not training or rng is None:
            return inputs
        return inputs + self.sigma * jax.random.normal(
            rng, inputs.shape, inputs.dtype
        )


class GaussianDropout(Layer):
    def __init__(self, p, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.p = float(p)

    def call(self, params, inputs, state=None, training=False, rng=None):
        if not training or rng is None or self.p <= 0:
            return inputs
        std = np.sqrt(self.p / (1.0 - self.p))
        return inputs * (
            1.0 + std * jax.random.normal(rng, inputs.shape, inputs.dtype)
        )


class Flatten(Layer):
    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs.reshape(inputs.shape[0], -1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], int(np.prod(input_shape[1:])))


class Reshape(Layer):
    """Reshape non-batch dims; one dim may be -1 (reference Reshape.scala)."""

    def __init__(self, target_shape, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.target_shape = tuple(int(d) for d in target_shape)
        self._config = dict(target_shape=self.target_shape)

    def _resolve(self, input_shape):
        in_elems = int(np.prod(input_shape[1:]))
        tgt = list(self.target_shape)
        if -1 in tgt:
            known = int(np.prod([d for d in tgt if d != -1]))
            tgt[tgt.index(-1)] = in_elems // known
        return tuple(tgt)

    def call(self, params, inputs, state=None, training=False, rng=None):
        tgt = self._resolve((None,) + inputs.shape[1:])
        return inputs.reshape((inputs.shape[0],) + tgt)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self._resolve(input_shape)


class Permute(Layer):
    """Permute non-batch axes; dims are 1-based (Keras-1 / reference
    Permute.scala convention)."""

    def __init__(self, dims, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dims = tuple(int(d) for d in dims)
        self._config = dict(dims=self.dims)

    def call(self, params, inputs, state=None, training=False, rng=None):
        perm = (0,) + tuple(d for d in self.dims)
        return jnp.transpose(inputs, perm)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + tuple(
            input_shape[d] for d in self.dims
        )


class RepeatVector(Layer):
    """(b, f) -> (b, n, f). Reference RepeatVector.scala."""

    def __init__(self, n, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.n = int(n)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.repeat(inputs[:, None, :], self.n, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.n, input_shape[1])


class Masking(Layer):
    """Zero out timesteps equal to mask_value (reference Masking.scala).
    Under XLA's static-shape regime masking is value-level, not shape-level."""

    def __init__(self, mask_value=0.0, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.mask_value = float(mask_value)

    def call(self, params, inputs, state=None, training=False, rng=None):
        keep = jnp.any(inputs != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, inputs, 0.0)


class Highway(Layer):
    """Highway network layer: ``y = t*h(xW_h) + (1-t)*x`` (reference
    Highway.scala)."""

    def __init__(self, activation="tanh", bias=True, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.activation = get_activation(activation)
        self.bias = bias

    def build(self, input_shape):
        d = int(input_shape[-1])
        self.add_weight("kernel", (d, d))
        self.add_weight("gate_kernel", (d, d))
        if self.bias:
            self.add_weight("bias", (d,), "zero")
            # negative gate bias → start as identity (standard highway init)
            self.add_weight("gate_bias", (d,), -1.0)

    def call(self, params, inputs, state=None, training=False, rng=None):
        h = inputs @ params["kernel"]
        t = inputs @ params["gate_kernel"]
        if self.bias:
            h = h + params["bias"]
            t = t + params["gate_bias"]
        t = jax.nn.sigmoid(t)
        return t * self.activation(h) + (1.0 - t) * inputs


class Identity(Layer):
    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs


class Select(Layer):
    """Select one index along an axis (reference Select.scala); axis is
    0-based including batch for fidelity with Zoo's Select(dim, index)."""

    def __init__(self, dim, index, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dim = int(dim)
        self.index = int(index)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.take(inputs, self.index, axis=self.dim)

    def compute_output_shape(self, input_shape):
        shape = list(input_shape)
        dim = self.dim if self.dim >= 0 else len(shape) + self.dim
        del shape[dim]
        return tuple(shape)


class Squeeze(Layer):
    """Squeeze singleton dims (reference Squeeze.scala); dims 0-based
    including batch."""

    def __init__(self, dims, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dims = (dims,) if isinstance(dims, int) else tuple(dims)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.squeeze(inputs, axis=self.dims)

    def compute_output_shape(self, input_shape):
        nd = len(input_shape)
        drop = {d % nd for d in self.dims}
        return tuple(s for i, s in enumerate(input_shape) if i not in drop)


class ExpandDim(Layer):
    def __init__(self, dim, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dim = int(dim)

    def call(self, params, inputs, state=None, training=False, rng=None):
        return jnp.expand_dims(inputs, self.dim)

    def compute_output_shape(self, input_shape):
        shape = list(input_shape)
        dim = self.dim if self.dim >= 0 else len(shape) + 1 + self.dim
        shape.insert(dim, 1)
        return tuple(shape)


class SpatialDropout3D(_SpatialDropoutND):
    """NDHWC volume feature-map dropout."""


class MaxoutDense(Layer):
    """Maxout over ``nb_feature`` linear maps (reference MaxoutDense.scala):
    ``y_j = max_k (x @ W_k + b_k)_j``.  The k maps are one fused matmul
    (in, nb_feature*out) so the MXU sees a single large contraction.
    """

    def __init__(self, output_dim, nb_feature=4, bias=True,
                 init="glorot_uniform", input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.output_dim = int(output_dim)
        self.nb_feature = int(nb_feature)
        self.bias = bias
        self.init = init
        self._config = dict(output_dim=output_dim, nb_feature=nb_feature)

    def build(self, input_shape):
        in_dim = int(input_shape[-1])
        self.add_weight(
            "kernel", (in_dim, self.nb_feature * self.output_dim), self.init
        )
        if self.bias:
            self.add_weight("bias", (self.nb_feature * self.output_dim,),
                            "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        y = inputs @ params["kernel"]
        if self.bias:
            y = y + params["bias"]
        y = y.reshape(y.shape[:-1] + (self.nb_feature, self.output_dim))
        return jnp.max(y, axis=-2)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class SparseDense(Layer):
    """Dense layer over sparse COO input (reference SparseDense.scala, which
    wraps BigDL SparseLinear).

    Input may be a dense array or a ``(indices, values, dense_shape)`` COO
    triple — ``indices`` (nnz, 2) int rows of (sample, feature);
    ``dense_shape`` must be static Python ints (it fixes the output batch
    size at trace time), not a traced array.  The sparse
    path materialises per-sample dense rows with a segment-sum scatter, the
    natural XLA lowering (TPUs have no sparse MXU path; for the very sparse
    + very wide case shard the kernel over the model axis instead).
    Gradients flow to kernel/bias and the COO ``values``;
    ``backward_start``/``backward_length`` (1-based, like the reference)
    restrict which input features receive gradient.
    """

    def __init__(self, output_dim, activation=None, bias=True,
                 init="glorot_uniform", backward_start=-1, backward_length=-1,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.output_dim = int(output_dim)
        self.activation = get_activation(activation)
        self.bias = bias
        self.init = init
        self.backward_start = int(backward_start)
        self.backward_length = int(backward_length)
        if self.backward_start not in (-1,) and self.backward_start < 1:
            raise ValueError(
                "backward_start is 1-based (like the reference "
                "SparseLinear): use -1 to disable or a value >= 1, got "
                f"{backward_start}"
            )
        self._config = dict(output_dim=output_dim, bias=bias,
                            backward_start=backward_start,
                            backward_length=backward_length)

    def _grad_window(self, n_features):
        if self.backward_start < 0:
            return None
        start = self.backward_start - 1  # reference is 1-based
        length = (self.backward_length if self.backward_length >= 0
                  else n_features - start)
        return start, start + length

    def build(self, input_shape):
        in_dim = int(input_shape[-1])
        self.add_weight("kernel", (in_dim, self.output_dim), self.init)
        if self.bias:
            self.add_weight("bias", (self.output_dim,), "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        if isinstance(inputs, (tuple, list)) and len(inputs) == 3:
            indices, values, dense_shape = inputs
            try:
                n = int(dense_shape[0])
                n_feat = int(dense_shape[1])
            except TypeError as e:  # traced array under jit
                raise TypeError(
                    "SparseDense: dense_shape must be static Python ints "
                    "(it fixes the output batch size at trace time); pass a "
                    "plain tuple, not a traced jax array"
                ) from e
            window = self._grad_window(n_feat)
            if window is not None:
                lo, hi = window
                in_win = (indices[:, 1] >= lo) & (indices[:, 1] < hi)
                frozen = jax.lax.stop_gradient(values)
                values = jnp.where(in_win, values, frozen)
            # rows of W gathered per nnz, scaled, then scatter-added per
            # sample: one gather + one segment_sum, both XLA-native.
            contrib = values[:, None] * params["kernel"][indices[:, 1]]
            y = jax.ops.segment_sum(contrib, indices[:, 0], num_segments=n)
        else:
            window = self._grad_window(inputs.shape[-1])
            if window is not None:
                lo, hi = window
                mask = jnp.zeros(inputs.shape[-1], inputs.dtype
                                 ).at[lo:hi].set(1.0)
                frozen = jax.lax.stop_gradient(inputs)
                inputs = frozen + (inputs - frozen) * mask
            y = inputs @ params["kernel"]
        if self.bias:
            y = y + params["bias"]
        return self.activation(y)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)
