"""Pooling layers, NHWC, via ``lax.reduce_window`` (XLA-native windows).

Reference: pipeline/api/keras/layers/{MaxPooling1D/2D/3D,
AveragePooling1D/2D/3D,GlobalMaxPooling1D/2D/3D,GlobalAveragePooling1D/2D/3D}
.scala.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import (
    _conv_out_dim,
    _ntuple,
)


class _PoolND(Layer):
    rank = 2
    mode = "max"

    def __init__(self, pool_size=2, strides=None, border_mode="valid",
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.pool_size = _ntuple(pool_size, self.rank)
        self.strides = _ntuple(strides, self.rank) if strides is not None \
            else self.pool_size
        self.border_mode = border_mode

    def call(self, params, inputs, state=None, training=False, rng=None):
        window = (1,) + self.pool_size + (1,)
        strides = (1,) + self.strides + (1,)
        if self.mode == "max":
            init = -jnp.inf
            y = lax.reduce_window(
                inputs, init, lax.max, window, strides,
                self.border_mode.upper(),
            )
        else:
            y = lax.reduce_window(
                inputs, 0.0, lax.add, window, strides,
                self.border_mode.upper(),
            )
            if self.border_mode == "same":
                ones = jnp.ones_like(inputs)
                counts = lax.reduce_window(
                    ones, 0.0, lax.add, window, strides, "SAME"
                )
                y = y / counts
            else:
                # static python arithmetic: jnp.prod here would stage the
                # op and yield a tracer, breaking float() under jit
                import math

                y = y / float(math.prod(self.pool_size))
        return y

    def compute_output_shape(self, input_shape):
        spatial = tuple(
            _conv_out_dim(s, k, st, self.border_mode)
            for s, k, st in zip(input_shape[1:-1], self.pool_size,
                                self.strides)
        )
        return (input_shape[0],) + spatial + (input_shape[-1],)


class MaxPooling1D(_PoolND):
    rank, mode = 1, "max"

    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 **kwargs):
        super().__init__(pool_length, stride, border_mode, **kwargs)


class MaxPooling2D(_PoolND):
    rank, mode = 2, "max"


class MaxPooling3D(_PoolND):
    rank, mode = 3, "max"


class AveragePooling1D(_PoolND):
    rank, mode = 1, "avg"

    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 **kwargs):
        super().__init__(pool_length, stride, border_mode, **kwargs)


class AveragePooling2D(_PoolND):
    rank, mode = 2, "avg"


class AveragePooling3D(_PoolND):
    rank, mode = 3, "avg"


class _GlobalPoolND(Layer):
    rank = 2
    mode = "max"

    def call(self, params, inputs, state=None, training=False, rng=None):
        axes = tuple(range(1, 1 + self.rank))
        if self.mode == "max":
            return jnp.max(inputs, axis=axes)
        return jnp.mean(inputs, axis=axes)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], input_shape[-1])


class GlobalMaxPooling1D(_GlobalPoolND):
    rank, mode = 1, "max"


class GlobalMaxPooling2D(_GlobalPoolND):
    rank, mode = 2, "max"


class GlobalMaxPooling3D(_GlobalPoolND):
    rank, mode = 3, "max"


class GlobalAveragePooling1D(_GlobalPoolND):
    rank, mode = 1, "avg"


class GlobalAveragePooling2D(_GlobalPoolND):
    rank, mode = 2, "avg"


class GlobalAveragePooling3D(_GlobalPoolND):
    rank, mode = 3, "avg"
