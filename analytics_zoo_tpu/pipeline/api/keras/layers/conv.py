"""Convolution layers — NHWC/NWC layouts, lowered to
``lax.conv_general_dilated`` so XLA tiles them onto the MXU.

Reference: pipeline/api/keras/layers/{Convolution1D,Convolution2D,
Convolution3D,SeparableConvolution2D,Deconvolution2D,AtrousConvolution1D,
AtrousConvolution2D,LocallyConnected1D,LocallyConnected2D,Cropping1D/2D/3D,
ZeroPadding1D/2D/3D,UpSampling1D/2D/3D}.scala.  The reference defaults to
Torch-style NCHW ("th" dim ordering); this rebuild is channels-last (NHWC)
throughout — the layout the TPU vector units and XLA convolution emitters
prefer — and kernels are stored HWIO.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from analytics_zoo_tpu.ops.activations import get_activation
from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


def _ntuple(x, n):
    if isinstance(x, int):
        return (x,) * n
    t = tuple(int(v) for v in x)
    assert len(t) == n, f"expected {n} values, got {t}"
    return t


def _conv_out_dim(size, k, stride, mode, dilation=1):
    if size is None:
        return None
    eff = (k - 1) * dilation + 1
    out = ((size + stride - 1) // stride if mode == "same"
           else (size - eff) // stride + 1)
    if out <= 0:
        raise ValueError(
            f"spatial dim collapses to {out}: input size {size} is too "
            f"small for kernel {k} (stride {stride}, dilation {dilation}, "
            f"border_mode={mode!r}) — use a larger input or 'same' padding"
        )
    return out


_DIMNUMS = {1: ("NWC", "WIO", "NWC"),
            2: ("NHWC", "HWIO", "NHWC"),
            3: ("NDHWC", "DHWIO", "NDHWC")}


class _ConvND(Layer):
    """Shared N-d convolution over the trailing channel axis."""

    rank: int = 2

    def __init__(self, nb_filter, kernel_size, subsample=1,
                 border_mode="valid", activation=None, bias=True,
                 dilation=1, init="glorot_uniform", input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.nb_filter = int(nb_filter)
        self.kernel_size = _ntuple(kernel_size, self.rank)
        self.subsample = _ntuple(subsample, self.rank)
        self.dilation = _ntuple(dilation, self.rank)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode {border_mode!r}")
        self.border_mode = border_mode
        self.activation = get_activation(activation)
        self.bias = bias
        self.init = init
        self._config = dict(nb_filter=nb_filter, kernel_size=self.kernel_size,
                            subsample=self.subsample,
                            border_mode=border_mode, bias=bias)

    def build(self, input_shape):
        in_ch = int(input_shape[-1])
        self.add_weight("kernel",
                        self.kernel_size + (in_ch, self.nb_filter),
                        self.init)
        if self.bias:
            self.add_weight("bias", (self.nb_filter,), "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        y = lax.conv_general_dilated(
            inputs, params["kernel"],
            window_strides=self.subsample,
            padding=self.border_mode.upper(),
            rhs_dilation=self.dilation,
            dimension_numbers=_DIMNUMS[self.rank],
        )
        if self.bias:
            y = y + params["bias"]
        return self.activation(y)

    def compute_output_shape(self, input_shape):
        spatial = input_shape[1:-1]
        out_spatial = tuple(
            _conv_out_dim(s, k, st, self.border_mode, d)
            for s, k, st, d in zip(spatial, self.kernel_size,
                                   self.subsample, self.dilation)
        )
        return (input_shape[0],) + out_spatial + (self.nb_filter,)


class Convolution1D(_ConvND):
    """Reference Convolution1D.scala; input (batch, steps, channels)."""
    rank = 1

    def __init__(self, nb_filter, filter_length, subsample_length=1,
                 border_mode="valid", activation=None, bias=True,
                 dilation_rate=1, init="glorot_uniform", input_shape=None,
                 name=None, **kwargs):
        super().__init__(nb_filter, filter_length, subsample_length,
                         border_mode, activation, bias, dilation_rate, init,
                         input_shape, name, **kwargs)


class Convolution2D(_ConvND):
    """Reference Convolution2D.scala; input NHWC."""
    rank = 2

    def __init__(self, nb_filter, nb_row, nb_col=None, subsample=(1, 1),
                 border_mode="valid", activation=None, bias=True,
                 dilation=(1, 1), init="glorot_uniform", input_shape=None,
                 name=None, **kwargs):
        ksize = (nb_row, nb_col) if nb_col is not None else nb_row
        super().__init__(nb_filter, ksize, subsample, border_mode,
                         activation, bias, dilation, init, input_shape, name,
                         **kwargs)


class Convolution3D(_ConvND):
    """Reference Convolution3D.scala; input NDHWC."""
    rank = 3

    def __init__(self, nb_filter, kernel_dim1, kernel_dim2=None,
                 kernel_dim3=None, subsample=(1, 1, 1), border_mode="valid",
                 activation=None, bias=True, init="glorot_uniform",
                 input_shape=None, name=None, **kwargs):
        if kernel_dim2 is None:
            ksize = kernel_dim1
        else:
            ksize = (kernel_dim1, kernel_dim2, kernel_dim3)
        super().__init__(nb_filter, ksize, subsample, border_mode,
                         activation, bias, 1, init, input_shape, name,
                         **kwargs)


class AtrousConvolution1D(Convolution1D):
    """Dilated conv (reference AtrousConvolution1D.scala)."""

    def __init__(self, nb_filter, filter_length, atrous_rate=1, **kwargs):
        super().__init__(nb_filter, filter_length,
                         dilation_rate=atrous_rate, **kwargs)


class AtrousConvolution2D(Convolution2D):
    def __init__(self, nb_filter, nb_row, nb_col=None, atrous_rate=(1, 1),
                 **kwargs):
        super().__init__(nb_filter, nb_row, nb_col, dilation=atrous_rate,
                         **kwargs)


def _depthwise_lower(inputs, kernel, subsample, border_mode):
    """The grouped-conv lowering shared by DepthwiseConvolution2D and
    SeparableConvolution2D: kernel layout (kh, kw, 1, in*dm),
    feature_group_count = in_ch."""
    return lax.conv_general_dilated(
        inputs, kernel,
        window_strides=subsample,
        padding=border_mode.upper(),
        dimension_numbers=_DIMNUMS[2],
        feature_group_count=inputs.shape[-1],
    )


class DepthwiseConvolution2D(Layer):
    """Depthwise-only conv, NHWC — standalone so MobileNet-style blocks
    can put BatchNorm/activation BETWEEN the depthwise and pointwise
    stages (reference mobilenet config,
    ImageClassificationConfig.scala:48-49).  Also the base class of
    SeparableConvolution2D, which adds the pointwise projection."""

    def __init__(self, nb_row, nb_col=None, subsample=(1, 1),
                 border_mode="valid", depth_multiplier=1, activation=None,
                 bias=True, init="glorot_uniform", input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode {border_mode!r}")
        self.kernel_size = _ntuple((nb_row, nb_col) if nb_col else nb_row, 2)
        self.subsample = _ntuple(subsample, 2)
        self.border_mode = border_mode
        self.depth_multiplier = int(depth_multiplier)
        self.activation = get_activation(activation)
        self.bias = bias
        self.init = init

    def _add_depthwise_kernel(self, input_shape):
        in_ch = int(input_shape[-1])
        self.add_weight(
            "depthwise_kernel",
            self.kernel_size + (1, in_ch * self.depth_multiplier), self.init
        )
        return in_ch

    def build(self, input_shape):
        in_ch = self._add_depthwise_kernel(input_shape)
        if self.bias:
            self.add_weight("bias", (in_ch * self.depth_multiplier,),
                            "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        y = _depthwise_lower(inputs, params["depthwise_kernel"],
                             self.subsample, self.border_mode)
        if self.bias:
            y = y + params["bias"]
        return self.activation(y)

    def _spatial_out(self, input_shape):
        return tuple(
            _conv_out_dim(s, k, st, self.border_mode)
            for s, k, st in zip(input_shape[1:-1], self.kernel_size,
                                self.subsample)
        )

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self._spatial_out(input_shape) + (
            int(input_shape[-1]) * self.depth_multiplier,)


class SeparableConvolution2D(DepthwiseConvolution2D):
    """Depthwise + pointwise conv (reference
    SeparableConvolution2D.scala), NHWC."""

    def __init__(self, nb_filter, nb_row, nb_col=None, **kwargs):
        super().__init__(nb_row, nb_col, **kwargs)
        self.nb_filter = int(nb_filter)

    def build(self, input_shape):
        in_ch = self._add_depthwise_kernel(input_shape)
        self.add_weight(
            "pointwise_kernel",
            (1, 1, in_ch * self.depth_multiplier, self.nb_filter), self.init
        )
        if self.bias:
            self.add_weight("bias", (self.nb_filter,), "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        y = _depthwise_lower(inputs, params["depthwise_kernel"],
                             self.subsample, self.border_mode)
        y = lax.conv_general_dilated(
            y, params["pointwise_kernel"], window_strides=(1, 1),
            padding="VALID", dimension_numbers=_DIMNUMS[2],
        )
        if self.bias:
            y = y + params["bias"]
        return self.activation(y)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self._spatial_out(input_shape) + (
            self.nb_filter,)


class Deconvolution2D(Layer):
    """Transposed convolution (reference Deconvolution2D.scala), NHWC."""

    def __init__(self, nb_filter, nb_row, nb_col=None, subsample=(1, 1),
                 border_mode="valid", activation=None, bias=True,
                 init="glorot_uniform", input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.nb_filter = int(nb_filter)
        self.kernel_size = _ntuple((nb_row, nb_col) if nb_col else nb_row, 2)
        self.subsample = _ntuple(subsample, 2)
        self.border_mode = border_mode
        self.activation = get_activation(activation)
        self.bias = bias
        self.init = init

    def build(self, input_shape):
        in_ch = int(input_shape[-1])
        self.add_weight("kernel", self.kernel_size + (in_ch, self.nb_filter),
                        self.init)
        if self.bias:
            self.add_weight("bias", (self.nb_filter,), "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        y = lax.conv_transpose(
            inputs, params["kernel"], strides=self.subsample,
            padding=self.border_mode.upper(),
            dimension_numbers=_DIMNUMS[2],
        )
        if self.bias:
            y = y + params["bias"]
        return self.activation(y)

    def compute_output_shape(self, input_shape):
        spatial = input_shape[1:-1]
        out = []
        for s, k, st in zip(spatial, self.kernel_size, self.subsample):
            if s is None:
                out.append(None)
            elif self.border_mode == "same":
                out.append(s * st)
            else:
                out.append(s * st + max(k - st, 0))
        return (input_shape[0],) + tuple(out) + (self.nb_filter,)


class _ZeroPaddingND(Layer):
    rank = 2

    def __init__(self, padding, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if isinstance(padding, int):
            padding = ((padding, padding),) * self.rank
        else:
            padding = tuple(
                (p, p) if isinstance(p, int) else tuple(p) for p in padding
            )
        self.padding = padding

    def call(self, params, inputs, state=None, training=False, rng=None):
        cfg = ((0, 0),) + self.padding + ((0, 0),)
        return jnp.pad(inputs, cfg)

    def compute_output_shape(self, input_shape):
        spatial = [
            None if s is None else s + p[0] + p[1]
            for s, p in zip(input_shape[1:-1], self.padding)
        ]
        return (input_shape[0],) + tuple(spatial) + (input_shape[-1],)


class ZeroPadding1D(_ZeroPaddingND):
    rank = 1


class ZeroPadding2D(_ZeroPaddingND):
    rank = 2


class ZeroPadding3D(_ZeroPaddingND):
    rank = 3


class _CroppingND(Layer):
    rank = 2

    def __init__(self, cropping, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.cropping = tuple(
            (c, c) if isinstance(c, int) else tuple(c) for c in cropping
        )

    def call(self, params, inputs, state=None, training=False, rng=None):
        idx = [slice(None)]
        for (lo, hi), size in zip(self.cropping, inputs.shape[1:-1]):
            idx.append(slice(lo, size - hi))
        idx.append(slice(None))
        return inputs[tuple(idx)]

    def compute_output_shape(self, input_shape):
        spatial = [
            None if s is None else s - lo - hi
            for s, (lo, hi) in zip(input_shape[1:-1], self.cropping)
        ]
        return (input_shape[0],) + tuple(spatial) + (input_shape[-1],)


class Cropping1D(_CroppingND):
    rank = 1

    def __init__(self, cropping=(1, 1), **kwargs):
        super().__init__((cropping,), **kwargs)


class Cropping2D(_CroppingND):
    rank = 2


class Cropping3D(_CroppingND):
    rank = 3


class _UpSamplingND(Layer):
    rank = 2

    def __init__(self, size=2, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.size = _ntuple(size, self.rank)

    def call(self, params, inputs, state=None, training=False, rng=None):
        y = inputs
        for ax, rep in enumerate(self.size):
            y = jnp.repeat(y, rep, axis=ax + 1)
        return y

    def compute_output_shape(self, input_shape):
        spatial = [
            None if s is None else s * r
            for s, r in zip(input_shape[1:-1], self.size)
        ]
        return (input_shape[0],) + tuple(spatial) + (input_shape[-1],)


class UpSampling1D(_UpSamplingND):
    rank = 1


class UpSampling2D(_UpSamplingND):
    rank = 2


class UpSampling3D(_UpSamplingND):
    rank = 3


class LocallyConnected1D(Layer):
    """Unshared-weights 1D conv (reference LocallyConnected1D.scala).
    Implemented as an einsum over unfolded patches — a single MXU-friendly
    contraction rather than a per-position loop."""

    def __init__(self, nb_filter, filter_length, subsample_length=1,
                 activation=None, bias=True, init="glorot_uniform",
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.nb_filter = int(nb_filter)
        self.filter_length = int(filter_length)
        self.subsample = int(subsample_length)
        self.activation = get_activation(activation)
        self.bias = bias
        self.init = init

    def _out_len(self, steps):
        return (steps - self.filter_length) // self.subsample + 1

    def build(self, input_shape):
        steps, in_ch = int(input_shape[-2]), int(input_shape[-1])
        out_len = self._out_len(steps)
        self.add_weight(
            "kernel", (out_len, self.filter_length * in_ch, self.nb_filter),
            self.init,
        )
        if self.bias:
            self.add_weight("bias", (out_len, self.nb_filter), "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        b, steps, ch = inputs.shape
        out_len = self._out_len(steps)
        starts = np.arange(out_len) * self.subsample
        gather = starts[:, None] + np.arange(self.filter_length)[None, :]
        patches = inputs[:, gather, :].reshape(b, out_len, -1)
        y = jnp.einsum("blk,lko->blo", patches, params["kernel"])
        if self.bias:
            y = y + params["bias"]
        return self.activation(y)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self._out_len(input_shape[1]),
                self.nb_filter)


class LocallyConnected2D(Layer):
    """Unshared-weights 2D conv (reference LocallyConnected2D.scala), NHWC.

    Like LocallyConnected1D, lowered to one einsum over extracted patches —
    a single large MXU contraction instead of per-position kernels.
    """

    def __init__(self, nb_filter, nb_row, nb_col, subsample=(1, 1),
                 border_mode="valid", activation=None, bias=True,
                 init="glorot_uniform", input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        assert border_mode == "valid", (
            "LocallyConnected2D supports border_mode='valid' (the reference "
            "raises for 'same' too)"
        )
        self.nb_filter = int(nb_filter)
        self.nb_row = int(nb_row)
        self.nb_col = int(nb_col)
        self.subsample = _ntuple(subsample, 2)
        self.activation = get_activation(activation)
        self.bias = bias
        self.init = init

    def _out_hw(self, h, w):
        return ((h - self.nb_row) // self.subsample[0] + 1,
                (w - self.nb_col) // self.subsample[1] + 1)

    def build(self, input_shape):
        h, w, in_ch = (int(s) for s in input_shape[-3:])
        oh, ow = self._out_hw(h, w)
        k = self.nb_row * self.nb_col * in_ch
        self.add_weight("kernel", (oh, ow, k, self.nb_filter), self.init)
        if self.bias:
            self.add_weight("bias", (oh, ow, self.nb_filter), "zero")

    def call(self, params, inputs, state=None, training=False, rng=None):
        b, h, w, c = inputs.shape
        oh, ow = self._out_hw(h, w)
        rows = (np.arange(oh) * self.subsample[0])[:, None] \
            + np.arange(self.nb_row)[None, :]
        cols = (np.arange(ow) * self.subsample[1])[:, None] \
            + np.arange(self.nb_col)[None, :]
        # (B,H,W,C) -> (B,OH,kh,W,C) -> (B,OH,kh,OW,kw,C)
        patches = inputs[:, rows][:, :, :, cols]
        patches = jnp.transpose(patches, (0, 1, 3, 2, 4, 5))
        patches = patches.reshape(b, oh, ow, -1)
        y = jnp.einsum("bhwk,hwkf->bhwf", patches, params["kernel"])
        if self.bias:
            y = y + params["bias"]
        return self.activation(y)

    def compute_output_shape(self, input_shape):
        oh, ow = self._out_hw(input_shape[1], input_shape[2])
        return (input_shape[0], oh, ow, self.nb_filter)


class ShareConvolution2D(_ConvND):
    """Reference ShareConvolution2D.scala: a Convolution2D variant that in
    BigDL shares the im2col workspace across replicas to save host memory.
    Under XLA there is no im2col buffer to share (the conv is emitted
    directly on the MXU), so this is the same lowering as Convolution2D;
    kept as a distinct class for API parity, including the explicit pad
    arguments.
    """

    rank = 2

    def __init__(self, nb_filter, nb_row, nb_col, subsample=(1, 1),
                 pad_h=0, pad_w=0, propagate_back=True, activation=None,
                 bias=True, init="glorot_uniform", input_shape=None,
                 name=None, **kwargs):
        super().__init__(nb_filter, (nb_row, nb_col), subsample, "valid",
                         activation, bias, 1, init, input_shape, name,
                         **kwargs)
        self.pad_h = int(pad_h)
        self.pad_w = int(pad_w)
        self.propagate_back = bool(propagate_back)
        self._config.update(pad_h=self.pad_h, pad_w=self.pad_w,
                            propagate_back=self.propagate_back)

    def call(self, params, inputs, state=None, training=False, rng=None):
        if self.pad_h or self.pad_w:
            inputs = jnp.pad(
                inputs,
                ((0, 0), (self.pad_h, self.pad_h),
                 (self.pad_w, self.pad_w), (0, 0)),
            )
        if not self.propagate_back:
            inputs = lax.stop_gradient(inputs)
        return super().call(params, inputs, state=state, training=training,
                            rng=rng)

    def compute_output_shape(self, input_shape):
        b, h, w, _ = input_shape
        padded = (b,
                  None if h is None else h + 2 * self.pad_h,
                  None if w is None else w + 2 * self.pad_w,
                  input_shape[3])
        return super().compute_output_shape(padded)
