"""Validation metrics — reference ``pipeline/api/keras/metrics/*.scala``
(Accuracy, SparseCategoricalAccuracy, BinaryAccuracy, CategoricalAccuracy,
Top5Accuracy, MAE, MSE, AUC) re-designed as pure streaming aggregators.

Each metric maps a device-resident batch to a small ``(numerator,
denominator)`` pair inside the jitted eval step (so evaluation is one XLA
program, not a host loop over layers), and the host accumulates pairs —
the role of BigDL ``ValidationMethod.apply`` + ``ValidationResult`` merging.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class Metric:
    """Streaming metric: ``batch_stats`` runs jitted; ``finalize`` on host."""

    name = "metric"
    #: number of scalar accumulators this metric produces
    n_stats = 2

    def batch_stats(self, y_true, y_pred, mask=None):
        """Return a tuple of scalars to accumulate (device side).

        ``mask`` is an optional (batch,) 0/1 array marking real (non-padded)
        rows; padded rows must not bias numerators or denominators.
        """
        raise NotImplementedError

    def finalize(self, stats) -> float:
        num, den = stats
        return float(num) / max(float(den), 1e-12)


def _match_binary(y_true, y_pred):
    pred = (y_pred > 0.5).astype(jnp.int32)
    return (pred == y_true.astype(jnp.int32)).astype(jnp.float32)


def _masked_num_den(correct, mask):
    """Sum/count of per-element values, zeroing padded batch rows."""
    if mask is None:
        return jnp.sum(correct), jnp.asarray(correct.size, jnp.float32)
    m = mask.reshape((mask.shape[0],) + (1,) * (correct.ndim - 1))
    m = jnp.broadcast_to(m, correct.shape)
    return jnp.sum(correct * m), jnp.sum(m)


class Accuracy(Metric):
    """Auto-dispatching accuracy like the reference's ``Accuracy``
    (keras/metrics/Accuracy.scala): binary if the prediction is scalar,
    else categorical over the last axis; integer or one-hot targets."""

    name = "accuracy"

    def batch_stats(self, y_true, y_pred, mask=None):
        if y_pred.ndim >= 1 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1)
            if y_true.ndim == y_pred.ndim:
                true = jnp.argmax(y_true, axis=-1) \
                    if y_true.shape[-1] > 1 else y_true[..., 0]
            else:
                true = y_true
            correct = (pred == true.astype(pred.dtype)).astype(jnp.float32)
        else:
            yp = y_pred[..., 0] if y_pred.ndim > 1 else y_pred
            yt = y_true[..., 0] if y_true.ndim > 1 else y_true
            correct = _match_binary(yt, yp)
        return _masked_num_den(correct, mask)


class SparseCategoricalAccuracy(Accuracy):
    name = "sparse_categorical_accuracy"


class CategoricalAccuracy(Accuracy):
    name = "categorical_accuracy"


class BinaryAccuracy(Metric):
    name = "binary_accuracy"

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def batch_stats(self, y_true, y_pred, mask=None):
        yp = y_pred.reshape(y_pred.shape[0], -1)
        yt = y_true.reshape(y_true.shape[0], -1).astype(jnp.int32)
        correct = ((yp > self.threshold).astype(jnp.int32) == yt)
        return _masked_num_den(correct.astype(jnp.float32), mask)


class Top5Accuracy(Metric):
    """Reference keras/metrics Top5Accuracy.scala."""

    name = "top5_accuracy"

    def batch_stats(self, y_true, y_pred, mask=None):
        true = y_true
        if true.ndim == y_pred.ndim:
            true = jnp.argmax(true, axis=-1) if true.shape[-1] > 1 \
                else true[..., 0]
        true = true.astype(jnp.int32)
        top5 = jnp.argsort(y_pred, axis=-1)[..., -5:]
        correct = jnp.any(top5 == true[..., None], axis=-1)
        return _masked_num_den(correct.astype(jnp.float32), mask)


class MAE(Metric):
    name = "mae"

    def batch_stats(self, y_true, y_pred, mask=None):
        return _masked_num_den(jnp.abs(y_pred - y_true), mask)


class MSE(Metric):
    name = "mse"

    def batch_stats(self, y_true, y_pred, mask=None):
        return _masked_num_den((y_pred - y_true) ** 2, mask)


class Loss(Metric):
    """Wraps the compiled loss as a validation metric (reference keras
    metrics use `Loss(criterion)` similarly)."""

    name = "loss"

    def __init__(self, loss_fn):
        self.loss_fn = loss_fn
        self.name = "loss"

    def batch_stats(self, y_true, y_pred, mask=None):
        per_sample = self.loss_fn(y_true, y_pred)
        return _masked_num_den(per_sample, mask)


class AUC(Metric):
    """Thresholded streaming ROC-AUC (reference keras/metrics/AUC.scala):
    accumulates TP/FP/TN/FN histograms over fixed thresholds on device,
    trapezoidal ROC integration on host."""

    name = "auc"
    n_stats = 4

    def __init__(self, thresholds: int = 200):
        self.thresholds = np.linspace(0.0, 1.0, thresholds)

    def batch_stats(self, y_true, y_pred, mask=None):
        b = y_pred.shape[0]
        per_row = max(1, int(np.prod(y_pred.shape)) // max(b, 1))
        yp = y_pred.reshape(-1)
        yt = y_true.reshape(-1)
        if mask is None:
            w = jnp.ones_like(yp)
        else:
            w = jnp.repeat(mask.astype(yp.dtype), per_row)
        th = jnp.asarray(self.thresholds)[:, None]
        pred_pos = (yp[None, :] >= th)
        pos = (yt[None, :] > 0.5)
        wf = w[None, :]
        tp = jnp.sum(pred_pos * pos * wf, axis=1)
        fp = jnp.sum(pred_pos * (1 - pos) * wf, axis=1)
        fn = jnp.sum((1 - pred_pos) * pos * wf, axis=1)
        tn = jnp.sum((1 - pred_pos) * (1 - pos) * wf, axis=1)
        return tp, fp, fn, tn

    def finalize(self, stats) -> float:
        tp, fp, fn, tn = (np.asarray(s, dtype=np.float64) for s in stats)
        tpr = tp / np.maximum(tp + fn, 1e-12)
        fpr = fp / np.maximum(fp + tn, 1e-12)
        order = np.argsort(fpr)
        return float(np.trapezoid(tpr[order], fpr[order]))


_METRICS = {
    "accuracy": Accuracy,
    "acc": Accuracy,
    "sparse_categorical_accuracy": SparseCategoricalAccuracy,
    "categorical_accuracy": CategoricalAccuracy,
    "binary_accuracy": BinaryAccuracy,
    "top5accuracy": Top5Accuracy,
    "top5_accuracy": Top5Accuracy,
    "top5": Top5Accuracy,
    "mae": MAE,
    "mse": MSE,
    "auc": AUC,
}


def get_metric(identifier) -> Metric:
    if isinstance(identifier, Metric):
        return identifier
    if isinstance(identifier, str) and identifier.lower() in _METRICS:
        return _METRICS[identifier.lower()]()
    if isinstance(identifier, type) and issubclass(identifier, Metric):
        return identifier()
    raise ValueError(f"unknown metric {identifier!r}")
