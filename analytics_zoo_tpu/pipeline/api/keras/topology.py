"""KerasNet / Sequential / Model — the user-facing model API.

TPU-native re-design of the reference's
``pipeline/api/keras/models/Topology.scala``:

- ``KerasNet`` (Topology.scala:63-600): compile/fit/evaluate/predict,
  TensorBoard wiring, checkpointing, gradient clipping, ``summary()``.
- ``Model`` (graph, Topology.scala:602-759) and ``Sequential``
  (Topology.scala:825-959).

Where the reference's ``fit`` spins up ``InternalDistriOptimizer`` (Spark jobs
+ block-manager all-reduce, Topology.scala:1076-1259), here ``fit`` builds a
single jit-compiled SPMD train step through
:mod:`analytics_zoo_tpu.pipeline.estimator` — forward, backward, psum over the
``data`` mesh axis, and the optimizer update fused into one XLA program.

Models are also Layers, so they nest (a Sequential inside a Model graph), and
their parameters are ordinary pytrees: ``net.params`` / ``net.state``.
"""

from __future__ import annotations

import os
import pickle
from analytics_zoo_tpu.common.safe_pickle import (
    safe_load,
    safe_loads,
)
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common.engine import get_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.engine import (
    GraphFunction,
    InputLayer,
    Layer,
    Variable,
    _ContainerBase,
    canonicalize_names,
)


def _copy_tree(tree):
    """Fresh device buffers for every leaf (donation-safe adoption)."""
    return jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), tree)


def _normalize_names(names) -> tuple:
    """Accept both freeze("a", "b") and freeze(["a", "b"])."""
    if len(names) == 1 and isinstance(names[0], (list, tuple)):
        return tuple(names[0])
    return tuple(names)


class KerasNet(_ContainerBase):
    """Base for trainable containers (reference KerasNet,
    Topology.scala:63-600)."""

    def __init__(self, name=None):
        super().__init__(name=name)
        self.params: dict | None = None
        self.state: dict | None = None
        self._compiled = None   # set by compile()
        self._tensorboard = None  # (log_dir, app_name)
        self._checkpoint = None   # (path, over_write)
        self._grad_clip = None    # ("l2norm", v) | ("const", lo, hi)
        self._estimator = None
        self._predict_fn = None   # cached jitted forward (shape-keyed by jit)
        self._frozen: set = set()  # layer names excluded from training

    # ------------------------------------------------------------------
    # parameter materialization
    # ------------------------------------------------------------------
    def build_params(self, rng=None, force: bool = False):
        """Materialize params/state pytrees (idempotent)."""
        if self.params is not None and not force:
            return self.params, self.state
        if force:
            self.params = self.state = None
        rng = rng if rng is not None else jax.random.PRNGKey(
            get_zoo_context().seed
        )
        self.params = self.init_params(rng)
        self.state = self.init_state()
        return self.params, self.state

    def forward(self, params, inputs, state=None, training=False, rng=None):
        """Pure forward; containers implement via call()."""
        return self.call(params, inputs, state=state, training=training,
                         rng=rng)

    # ------------------------------------------------------------------
    # compile / fit / evaluate / predict  (Topology.scala:135-547)
    # ------------------------------------------------------------------
    def compile(self, optimizer, loss, metrics=None):
        """Configure training (reference ``compile`` Topology.scala:135-166)."""
        from analytics_zoo_tpu.pipeline.api.keras.metrics import get_metric
        from analytics_zoo_tpu.pipeline.api.keras.objectives import get_loss
        from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
            get_optimizer,
        )

        self._compiled = dict(
            optimizer=get_optimizer(optimizer),
            loss=get_loss(loss),
            metrics=[get_metric(m) for m in (metrics or [])],
        )
        self._estimator = None
        return self

    def _require_compiled(self):
        if self._compiled is None:
            raise RuntimeError(
                "model not compiled; call compile(optimizer, loss) first"
            )

    def set_tensorboard(self, log_dir, app_name):
        """Reference Topology.scala:183-202."""
        self._tensorboard = (log_dir, app_name)

    def set_checkpoint(self, path, over_write=True):
        """Reference Topology.scala:245-255."""
        self._checkpoint = (path, over_write)

    def set_gradient_clipping_by_l2_norm(self, clip_norm):
        """Reference Topology.scala (clipping setters ~:168-181)."""
        self._grad_clip = ("l2norm", float(clip_norm))

    def set_constant_gradient_clipping(self, min_value, max_value):
        self._grad_clip = ("const", float(min_value), float(max_value))

    def clear_gradient_clipping(self):
        self._grad_clip = None

    def _make_estimator(self):
        from analytics_zoo_tpu.pipeline.estimator import Estimator

        self._require_compiled()
        est = Estimator(
            self,
            optimizer=self._compiled["optimizer"],
            loss=self._compiled["loss"],
            metrics=self._compiled["metrics"],
            grad_clip=self._grad_clip,
            tensorboard=self._tensorboard,
            checkpoint=self._checkpoint,
        )
        return est

    def fit(self, x, y=None, batch_size=32, nb_epoch=10,
            validation_data=None, distributed=True, sample_weight=None,
            autotune=None, plan=None, elastic=None):
        """Train (reference ``fit`` Topology.scala:418-431 →
        InternalDistriOptimizer.train Topology.scala:1076-1259).

        ``autotune=True`` (or ``ZOO_AUTOTUNE=1``) turns on the
        closed-loop tuner: prefetch workers/depth/read-ahead and the
        fused-dispatch K are tuned online from telemetry, with a
        bit-identical loss trajectory (see docs/data-pipeline.md
        "Autotuning").

        ``plan``: sharding plan for params/optimizer state/batch — a
        :class:`~analytics_zoo_tpu.parallel.plan.ShardingPlan` or a
        canned name ("dp"/"zero1"/"fsdp"); ``None`` defers to
        ``ZOO_SHARDING_PLAN``.  Loss trajectory is placement-invariant
        (see docs/parallelism.md).

        ``elastic``: an :class:`~analytics_zoo_tpu.elastic.membership.
        ElasticSession` turns this fit into one elastic training leg —
        it yields with :class:`~analytics_zoo_tpu.elastic.membership.
        GenerationChange` (after a durable snapshot) when the worker
        membership changes (see docs/elastic-training.md)."""
        from analytics_zoo_tpu.feature.dataset import FeatureSet

        train_set = FeatureSet.of(x, y, sample_weight=sample_weight)
        val_set = (FeatureSet.of(*validation_data)
                   if validation_data is not None else None)
        if self._estimator is None:
            self._estimator = self._make_estimator()
        self._estimator.train(
            train_set, batch_size=batch_size, nb_epoch=nb_epoch,
            validation_set=val_set, autotune=autotune, plan=plan,
            elastic=elastic,
        )
        self._sync_nested()
        return self

    def _sync_nested(self):
        """Copy trained subtrees back into nested KerasNet layers
        (pretrained backbones) so backbone.predict sees post-fit weights.
        Copies, not aliases: the nested net may later be fit() directly,
        and its donated buffers must not be this model's live params."""
        for ly in self.layers:
            if isinstance(ly, KerasNet):
                if self.params is not None and ly.name in self.params:
                    ly.params = _copy_tree(self.params[ly.name])
                if self.state is not None and ly.name in self.state:
                    ly.state = _copy_tree(self.state[ly.name])
                ly._sync_nested()

    def evaluate(self, x, y=None, batch_size=32):
        """Reference ``evaluate`` Topology.scala:472-501; returns a dict of
        metric name -> value (loss always included)."""
        from analytics_zoo_tpu.feature.dataset import FeatureSet

        if self._estimator is None:
            self._estimator = self._make_estimator()
        return self._estimator.evaluate(
            FeatureSet.of(x, y), batch_size=batch_size
        )

    def predict(self, x, batch_size=32, distributed=True):
        """Distributed inference (reference ``predict`` Topology.scala:511-547
        → Predictor.scala:155-189: broadcast + per-partition batching; here:
        jitted forward over batches sharded across the mesh)."""
        from analytics_zoo_tpu.feature.dataset import FeatureSet

        self.build_params()
        ctx = get_zoo_context()
        fs = FeatureSet.of(x)
        n = fs.num_samples

        cached = getattr(self, "_predict_fn", None)
        if cached is None or cached[0] is not ctx.compute_dtype:
            # Cached so repeated predict() calls hit jit's shape-keyed
            # compile cache instead of rebuilding a fresh function object
            # (and paying full compilation) every call.  Keyed by compute
            # dtype; invalidated by Sequential.add().  Model state stays f32
            # (BN running stats must not be rounded).
            from analytics_zoo_tpu.common.engine import cast_floats
            dtype = ctx.compute_dtype

            def _fwd(p, s, xb):
                out, _ = self.forward(
                    cast_floats(p, dtype), cast_floats(xb, dtype),
                    state=s, training=False)
                return cast_floats(out, jnp.float32)

            # through the unified partitioner's choke point: predict
            # programs share the persistent compile cache / metering /
            # HLO features with training (parallel/plan.py)
            from analytics_zoo_tpu.parallel.plan import compile_step

            cached = (ctx.compute_dtype,
                      compile_step(_fwd, label="predict_step"))
            self._predict_fn = cached
        fwd = cached[1]
        outs = []
        for batch in fs.batches(batch_size, shuffle=False, drop_last=False,
                                pad_to_batch=ctx.data_parallel_size):
            xb = ctx.shard_batch(batch["x"])
            out = fwd(self.params, self.state, xb)
            outs.append([np.asarray(o) for o in out]
                        if isinstance(out, (list, tuple))
                        else np.asarray(out))
        if isinstance(outs[0], list):  # multi-output graph
            return [np.concatenate([o[i] for o in outs], axis=0)[:n]
                    for i in range(len(outs[0]))]
        return np.concatenate(outs, axis=0)[:n]

    def predict_classes(self, x, batch_size=32, zero_based_label=True):
        """Reference ``predictClasses`` (Topology.scala:549+)."""
        probs = self.predict(x, batch_size)
        cls = np.argmax(probs, axis=-1)
        return cls if zero_based_label else cls + 1

    # ------------------------------------------------------------------
    # transfer learning: freeze / unfreeze
    # (reference NetUtils.scala freeze/unFreeze + the dogs-vs-cats app's
    # freeze_up_to recipe; here frozen layers get their optimizer updates
    # masked to zero inside the jitted train step — no graph surgery)
    # ------------------------------------------------------------------
    def _validate_layer_names(self, names):
        avail = {ly.name for ly in self.layers}
        unknown = [n for n in names if n not in avail]
        if unknown:
            raise ValueError(
                f"unknown layer(s) {unknown}; available: {sorted(avail)}"
            )

    def freeze(self, *names) -> "KerasNet":
        """Mark the named layers (all layers if none given) non-trainable.

        Reference ``Net.freeze`` (NetUtils.scala): frozen layers keep their
        weights through ``fit``.  Takes effect on the next fit().
        """
        names = _normalize_names(names)
        if not names:
            names = tuple(ly.name for ly in self.layers)
        self._validate_layer_names(names)
        self._frozen.update(names)
        self._estimator = None  # train step must be rebuilt with the mask
        return self

    def unfreeze(self, *names) -> "KerasNet":
        """Reference ``Net.unFreeze``: re-enable training for the named
        layers (all if none given)."""
        names = _normalize_names(names)
        if not names:
            self._frozen.clear()
        else:
            self._validate_layer_names(names)
            self._frozen.difference_update(names)
        self._estimator = None
        return self

    @property
    def frozen_layers(self) -> list[str]:
        return sorted(self._frozen)

    # ------------------------------------------------------------------
    # weights / persistence
    # ------------------------------------------------------------------
    def get_weights(self):
        self.build_params()
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights):
        self.build_params()
        jax.tree_util.tree_map(lambda a, b: None, self.params, weights)
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def save_weights(self, path, over_write=True):
        self.build_params()
        if os.path.exists(path) and not over_write:
            raise IOError(f"{path} exists and over_write=False")
        flat, treedef = jax.tree_util.tree_flatten((self.params, self.state))
        np.savez(path, treedef=np.frombuffer(
            pickle.dumps(treedef), dtype=np.uint8),
            **{str(i): np.asarray(a) for i, a in enumerate(flat)})

    def load_weights(self, path):
        data = np.load(path if path.endswith(".npz") else path + ".npz",
                       allow_pickle=False)
        treedef = safe_loads(data["treedef"].tobytes())
        flat = [data[str(i)] for i in range(len(data.files) - 1)]
        self.params, self.state = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(a) for a in flat]
        )

    def _nets(self) -> list["KerasNet"]:
        """Self plus every nested KerasNet, recursively."""
        nets, stack = [self], list(self.layers)
        while stack:
            ly = stack.pop()
            if isinstance(ly, KerasNet):
                nets.append(ly)
                stack.extend(ly.layers)
        return nets

    def load_checkpoint(self, path) -> "KerasNet":
        """Restore weights/state from the LATEST training checkpoint in
        ``path`` (as written by ``set_checkpoint`` during fit) without
        training — the reference's evaluate-from-checkpoint flow
        (tf_optimizer/evaluate_lenet.py; Net.load for .bigdl snapshots)."""
        from analytics_zoo_tpu.pipeline.estimator.estimator import (
            _Checkpointer,
        )

        blob = _Checkpointer(path).latest()
        if blob is None:
            raise FileNotFoundError(f"no checkpoint found under {path}")
        self.params = jax.tree_util.tree_map(jnp.asarray, blob["params"])
        self.state = jax.tree_util.tree_map(jnp.asarray, blob["state"])
        self._sync_nested()
        return self

    def save(self, path, over_write=True):
        """Whole-model save (reference ZooModel.saveModel /
        KerasNet.saveModule): config + weights in one pickle.  Device
        arrays and runtime state are stripped from EVERY net in the tree
        (nested backbones carry their own param copies after
        ``_sync_nested``; leaving them in would pickle each backbone's
        weights twice)."""
        if os.path.exists(path) and not over_write:
            raise IOError(f"{path} exists and over_write=False")
        weights = (
            jax.tree_util.tree_map(np.asarray, (self.params, self.state))
            if self.params is not None else None
        )
        stashed = []
        for net in self._nets():
            stashed.append((net, net.params, net.state, net._estimator,
                            net._compiled, getattr(net, "_predict_fn", None)))
            net.params = net.state = None
            net._estimator = net._compiled = net._predict_fn = None
        try:
            with open(path, "wb") as f:
                pickle.dump({"net": self, "weights": weights}, f)
        finally:
            for net, params, state, est, compiled, pfn in stashed:
                net.params, net.state = params, state
                net._estimator, net._compiled = est, compiled
                net._predict_fn = pfn

    @staticmethod
    def load(path) -> "KerasNet":
        with open(path, "rb") as f:
            blob = safe_load(f)
        net = blob["net"]
        if blob["weights"] is not None:
            net.params, net.state = jax.tree_util.tree_map(
                jnp.asarray, blob["weights"]
            )
            net._sync_nested()  # repopulate nested backbones' copies
        return net

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def layers(self) -> list[Layer]:
        raise NotImplementedError

    def summary(self, line_length: int = 100):
        """Layer table like the reference's ``summary()``
        (Topology.scala KerasNet.summary)."""
        lines = []
        lines.append("_" * line_length)
        lines.append(f"{'Layer (type)':<44}{'Output Shape':<28}{'Param #':<12}")
        lines.append("=" * line_length)
        total = 0
        for layer in self.layers:
            if isinstance(layer, InputLayer):
                shape, count = layer._build_shape, 0
            else:
                try:
                    shape = layer.compute_output_shape(
                        (None,) + tuple(layer._build_shape or ())
                    )
                except Exception:
                    shape = "?"
                count = layer.param_count() if layer.built else 0
            total += count
            name = f"{layer.name} ({type(layer).__name__})"
            lines.append(f"{name:<44}{str(shape):<28}{count:<12}")
        lines.append("=" * line_length)
        lines.append(f"Total params: {total:,}")
        lines.append("_" * line_length)
        text = "\n".join(lines)
        print(text)
        return text


class Sequential(KerasNet):
    """Linear stack of layers (reference Sequential,
    Topology.scala:825-959)."""

    def __init__(self, name=None):
        super().__init__(name=name)
        self._layers: list[Layer] = []
        self._output_shape = None  # batch-less

    @property
    def layers(self):
        return self._layers

    def add(self, layer: Layer) -> "Sequential":
        if not self._layers:
            in_shape = layer._input_shape
            if in_shape is None and not layer.built:
                raise ValueError(
                    "first layer needs input_shape=..., as in the reference "
                    "Sequential API"
                )
        else:
            in_shape = self._output_shape
        layer.ensure_built(in_shape)
        out_full = layer.compute_output_shape((None,) + tuple(in_shape or ()))
        self._output_shape = tuple(out_full[1:])
        self._layers.append(layer)
        canonicalize_names(self._layers)
        if self.params is not None:
            # Weights already materialized (a new_graph'd pretrained stack
            # being extended with a fresh head): keep them and init only
            # the new layer — nulling params here would silently retrain
            # the "pretrained" backbone from scratch.
            if not isinstance(layer, InputLayer):
                rng = jax.random.fold_in(
                    jax.random.PRNGKey(get_zoo_context().seed),
                    len(self._layers) - 1)
                p = layer.init_params(rng)  # KerasNet adopts its copy here
                if p:
                    self.params[layer.name] = p
                s = layer.init_state()
                if s:
                    if self.state is None:
                        self.state = {}
                    self.state[layer.name] = s
        self._predict_fn = None  # a cached jitted forward is stale now
        return self

    def build(self, input_shape):
        pass  # layers build incrementally in add()

    @property
    def stateful(self):
        return True

    def get_output_shape(self):
        return (None,) + tuple(self._output_shape or ())

    def get_input_shape(self):
        if not self._layers:
            return None
        first = self._layers[0]
        return (None,) + tuple(first._build_shape or ())

    def init_params(self, rng):
        # A nested KerasNet that already materialized weights (a pretrained
        # backbone from new_graph / load) contributes a COPY of those
        # weights — the transfer-learning contract.  A copy, because the
        # outer model's train step donates its param buffers to XLA; shared
        # arrays would leave the backbone holding deleted buffers after the
        # first step.
        if self.params is not None:
            return _copy_tree(self.params)
        params = {}
        for i, layer in enumerate(self._layers):
            if isinstance(layer, InputLayer):
                continue
            p = layer.init_params(jax.random.fold_in(rng, i))
            if p:
                params[layer.name] = p
        return params

    def init_state(self):
        if self.state is not None:
            return _copy_tree(self.state)
        state = {}
        for layer in self._layers:
            s = layer.init_state()
            if s:
                state[layer.name] = s
        return state

    def call(self, params, inputs, state=None, training=False, rng=None):
        state = state or {}
        new_state = dict(state)
        y = inputs
        for i, layer in enumerate(self._layers):
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            y, s = layer.apply(
                params.get(layer.name, {}), y,
                state=new_state.get(layer.name),
                training=training, rng=lrng,
            )
            if s:  # {} stays omitted — must mirror init_state's filter or
                # a nested stateless KerasNet changes the state treedef
                new_state[layer.name] = s
        return y, new_state

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + tuple(self._output_shape)

    def ensure_built(self, input_shape):
        # Built incrementally; verify compatibility.
        self.built = True
        self._build_shape = input_shape
        return input_shape

    # ------------------------------------------------------------------
    # transfer learning (reference dogs-vs-cats app recipe:
    # Net.load(...).new_graph(out).freeze_up_to(layer))
    # ------------------------------------------------------------------
    def freeze_up_to(self, *names) -> "Sequential":
        """Freeze every layer from the input up to and including the named
        layer(s) (reference ``freezeUpTo``, NetUtils.scala)."""
        names = _normalize_names(names)
        if not names:
            raise ValueError("freeze_up_to requires at least one layer "
                             "name (use freeze() to freeze everything)")
        self._validate_layer_names(names)
        idx = {ly.name: i for i, ly in enumerate(self._layers)}
        cut = max(idx[n] for n in names)
        return self.freeze(*[ly.name for ly in self._layers[:cut + 1]])

    def new_graph(self, outputs) -> "Sequential":
        """Truncate at the named layer: a new Sequential ending there,
        SHARING layer objects and (if materialized) their weights — the
        reference's ``new_graph(output)`` feature-extraction surgery
        (NetUtils.scala newGraph)."""
        names = [outputs] if isinstance(outputs, str) else list(outputs)
        if len(names) != 1:
            raise ValueError("Sequential.new_graph takes exactly one output"
                             " layer name")
        self._validate_layer_names(names)
        idx = {ly.name: i for i, ly in enumerate(self._layers)}
        cut = idx[names[0]]
        sub = Sequential(name=f"{self.name}_graph")
        sub._layers = list(self._layers[:cut + 1])
        for ly in sub._layers:   # pin: a later sub.add() must not renumber
            ly._auto_named = False
        last = self._layers[cut]
        sub._output_shape = tuple(
            last.compute_output_shape(
                (None,) + tuple(last._build_shape or ())
            )[1:]
        )
        sub.built = True
        sub._build_shape = (self._layers[0]._build_shape
                            if self._layers else None)
        if self.params is not None:
            # Copies: either model may later fit() (donating its buffers);
            # shared arrays would leave the other holding deleted buffers.
            keep = {ly.name for ly in sub._layers}
            sub.params = _copy_tree(
                {k: v for k, v in self.params.items() if k in keep})
            sub.state = _copy_tree(
                {k: v for k, v in (self.state or {}).items() if k in keep})
        return sub


class Model(KerasNet):
    """Graph model from symbolic inputs/outputs (reference Model,
    Topology.scala:602-759)."""

    def __init__(self, input, output, name=None):
        super().__init__(name=name)
        inputs = input if isinstance(input, (list, tuple)) else [input]
        outputs = output if isinstance(output, (list, tuple)) else [output]
        for v in list(inputs) + list(outputs):
            if not isinstance(v, Variable):
                raise TypeError("Model(input, output) takes symbolic "
                                "Variables from Input(...)")
        self._graph = GraphFunction(inputs, outputs)
        self.built = True
        self._build_shape = [tuple(v.shape[1:]) for v in inputs]
        if len(self._build_shape) == 1:
            self._build_shape = self._build_shape[0]
        self._output_vars = outputs

    @property
    def layers(self):
        return self._graph.layers

    @property
    def stateful(self):
        return True

    def get_output_shape(self):
        shapes = [v.shape for v in self._graph.outputs]
        return shapes[0] if len(shapes) == 1 else shapes

    def get_input_shape(self):
        shapes = [v.shape for v in self._graph.inputs]
        return shapes[0] if len(shapes) == 1 else shapes

    def init_params(self, rng):
        if self.params is not None:   # pretrained: adopt a copy (donation
            return _copy_tree(self.params)   # safety — see Sequential)
        params, _ = self._graph.init(rng)
        return params

    def init_state(self):
        if self.state is not None:
            return _copy_tree(self.state)
        _, state = self._graph.init(jax.random.PRNGKey(0))
        return state

    def call(self, params, inputs, state=None, training=False, rng=None):
        return self._graph(params, inputs, state=state, training=training,
                           rng=rng)

    def compute_output_shape(self, input_shape):
        shapes = [v.shape for v in self._graph.outputs]
        return shapes[0] if len(shapes) == 1 else shapes

    # ------------------------------------------------------------------
    # transfer learning (reference NetUtils.scala newGraph/freezeUpTo on
    # the static graph)
    # ------------------------------------------------------------------
    def freeze_up_to(self, *names) -> "Model":
        """Freeze the named layers and every graph ancestor of them."""
        names = _normalize_names(names)
        if not names:
            raise ValueError("freeze_up_to requires at least one layer "
                             "name (use freeze() to freeze everything)")
        self._validate_layer_names(names)
        stack = [n for n in self._graph.nodes if n.layer.name in set(names)]
        seen, frozen = set(), set()
        while stack:
            node = stack.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            if not isinstance(node.layer, InputLayer):
                frozen.add(node.layer.name)
            for v in node.inbound:
                stack.append(v.node)
        return self.freeze(*sorted(frozen))

    def new_graph(self, outputs) -> "Model":
        """A new Model over the same graph, re-rooted at the named layers'
        outputs; weights (if materialized) are shared for retained layers."""
        names = [outputs] if isinstance(outputs, str) else list(outputs)
        self._validate_layer_names(names)
        by_name: dict[str, Any] = {}
        for node in self._graph.nodes:
            by_name.setdefault(node.layer.name, node)
        out_vars = [by_name[n].outputs[0] for n in names]
        # Names were canonicalized when THIS model was built; pin them so
        # the sub-model's canonicalize_names pass can't renumber shared
        # layers (which would corrupt both models' param keys).
        for ly in self.layers:
            ly._auto_named = False
        sub = Model(input=self._graph.inputs, output=out_vars,
                    name=f"{self.name}_graph")
        if self.params is not None:
            # Copies — donation safety, see Sequential.new_graph.
            keep = {ly.name for ly in sub.layers}
            sub.params = _copy_tree(
                {k: v for k, v in self.params.items() if k in keep})
            sub.state = _copy_tree(
                {k: v for k, v in (self.state or {}).items() if k in keep})
        return sub


def merge(inputs, mode="sum", concat_axis=-1, name=None):
    """Functional merge helper (reference Merge.scala / ``merge`` in
    keras API).  Takes symbolic Variables."""
    from analytics_zoo_tpu.pipeline.api.keras.layers.merge import Merge

    return Merge(mode=mode, concat_axis=concat_axis, name=name)(inputs)
