"""Optimizers — Keras-1 names over optax gradient transforms.

Reference: ``pipeline/api/keras/optimizers/Adam.scala`` (Adam with
schedule-aware LR), ``AdamWeightDecay.scala`` (BERT-style decoupled weight
decay with warmup/linear-decay schedule), plus BigDL ``SGD`` schedules used by
the examples (warmup + epoch decay in examples/resnet/TrainImageNet.scala:36-120).

The reference applies the optimizer per parameter-slice inside its Spark
all-reduce ("parameter server on Spark", docs/docs/wp-bigdl.md:148-164).  Here
the optimizer update is fused into the jitted SPMD train step right after the
psum — the sharding-aware analogue of that slice-wise update, with XLA free to
shard the update across chips (cf. PAPERS.md "Automatic Cross-Replica Sharding
of Weight Update in Data-Parallel Training").
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import optax

Schedule = Callable[[int], float]  # step -> lr multiplier or absolute lr


def warmup_linear_decay(warmup_steps: int, total_steps: int) -> Schedule:
    """BERT-style warmup-then-linear-decay multiplier
    (reference AdamWeightDecay.scala warmupPortion semantics)."""

    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        warm = jnp.maximum(warmup_steps, 1)
        lin = jnp.maximum(total_steps - warmup_steps, 1)
        return jnp.where(
            step < warmup_steps,
            step / warm,
            jnp.maximum(0.0, 1.0 - (step - warmup_steps) / lin),
        )

    return fn


def warmup_epoch_decay(
    warmup_steps: int,
    steps_per_epoch: int,
    boundaries_epochs=(30, 60, 80),
    decay: float = 0.1,
    warmup_start: float = 0.0,
) -> Schedule:
    """ResNet-ImageNet schedule: linear warmup then step decay at epoch
    boundaries (reference examples/resnet/TrainImageNet.scala:36-120:
    warmup + decay 0.1 @ epochs 30/60/80)."""

    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.minimum(step / jnp.maximum(warmup_steps, 1), 1.0)
        warm = warmup_start + (1.0 - warmup_start) * frac
        epoch = step / steps_per_epoch
        mult = jnp.asarray(1.0, jnp.float32)
        for b in boundaries_epochs:
            mult = mult * jnp.where(epoch >= b, decay, 1.0)
        return jnp.where(step < warmup_steps, warm, mult)

    return fn


def poly_decay(power: float, max_steps: int) -> Schedule:
    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        return jnp.maximum(0.0, (1.0 - step / max_steps)) ** power

    return fn


class Optimizer:
    """An optax transform + learning-rate schedule, Keras-1-flavored."""

    def __init__(self, tx: optax.GradientTransformation, name: str,
                 learning_rate: float = 0.01,
                 schedule: Optional[Schedule] = None):
        self.name = name
        self.learning_rate = learning_rate
        self.schedule = schedule
        self._tx = tx

    # -- optax protocol ---------------------------------------------------
    def init(self, params):
        return self._tx.init(params)

    def update(self, grads, opt_state, params=None):
        return self._tx.update(grads, opt_state, params)

    def current_lr(self, step: int) -> float:
        if self.schedule is None:
            return float(self.learning_rate)
        return float(self.learning_rate * self.schedule(step))


def _scheduled(lr, schedule):
    if schedule is None:
        return lr
    return lambda step: lr * schedule(step)


class SGD(Optimizer):
    def __init__(self, lr=0.01, momentum=0.0, decay=0.0, nesterov=False,
                 weight_decay=0.0, schedule: Optional[Schedule] = None):
        sched = schedule
        if decay and sched is None:
            sched = lambda step: 1.0 / (1.0 + decay * step)
        chain = []
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay))
        chain.append(
            optax.sgd(_scheduled(lr, sched), momentum=momentum or None,
                      nesterov=nesterov)
        )
        super().__init__(optax.chain(*chain), "sgd", lr, sched)


class Adam(Optimizer):
    """Reference keras/optimizers/Adam.scala (schedule-aware Adam)."""

    def __init__(self, lr=0.001, beta_1=0.9, beta_2=0.999, epsilon=1e-8,
                 decay=0.0, schedule: Optional[Schedule] = None):
        sched = schedule
        if decay and sched is None:
            sched = lambda step: 1.0 / (1.0 + decay * step)
        tx = optax.adam(_scheduled(lr, sched), b1=beta_1, b2=beta_2,
                        eps=epsilon)
        super().__init__(tx, "adam", lr, sched)
        # the exact optax.adam arguments, so the kernel plane
        # (ops/pallas/fused_adam.py) can rebuild a transform whose inner
        # chain — and therefore state structure and fallback trajectory —
        # is identical to self._tx
        self.hyperparams = {"learning_rate": _scheduled(lr, sched),
                            "b1": beta_1, "b2": beta_2, "eps": epsilon}


class AdamWeightDecay(Optimizer):
    """Decoupled weight decay + warmup/linear-decay (reference
    keras/optimizers/AdamWeightDecay.scala, used by BERT)."""

    def __init__(self, lr=0.001, warmup_portion=-1.0, total=-1,
                 schedule=None, beta_1=0.9, beta_2=0.999, epsilon=1e-6,
                 weight_decay=0.01):
        sched = schedule
        if sched is None and total > 0:
            warmup = int(max(warmup_portion, 0.0) * total)
            sched = warmup_linear_decay(warmup, total)
        tx = optax.adamw(_scheduled(lr, sched), b1=beta_1, b2=beta_2,
                         eps=epsilon, weight_decay=weight_decay)
        super().__init__(tx, "adamw", lr, sched)


class RMSprop(Optimizer):
    def __init__(self, lr=0.001, rho=0.9, epsilon=1e-8,
                 schedule: Optional[Schedule] = None):
        tx = optax.rmsprop(_scheduled(lr, schedule), decay=rho, eps=epsilon)
        super().__init__(tx, "rmsprop", lr, schedule)


class Adagrad(Optimizer):
    def __init__(self, lr=0.01, epsilon=1e-8,
                 schedule: Optional[Schedule] = None):
        tx = optax.adagrad(_scheduled(lr, schedule), eps=epsilon)
        super().__init__(tx, "adagrad", lr, schedule)


class Adadelta(Optimizer):
    def __init__(self, lr=1.0, rho=0.95, epsilon=1e-8,
                 schedule: Optional[Schedule] = None):
        tx = optax.adadelta(_scheduled(lr, schedule), rho=rho, eps=epsilon)
        super().__init__(tx, "adadelta", lr, schedule)


class Adamax(Optimizer):
    def __init__(self, lr=0.002, beta_1=0.9, beta_2=0.999, epsilon=1e-8,
                 schedule: Optional[Schedule] = None):
        tx = optax.adamax(_scheduled(lr, schedule), b1=beta_1, b2=beta_2,
                          eps=epsilon)
        super().__init__(tx, "adamax", lr, schedule)


_OPTIMIZERS = {
    "sgd": SGD,
    "adam": Adam,
    "adamw": AdamWeightDecay,
    "rmsprop": RMSprop,
    "adagrad": Adagrad,
    "adadelta": Adadelta,
    "adamax": Adamax,
}


def get_optimizer(identifier) -> Optimizer:
    if isinstance(identifier, Optimizer):
        return identifier
    if isinstance(identifier, str) and identifier.lower() in _OPTIMIZERS:
        return _OPTIMIZERS[identifier.lower()]()
    if isinstance(identifier, optax.GradientTransformation):
        return Optimizer(identifier, "optax", 0.0)
    raise ValueError(f"unknown optimizer {identifier!r}")
