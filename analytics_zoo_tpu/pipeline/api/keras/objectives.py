"""Loss functions — Keras-1 names, JAX-native implementations.

Mirrors the reference's 15 loss wrappers under
``pipeline/api/keras/objectives/*.scala`` (SparseCategoricalCrossEntropy,
BinaryCrossEntropy, CategoricalCrossEntropy, KullbackLeiblerDivergence, hinge
variants, Poisson, CosineProximity, RankHinge, MeanSquaredError, ...).  The
reference wraps BigDL Criterions that run forward/backward natively; here each
loss is a pure ``fn(y_true, y_pred) -> per-sample loss`` differentiated by
``jax.grad`` — the role the reference fills with hand-written backward passes.

All losses reduce over non-batch axes and return shape ``(batch,)``; the
training loop takes the (possibly weighted) mean.  This keeps per-sample
weighting and sequence masking composable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-7


class LossFunction:
    """Callable loss with a name; subclass or wrap a function."""

    def __init__(self, fn, name):
        self.fn = fn
        self.name = name

    def __call__(self, y_true, y_pred):
        return self.fn(y_true, y_pred)

    def mean(self, y_true, y_pred, sample_weight=None):
        per_sample = self(y_true, y_pred)
        if sample_weight is not None:
            return jnp.sum(per_sample * sample_weight) / (
                jnp.sum(sample_weight) + _EPS
            )
        return jnp.mean(per_sample)


def _align(y_true, y_pred):
    """Reshape y_true to y_pred's shape when they hold the same number of
    elements.  Guards the classic silent-broadcast bug: (B,) targets vs
    (B, 1) predictions would otherwise broadcast to (B, B) inside an
    elementwise loss."""
    ts, ps = jnp.shape(y_true), jnp.shape(y_pred)
    if ts == ps:
        return y_true
    import math
    if math.prod(ts) == math.prod(ps):
        return jnp.reshape(y_true, ps)
    raise ValueError(
        f"loss target shape {ts} is incompatible with prediction shape {ps}"
    )


def _reduce_rest(x):
    """Mean over all non-batch axes -> (batch,)."""
    if x.ndim <= 1:
        return x
    return jnp.mean(x.reshape(x.shape[0], -1), axis=-1)


def _sum_rest(x):
    if x.ndim <= 1:
        return x
    return jnp.sum(x.reshape(x.shape[0], -1), axis=-1)


def mean_squared_error(y_true, y_pred):
    y_true = _align(y_true, y_pred)
    return _reduce_rest((y_pred - y_true) ** 2)


def mean_absolute_error(y_true, y_pred):
    y_true = _align(y_true, y_pred)
    return _reduce_rest(jnp.abs(y_pred - y_true))


def mean_absolute_percentage_error(y_true, y_pred):
    y_true = _align(y_true, y_pred)
    diff = jnp.abs((y_true - y_pred) / jnp.clip(jnp.abs(y_true), _EPS))
    return 100.0 * _reduce_rest(diff)


def mean_squared_logarithmic_error(y_true, y_pred):
    y_true = _align(y_true, y_pred)
    a = jnp.log(jnp.clip(y_pred, _EPS) + 1.0)
    b = jnp.log(jnp.clip(y_true, _EPS) + 1.0)
    return _reduce_rest((a - b) ** 2)


def binary_crossentropy(y_true, y_pred):
    """Expects probabilities in (0,1) (reference BinaryCrossEntropy.scala)."""
    y_true = _align(y_true, y_pred)
    y_pred = jnp.clip(y_pred, _EPS, 1.0 - _EPS)
    return _reduce_rest(
        -(y_true * jnp.log(y_pred) + (1.0 - y_true) * jnp.log1p(-y_pred))
    )


def binary_crossentropy_from_logits(y_true, logits):
    logits = logits.astype(jnp.float32)  # f32 CE under bf16 compute
    return _reduce_rest(
        jnp.maximum(logits, 0) - logits * y_true
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def categorical_crossentropy(y_true, y_pred):
    """One-hot targets, probability predictions
    (reference CategoricalCrossEntropy.scala)."""
    y_pred = y_pred / jnp.clip(
        jnp.sum(y_pred, axis=-1, keepdims=True), _EPS
    )
    y_pred = jnp.clip(y_pred, _EPS, 1.0)
    return _sum_rest(-y_true * jnp.log(y_pred))


def sparse_categorical_crossentropy(y_true, y_pred):
    """Integer targets, probability predictions (reference
    SparseCategoricalCrossEntropy.scala; BigDL zero-based labels)."""
    y_pred = jnp.clip(y_pred, _EPS, 1.0)
    logp = jnp.log(y_pred)
    labels = y_true.astype(jnp.int32)
    if labels.ndim == logp.ndim:
        labels = labels.squeeze(-1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if picked.ndim > 1:
        picked = picked.reshape(picked.shape[0], -1).mean(axis=-1)
    return -picked


def sparse_categorical_crossentropy_from_logits(y_true, logits):
    labels = y_true.astype(jnp.int32)
    if labels.ndim == logits.ndim:
        labels = labels.squeeze(-1)
    if logits.ndim == 2 and labels.ndim == 1:
        # Kernel plane: a plan routing loss.softmax_xent to the fused
        # pallas kernel computes lse - logits[label] without ever
        # materializing the (B, V) log-prob tensor in HBM — numerically
        # the same f32 quantity as the log_softmax path below.  Only
        # the plain (B, V) + (B,) shape routes; anything else (and any
        # plan picking "xla" or carrying no table) takes the XLA path.
        from analytics_zoo_tpu.parallel.plan import resolve_kernel

        if resolve_kernel("loss.softmax_xent") == "fused_softmax_xent":
            from analytics_zoo_tpu.ops.pallas.fused_softmax_xent import (
                softmax_xent,
            )

            return softmax_xent(logits, labels)
    # f32 softmax-CE regardless of compute dtype: a bf16 log-softmax over
    # a 32k-vocab axis loses the tail of the normalizer; the upcast fuses
    # into the reduction while the lm-head matmul stays bf16
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if picked.ndim > 1:
        picked = picked.reshape(picked.shape[0], -1).mean(axis=-1)
    return -picked


def kullback_leibler_divergence(y_true, y_pred):
    y_true_c = jnp.clip(y_true, _EPS, 1.0)
    y_pred_c = jnp.clip(y_pred, _EPS, 1.0)
    return _sum_rest(y_true_c * jnp.log(y_true_c / y_pred_c))


def poisson(y_true, y_pred):
    y_true = _align(y_true, y_pred)
    return _reduce_rest(y_pred - y_true * jnp.log(y_pred + _EPS))


def cosine_proximity(y_true, y_pred):
    def l2(x):
        return x / jnp.clip(
            jnp.linalg.norm(x, axis=-1, keepdims=True), _EPS
        )
    return -_sum_rest(l2(y_true) * l2(y_pred))


def hinge(y_true, y_pred):
    y_true = _align(y_true, y_pred)
    return _reduce_rest(jnp.maximum(1.0 - y_true * y_pred, 0.0))


def squared_hinge(y_true, y_pred):
    y_true = _align(y_true, y_pred)
    return _reduce_rest(jnp.maximum(1.0 - y_true * y_pred, 0.0) ** 2)


def rank_hinge(y_true, y_pred, margin: float = 1.0):
    """Pairwise ranking hinge for (pos, neg)-interleaved batches — reference
    RankHinge.scala (used by KNRM text matching).  Expects batch laid out as
    alternating positive/negative pairs."""
    pos = y_pred[0::2]
    neg = y_pred[1::2]
    loss = jnp.maximum(0.0, margin - pos + neg)
    return jnp.repeat(_reduce_rest(loss), 2)[: y_pred.shape[0]]


_LOSSES = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "mape": mean_absolute_percentage_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "binary_crossentropy": binary_crossentropy,
    "binary_crossentropy_from_logits": binary_crossentropy_from_logits,
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "sparse_categorical_crossentropy_from_logits":
        sparse_categorical_crossentropy_from_logits,
    "kld": kullback_leibler_divergence,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "rank_hinge": rank_hinge,
}

# Class-style aliases matching reference objective class names
# (pipeline/api/keras/objectives/*.scala).
def MeanSquaredError():
    return LossFunction(mean_squared_error, "mse")


def MeanAbsoluteError():
    return LossFunction(mean_absolute_error, "mae")


def BinaryCrossEntropy():
    return LossFunction(binary_crossentropy, "binary_crossentropy")


def CategoricalCrossEntropy():
    return LossFunction(categorical_crossentropy, "categorical_crossentropy")


def SparseCategoricalCrossEntropy():
    return LossFunction(sparse_categorical_crossentropy,
                        "sparse_categorical_crossentropy")


class RankHinge(LossFunction):
    """Pairwise ranking hinge (reference RankHinge.scala)."""

    def __init__(self, margin: float = 1.0):
        self.margin = margin
        super().__init__(self._fn, "rank_hinge")

    def _fn(self, y_true, y_pred):
        return rank_hinge(y_true, y_pred, self.margin)


def get_loss(identifier) -> LossFunction:
    if isinstance(identifier, LossFunction):
        return identifier
    if callable(identifier):
        return LossFunction(identifier,
                            getattr(identifier, "__name__", "custom"))
    if isinstance(identifier, str):
        key = identifier.lower()
        if key in _LOSSES:
            return LossFunction(_LOSSES[key], key)
    raise ValueError(f"unknown loss {identifier!r}")
