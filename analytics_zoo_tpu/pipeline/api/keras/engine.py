"""Keras-1-style layer/graph engine, re-designed for JAX.

The reference implements this surface as Scala wrappers over BigDL's mutable
``KerasLayer`` modules (reference pipeline/api/keras/layers/*.scala, ~120
files; graph topology in pipeline/api/keras/models/Topology.scala).  The
TPU-native re-design is *functional*: a ``Layer`` owns only static config and
weight *specs*; parameters and mutable state (e.g. BatchNorm running stats)
live in pytrees threaded through pure ``call`` functions, so an entire model
lowers to one jit-compiled XLA program (no per-layer native calls as in the
reference's MKL/JNI path).

Symbolic graph building (``Input``/``Variable``/``Node``) plays the role of
the reference's autograd ``Variable`` over BigDL ``ModuleNode``
(pipeline/api/autograd/math.scala:365-612): calling a layer on Variables
records a node; ``Model(inputs, outputs)`` topologically sorts the recorded
graph into a pure function.

Shape convention (Keras-1, matching the reference's ``computeOutputShape``):
user-facing ``input_shape`` excludes the batch dim; internal full shapes carry
``None`` in position 0.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common.utils import to_tuple_shape

# ---------------------------------------------------------------------------
# Weight specs & initializers
# ---------------------------------------------------------------------------

_INIT_FNS = {}


def register_init(name):
    def deco(fn):
        _INIT_FNS[name] = fn
        return fn
    return deco


@register_init("zero")
def _zero(rng, shape, dtype):
    return jnp.zeros(shape, dtype)


@register_init("one")
def _one(rng, shape, dtype):
    return jnp.ones(shape, dtype)


@register_init("glorot_uniform")
def _glorot_uniform(rng, shape, dtype):
    fan_in, fan_out = _compute_fans(shape)
    limit = np.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


@register_init("glorot_normal")
def _glorot_normal(rng, shape, dtype):
    fan_in, fan_out = _compute_fans(shape)
    std = np.sqrt(2.0 / (fan_in + fan_out))
    return std * jax.random.normal(rng, shape, dtype)


@register_init("he_normal")
def _he_normal(rng, shape, dtype):
    fan_in, _ = _compute_fans(shape)
    std = np.sqrt(2.0 / fan_in)
    return std * jax.random.normal(rng, shape, dtype)


@register_init("he_uniform")
def _he_uniform(rng, shape, dtype):
    fan_in, _ = _compute_fans(shape)
    limit = np.sqrt(6.0 / fan_in)
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


@register_init("lecun_uniform")
def _lecun_uniform(rng, shape, dtype):
    fan_in, _ = _compute_fans(shape)
    limit = np.sqrt(3.0 / fan_in)
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


@register_init("uniform")
def _uniform(rng, shape, dtype):
    return jax.random.uniform(rng, shape, dtype, -0.05, 0.05)


@register_init("normal")
def _normal(rng, shape, dtype):
    return 0.05 * jax.random.normal(rng, shape, dtype)


@register_init("orthogonal")
def _orthogonal(rng, shape, dtype):
    return jax.nn.initializers.orthogonal()(rng, shape, dtype)


def _compute_fans(shape):
    """Fan-in/fan-out for conv kernels shaped (..spatial.., in, out) and
    dense kernels shaped (in, out)."""
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive


class NamedInit:
    """Picklable by-name initializer."""

    def __init__(self, name):
        self.name = name

    def __call__(self, rng, shape, dtype):
        return _INIT_FNS[self.name](rng, shape, dtype)

    def __repr__(self):
        return f"init({self.name})"


class ConstInit:
    def __init__(self, value):
        self.value = value

    def __call__(self, rng, shape, dtype):
        return jnp.full(shape, self.value, dtype)


def get_initializer(init) -> Callable:
    """Resolve an init spec (name, callable, or constant) to rng->array fn.

    Mirrors the reference's ``init`` string args on layers (e.g. Dense
    ``init="glorot_uniform"``, keras/layers/core.scala Dense docs).
    """
    if isinstance(init, (int, float)):
        return ConstInit(init)
    if callable(init):
        return init
    if isinstance(init, str) and init in _INIT_FNS:
        return NamedInit(init)
    raise ValueError(f"unknown initializer {init!r}")


class WeightSpec(
    collections.namedtuple("WeightSpec", "name shape init dtype trainable")
):
    pass


# ---------------------------------------------------------------------------
# Symbolic tensors (Variable) and graph nodes
# ---------------------------------------------------------------------------

_uid_counters: dict[str, itertools.count] = collections.defaultdict(
    lambda: itertools.count(1)
)


def unique_name(prefix: str) -> str:
    return f"{prefix}_{next(_uid_counters[prefix])}"


def reset_name_counters() -> None:
    _uid_counters.clear()


class Node:
    """One application of a layer to symbolic inputs."""

    def __init__(self, layer: "Layer", inbound: list["Variable"],
                 outputs: list["Variable"]):
        self.layer = layer
        self.inbound = inbound
        self.outputs = outputs


class Variable:
    """A symbolic tensor: output slot of a Node.

    The TPU-native analogue of the reference autograd ``Variable`` wrapping a
    BigDL ``ModuleNode`` (pipeline/api/autograd/math.scala:365-612).  Math
    operators live in :mod:`analytics_zoo_tpu.pipeline.api.autograd` which
    monkey-patches them onto this class (single class, no wrapper layers).
    """

    def __init__(self, node: Node | None, index: int, shape: tuple,
                 name: str | None = None):
        self.node = node
        self.index = index
        self.shape = tuple(shape)  # full shape, batch dim = None
        self.name = name or unique_name("variable")

    def __repr__(self):
        return f"Variable(name={self.name}, shape={self.shape})"


def Input(shape=None, name: str | None = None) -> Variable:
    """Symbolic model input; ``shape`` excludes the batch dim.

    Reference: ``Input`` autograd/math py + keras (pyzoo
    pipeline/api/keras/layers/topology Input; Scala Topology.scala Input).
    """
    shape = to_tuple_shape(shape)
    layer = InputLayer(input_shape=shape, name=name)
    var = Variable(None, 0, (None,) + shape, name=layer.name)
    node = Node(layer, [], [var])
    var.node = node
    return var


# ---------------------------------------------------------------------------
# Layer base
# ---------------------------------------------------------------------------


class Layer:
    """Base layer: static config + weight specs; pure functional ``call``.

    Contract (TPU re-design of BigDL ``KerasLayer``):
      - ``build(input_shape)``: declare weights/state via ``add_weight`` /
        ``add_state`` given the (batch-less) input shape.
      - ``call(params, inputs, state=None, training=False, rng=None)``: pure;
        returns outputs, or ``(outputs, new_state)`` if the layer is stateful.
      - ``compute_output_shape(input_shape)``: shape inference, mirroring the
        reference's ``computeOutputShape`` on every layer.
    """

    def __init__(self, input_shape=None, name: str | None = None, **kwargs):
        cls = type(self).__name__.lower()
        # Auto-named layers are canonically renamed when adopted by a
        # container (position-based), so param-tree keys depend only on model
        # structure — not on how many models were built earlier in the
        # process.  Checkpoints therefore resume across fresh processes.
        self._auto_named = name is None
        self.name = name or unique_name(cls)
        self.built = False
        self._weight_specs: list[WeightSpec] = []
        self._state_specs: list[WeightSpec] = []
        self._input_shape = (
            to_tuple_shape(input_shape) if input_shape is not None else None
        )
        self._build_shape = None
        self._config = {}
        if kwargs:
            raise TypeError(f"{type(self).__name__}: unexpected args {kwargs}")

    # -- weights ----------------------------------------------------------
    def add_weight(self, name, shape, init="glorot_uniform",
                   dtype=jnp.float32, trainable=True):
        spec = WeightSpec(name, tuple(int(s) for s in shape),
                          get_initializer(init), dtype, trainable)
        if trainable:
            self._weight_specs.append(spec)
        else:
            self._state_specs.append(spec)
        return spec

    def add_state(self, name, shape, init="zero", dtype=jnp.float32):
        return self.add_weight(name, shape, init, dtype, trainable=False)

    # -- build / init -----------------------------------------------------
    def build(self, input_shape):  # pragma: no cover - default no-op
        del input_shape

    def ensure_built(self, input_shape):
        if not self.built:
            self._weight_specs.clear()
            self._state_specs.clear()
            self.build(input_shape)
            self._build_shape = input_shape
            self.built = True
        return self._build_shape

    def init_params(self, rng) -> dict:
        assert self.built, f"{self.name}: init_params before build"
        params = {}
        for i, spec in enumerate(self._weight_specs):
            params[spec.name] = spec.init(
                jax.random.fold_in(rng, i), spec.shape, spec.dtype
            )
        return params

    def init_state(self) -> dict:
        state = {}
        for spec in self._state_specs:
            state[spec.name] = spec.init(
                jax.random.PRNGKey(0), spec.shape, spec.dtype
            )
        return state

    @property
    def stateful(self) -> bool:
        return bool(self._state_specs)

    # -- forward ----------------------------------------------------------
    def call(self, params, inputs, state=None, training=False, rng=None):
        raise NotImplementedError

    def apply(self, params, inputs, state=None, training=False, rng=None):
        """Normalized forward: always returns (outputs, new_state)."""
        out = self.call(params, inputs, state=state, training=training,
                        rng=rng)
        if self.stateful or isinstance(self, _ContainerBase):
            return out  # stateful layers return (out, state) themselves
        return out, state

    # -- shapes -----------------------------------------------------------
    def compute_output_shape(self, input_shape):
        return input_shape

    # -- symbolic call ----------------------------------------------------
    def __call__(self, x):
        single = not isinstance(x, (list, tuple))
        xs = [x] if single else list(x)
        for v in xs:
            if not isinstance(v, Variable):
                raise TypeError(
                    f"{self.name} called on non-symbolic input {type(v)}; "
                    "use .apply(params, inputs) for concrete arrays"
                )
        in_shapes = [v.shape[1:] for v in xs]
        build_shape = in_shapes[0] if single else in_shapes
        self.ensure_built(build_shape)
        out_shape = self.compute_output_shape(
            xs[0].shape if single else [v.shape for v in xs]
        )
        multi = (isinstance(out_shape, list))
        out_shapes = out_shape if multi else [out_shape]
        outs = [Variable(None, i, s) for i, s in enumerate(out_shapes)]
        node = Node(self, xs, outs)
        for v in outs:
            v.node = node
        return outs if multi else outs[0]

    # -- misc -------------------------------------------------------------
    def param_count(self) -> int:
        assert self.built
        return sum(int(np.prod(s.shape)) for s in self._weight_specs) + sum(
            int(np.prod(s.shape)) for s in self._state_specs
        )

    def get_config(self) -> dict:
        return dict(self._config)

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name})"


class InputLayer(Layer):
    def __init__(self, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.built = True
        self._build_shape = self._input_shape

    def call(self, params, inputs, state=None, training=False, rng=None):
        return inputs

    def compute_output_shape(self, input_shape):
        return input_shape


class _ContainerBase(Layer):
    """Marker base for containers (Sequential/Model) whose ``call`` always
    returns (outputs, state)."""


# ---------------------------------------------------------------------------
# Graph executor (shared by Model and autograd-built graphs)
# ---------------------------------------------------------------------------


def canonicalize_names(layers: Sequence["Layer"]) -> None:
    """Rename auto-named layers to position-based canonical names within a
    container (``dense_0``, ``dense_1``, ... in adoption order).  Must run
    before params are materialized."""
    taken = {l.name for l in layers if not l._auto_named}
    counters: dict[str, int] = collections.defaultdict(int)
    for layer in layers:
        if not layer._auto_named:
            continue
        cls = type(layer).__name__.lower()
        while True:
            cand = f"{cls}_{counters[cls]}"
            counters[cls] += 1
            if cand not in taken:
                break
        layer.name = cand
        taken.add(cand)
        layer._auto_named = False
    names = [l.name for l in layers]
    if len(names) != len(set(names)):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise ValueError(
            f"duplicate layer names in one container: {dupes}; rename the "
            "layers (layers adopted from different containers can collide)"
        )


def topological_nodes(outputs: Sequence[Variable]) -> list[Node]:
    """Topologically sorted nodes reaching ``outputs`` (inputs first)."""
    order: list[Node] = []
    seen: set[int] = set()

    def visit(node: Node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for v in node.inbound:
            visit(v.node)
        order.append(node)

    for v in outputs:
        visit(v.node)
    return order


class GraphFunction:
    """Executable pure function compiled from a symbolic graph.

    Plays the role of BigDL ``StaticGraph`` under the reference's ``Model``
    (Topology.scala:602-759), but as data: a node list + param/state pytrees
    keyed by layer name, executed with jnp — jit/grad/vmap-compatible.
    """

    def __init__(self, inputs: Sequence[Variable], outputs: Sequence[Variable]):
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.nodes = topological_nodes(self.outputs)
        self.layers: list[Layer] = []
        seen_layers = set()
        for node in self.nodes:
            if id(node.layer) not in seen_layers:
                seen_layers.add(id(node.layer))
                self.layers.append(node.layer)
        canonicalize_names(self.layers)
        input_ids = {id(v) for v in self.inputs}
        for node in self.nodes:
            if isinstance(node.layer, InputLayer):
                if node.outputs and id(node.outputs[0]) not in input_ids:
                    raise ValueError(
                        "graph contains an Input not listed in `inputs`"
                    )

    def init(self, rng) -> tuple[dict, dict]:
        params, state = {}, {}
        for i, layer in enumerate(self.layers):
            if isinstance(layer, InputLayer):
                continue
            p = layer.init_params(jax.random.fold_in(rng, i))
            if p:
                params[layer.name] = p
            s = layer.init_state()
            if s:
                state[layer.name] = s
        return params, state

    def __call__(self, params, inputs, state=None, training=False, rng=None):
        state = state or {}
        values: dict[int, Any] = {}
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if len(xs) != len(self.inputs):
            raise ValueError(
                f"expected {len(self.inputs)} inputs, got {len(xs)}"
            )
        for var, x in zip(self.inputs, xs):
            values[id(var)] = x
        new_state = dict(state)
        for i, node in enumerate(self.nodes):
            layer = node.layer
            if isinstance(layer, InputLayer):
                continue
            args = [values[id(v)] for v in node.inbound]
            arg = args[0] if len(args) == 1 else args
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            out, s = layer.apply(
                params.get(layer.name, {}), arg,
                state=new_state.get(layer.name),
                training=training, rng=lrng,
            )
            if s:  # {} stays omitted, mirroring init's `if s:` filter
                new_state[layer.name] = s
            outs = out if isinstance(out, (list, tuple)) else [out]
            for v, o in zip(node.outputs, outs):
                values[id(v)] = o
        results = [values[id(v)] for v in self.outputs]
        result = results[0] if len(results) == 1 else results
        return result, new_state
