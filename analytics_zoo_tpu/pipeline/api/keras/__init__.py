"""Keras-1-style API (mirrors reference pyzoo/zoo/pipeline/api/keras)."""

from analytics_zoo_tpu.pipeline.api.keras.engine import (  # noqa: F401
    Input,
    Layer,
    Variable,
)
from analytics_zoo_tpu.pipeline.api.keras.topology import (  # noqa: F401
    KerasNet,
    Model,
    Sequential,
    merge,
)
