"""Unified model import surface.

Reference: pipeline/api/Net.scala:90-263 (``Net.load`` /``loadBigDL``/
``loadTorch``/``loadCaffe``/``loadTF``) plus the pipeline/api/net package
(TFNet/TorchNet).  One ``Net`` facade dispatching to the per-format
loaders; heavy backends import lazily and raise a clear error when their
runtime is unavailable.
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.net.torch_net import (  # noqa: F401
    TorchCriterion,
    TorchNet,
    import_state_dict,
)
from analytics_zoo_tpu.pipeline.api.net.tf_net import TFNet  # noqa: F401


class Net:
    """Reference Net.scala:90-263 — static loaders per serialized format."""

    @staticmethod
    def load(path):
        """Load a model saved by this framework (``KerasNet.save``;
        reference ``Net.load`` for the zoo/BigDL format)."""
        from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet

        return KerasNet.load(path)

    # the reference's loadBigDL is its own-format loader; ours is load()
    load_bigdl = load

    @staticmethod
    def load_torch(path, **kwargs):
        """TorchScript archive → :class:`TorchNet` (reference
        ``Net.loadTorch`` Net.scala:~150)."""
        return TorchNet.load(path, **kwargs)

    @staticmethod
    def load_tf(path, input_name=None, output_name=None, **kwargs):
        """Frozen GraphDef or SavedModel dir → :class:`TFNet` (reference
        ``Net.loadTF`` Net.scala:~170)."""
        import os

        if os.path.isdir(path):
            return TFNet.from_saved_model(path, **kwargs)
        if input_name is None or output_name is None:
            raise ValueError(
                "loading a frozen GraphDef requires input_name/output_name"
            )
        return TFNet.from_frozen(path, input_name, output_name, **kwargs)

    @staticmethod
    def load_onnx(path_or_bytes):
        """ONNX model → zoo keras graph (reference
        pyzoo/zoo/pipeline/api/onnx loader)."""
        from analytics_zoo_tpu.pipeline.api.onnx import load_onnx

        return load_onnx(path_or_bytes)

    @staticmethod
    def load_caffe(def_path, model_path=None):
        """Caffe prototxt (+ optional caffemodel weights) → zoo keras graph
        (reference ``Net.loadCaffe`` → models/caffe CaffeLoader.scala)."""
        from analytics_zoo_tpu.models.caffe import load_caffe

        return load_caffe(def_path, model_path)
