"""TFNet — run TensorFlow graphs inside the TPU framework.

Reference: pipeline/api/net/TFNet.scala:53-250 (frozen GraphDef executed
through libtensorflow JNI as a BigDL module; forward feeds inputs+weights
:173-250, backward runs a TF-generated gradient subgraph :278) and
TFNetForInference.scala (SavedModel variant).

TPU re-design: models should be jax-native (SURVEY.md §2.1 marks TFNet
"capability covered by jax.jit"), so TFNet exists as the compatibility
escape hatch: the TF function runs on the host CPU via
``jax.pure_callback`` wrapped in ``jax.custom_vjp`` (input gradients via
``tf.GradientTape``, the role of the reference's gradient subgraph).
Gated on the ``tensorflow`` import.
"""

from __future__ import annotations

import numpy as np
import jax

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


def _tf():
    try:
        import tensorflow as tf
    except Exception as e:  # pragma: no cover
        raise ImportError(
            "TFNet requires tensorflow, which is not available in this "
            "environment"
        ) from e
    return tf


class TFNet(Layer):
    """A frozen TF computation as a zoo Layer.

    Construct with any callable ``tf_fn(tf.Tensor) -> tf.Tensor`` (e.g. a
    ``tf.function`` concrete function); classmethods cover the reference's
    load paths: ``from_frozen`` (GraphDef .pb ≈ TFNet.scala:53) and
    ``from_saved_model`` (≈ TFNetForInference.scala).
    """

    def __init__(self, tf_fn, output_shape=None, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.tf_fn = tf_fn
        self._fixed_out_shape = (
            tuple(output_shape) if output_shape is not None else None
        )
        self._out_shapes: dict = {}  # per-input-shape cache

    @classmethod
    def from_frozen(cls, graph_def_path, input_name, output_name, **kwargs):
        """Load a frozen GraphDef ``.pb`` (reference TFNet(path) with
        input/output names, TFNet.scala:427-452)."""
        tf = _tf()
        gd = tf.compat.v1.GraphDef()
        with open(graph_def_path, "rb") as f:
            gd.ParseFromString(f.read())

        def imported(*args):
            return tf.compat.v1.import_graph_def(
                gd, input_map={input_name: args[0]},
                return_elements=[output_name],
            )[0]

        wrapped = tf.compat.v1.wrap_function(
            imported,
            [tf.TensorSpec(None, tf.float32)],
        )
        return cls(wrapped, **kwargs)

    @classmethod
    def from_saved_model(cls, export_dir, signature="serving_default",
                         **kwargs):
        """Load a SavedModel (reference TFNetForInference.scala)."""
        tf = _tf()
        sm = tf.saved_model.load(export_dir)
        fn = sm.signatures[signature]

        def call_fn(x):
            out = fn(x)
            if isinstance(out, dict):
                out = next(iter(out.values()))
            return out

        net = cls(call_fn, **kwargs)
        net._saved_model = sm  # keep variables alive
        return net

    @classmethod
    def from_keras(cls, keras_model, **kwargs):
        """Wrap a live tf.keras model (reference TFNet.fromKeras)."""
        return cls(lambda x: keras_model(x, training=False), **kwargs)

    def _infer_out_shape(self, input_shape):
        if self._fixed_out_shape is not None:
            return self._fixed_out_shape
        key = tuple(int(s) for s in input_shape)
        out = self._out_shapes.get(key)
        if out is None:  # shape-dependent graphs get a probe per shape
            tf = _tf()
            y = self.tf_fn(tf.zeros((1,) + key, tf.float32))
            out = self._out_shapes[key] = tuple(
                int(s) for s in y.shape[1:]
            )
        return out

    def build(self, input_shape):
        self._infer_out_shape(input_shape)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self._infer_out_shape(input_shape[1:])

    def call(self, params, inputs, state=None, training=False, rng=None):
        tf = _tf()
        out_shape = self._infer_out_shape(inputs.shape[1:])
        tf_fn = self.tf_fn

        @jax.custom_vjp
        def tf_apply(x):
            def host(xh):
                return np.asarray(
                    tf_fn(tf.convert_to_tensor(np.ascontiguousarray(xh)))
                )

            return jax.pure_callback(
                host,
                jax.ShapeDtypeStruct((x.shape[0],) + out_shape, x.dtype),
                x,
            )

        def fwd(x):
            return tf_apply(x), x

        def bwd(x, g):
            def host(xh, gh):
                xt = tf.convert_to_tensor(np.ascontiguousarray(xh))
                with tf.GradientTape() as tape:
                    tape.watch(xt)
                    y = tf_fn(xt)
                gx = tape.gradient(
                    y, xt,
                    output_gradients=tf.convert_to_tensor(
                        np.ascontiguousarray(gh)
                    ),
                )
                if gx is None:  # no gradient path (reference zeros
                    #                gradInput when no backward meta,
                    #                TFNet.scala:278)
                    return np.zeros_like(xh)
                return np.asarray(gx)

            gx = jax.pure_callback(
                host, jax.ShapeDtypeStruct(x.shape, x.dtype), x, g
            )
            return (gx,)

        tf_apply.defvjp(fwd, bwd)
        return tf_apply(inputs)
