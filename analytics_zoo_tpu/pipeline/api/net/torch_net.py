"""TorchNet / TorchCriterion — run PyTorch modules inside the TPU graph.

Reference: pipeline/api/net/TorchNet.scala:39-156 and TorchCriterion.scala
(TorchScript modules executed through libtorch JNI as BigDL modules;
python wrappers pyzoo/zoo/pipeline/api/net/torch_net.py /
torch_criterion.py trace an nn.Module and ship the bytes to the JVM).

TPU re-design: there is no JNI sandwich — the torch module runs on the
*host* CPU through ``jax.pure_callback``, wrapped in ``jax.custom_vjp`` so
``jax.grad`` through it triggers torch autograd on the host.  This is an
escape hatch for odd third-party models, exactly like the reference's
TorchNet (which also ran torch on CPU inside each executor); the idiomatic
path for production models is :func:`import_state_dict` — copy the weights
into native jax layers so the whole step stays on the TPU.
"""

from __future__ import annotations

import io

import numpy as np
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer


def _to_torch(x):
    import torch

    return torch.from_numpy(np.ascontiguousarray(x))


class TorchNet(Layer):
    """A frozen torch ``nn.Module`` as a zoo Layer (reference
    TorchNet.scala:39-156; one-model-per-executor special casing at
    Topology.scala:1101-1110 is unnecessary here — the callback is
    process-local).

    The module's parameters are captured at construction and are NOT
    trainable from the jax side (matching the reference, whose TorchNet
    exposes no gradWeight back to BigDL's all-reduce); the input gradient
    IS computed (via torch autograd), so a TorchNet can sit mid-graph.
    """

    def __init__(self, module, output_shape=None, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        import torch

        self.module = module.eval()
        for p in self.module.parameters():
            p.requires_grad_(False)
        self._fixed_out_shape = (
            tuple(output_shape) if output_shape is not None else None
        )
        self._out_shapes: dict = {}  # per-input-shape cache
        self._torch = torch

    # -- constructors matching the reference surface -----------------------
    @classmethod
    def from_pytorch(cls, module, input_shape=None, **kwargs):
        """Reference torch_net.py ``TorchNet.from_pytorch(module, ...)``."""
        return cls(module, input_shape=input_shape, **kwargs)

    @classmethod
    def load(cls, path, **kwargs):
        """Load a TorchScript archive saved with ``torch.jit.save``
        (reference TorchNet.scala loads TorchScript bytes)."""
        import torch

        return cls(torch.jit.load(path, map_location="cpu"), **kwargs)

    def save(self, path):
        import torch

        mod = self.module
        if not isinstance(mod, torch.jit.ScriptModule):
            mod = torch.jit.script(mod)
        torch.jit.save(mod, path)

    # -- shape inference ---------------------------------------------------
    def _infer_out_shape(self, input_shape):
        if self._fixed_out_shape is not None:
            return self._fixed_out_shape
        key = tuple(int(s) for s in input_shape)
        out = self._out_shapes.get(key)
        if out is None:  # shape-dependent graphs (e.g. fully-conv) get a
            #              fresh probe per input shape
            x = self._torch.zeros((1,) + key)
            with self._torch.no_grad():
                y = self.module(x)
            out = self._out_shapes[key] = tuple(y.shape[1:])
        return out

    def build(self, input_shape):
        self._infer_out_shape(input_shape)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self._infer_out_shape(input_shape[1:])

    # -- execution ---------------------------------------------------------
    def call(self, params, inputs, state=None, training=False, rng=None):
        out_shape = self._infer_out_shape(inputs.shape[1:])
        module, torch = self.module, self._torch

        @jax.custom_vjp
        def torch_apply(x):
            def fwd_host(xh):
                with torch.no_grad():
                    return module(_to_torch(xh)).numpy()

            return jax.pure_callback(
                fwd_host,
                jax.ShapeDtypeStruct((x.shape[0],) + out_shape, x.dtype),
                x,
            )

        def torch_fwd(x):
            return torch_apply(x), x

        def torch_bwd(x, g):
            def bwd_host(xh, gh):
                xt = _to_torch(xh).requires_grad_(True)
                y = module(xt)
                if not y.requires_grad:  # no grad path to the input —
                    #   zero gradInput like TFNet.scala:278; genuine
                    #   autograd errors still propagate
                    return np.zeros_like(xh)
                y.backward(_to_torch(gh))
                if xt.grad is None:
                    return np.zeros_like(xh)
                return xt.grad.numpy()

            gx = jax.pure_callback(
                bwd_host, jax.ShapeDtypeStruct(x.shape, x.dtype), x, g
            )
            return (gx,)

        torch_apply.defvjp(torch_fwd, torch_bwd)
        return torch_apply(inputs)


class TorchCriterion(Layer):
    """A torch loss as a zoo objective (reference TorchCriterion.scala;
    python wrapper torch_criterion.py traces ``loss_fn(input, label)``).

    Callable as ``crit(y_true, y_pred)`` returning the scalar batch loss —
    non-reducing torch losses (``reduction='none'``) are mean-reduced on the
    host — so it plugs into ``compile(loss=TorchCriterion.from_pytorch(...))``.
    """

    def __init__(self, loss_fn, name=None):
        super().__init__(name=name)
        import torch

        self.loss_fn = loss_fn
        self._torch = torch

    @classmethod
    def from_pytorch(cls, loss_fn, **kwargs):
        return cls(loss_fn, **kwargs)

    def __call__(self, y_true, y_pred):  # objective protocol
        loss_fn, torch = self.loss_fn, self._torch

        @jax.custom_vjp
        def crit(pred, true):
            def host(ph, th):
                with torch.no_grad():
                    val = loss_fn(_to_torch(ph), _to_torch(th))
                    if val.dim() > 0:  # reduction='none' losses
                        val = val.mean()
                return np.asarray(val.numpy(), dtype=ph.dtype).reshape(())

            return jax.pure_callback(
                host, jax.ShapeDtypeStruct((), pred.dtype), pred, true
            )

        def fwd(pred, true):
            return crit(pred, true), (pred, true)

        def bwd(res, g):
            pred, true = res

            def host(ph, th, gh):
                pt = _to_torch(ph).requires_grad_(True)
                val = loss_fn(pt, _to_torch(th))
                if val.dim() > 0:
                    val = val.mean()
                val.backward()
                return (pt.grad * float(gh)).numpy()

            gp = jax.pure_callback(
                host, jax.ShapeDtypeStruct(pred.shape, pred.dtype),
                pred, true, g,
            )
            return (gp, jnp.zeros_like(true))

        crit.defvjp(fwd, bwd)
        return crit(y_pred, y_true)

    def mean(self, y_true, y_pred, sample_weight=None):
        """Objective protocol used by the Estimator train step; torch
        criterions reduce to a scalar on the host, so per-sample weighting
        cannot be applied — reject it loudly rather than ignore it."""
        if sample_weight is not None:
            raise NotImplementedError(
                "TorchCriterion reduces to a scalar inside torch; "
                "sample_weight is not supported — use a native objective "
                "or fold the weights into the torch loss itself"
            )
        return self.__call__(y_true, y_pred)


def import_state_dict(model, state_dict, mapping):
    """Copy torch ``state_dict`` tensors into a zoo model's params pytree —
    the idiomatic TPU path for reusing pretrained torch weights (the
    capability TorchNet.scala provides by running torch itself).

    ``mapping``: list of ``(zoo_path, torch_key, transform)`` where
    ``zoo_path`` is a ``"layer/weight"`` key into the params dict and
    ``transform`` (optional) maps the numpy array (e.g. transpose
    OIHW→HWIO).  Returns the updated params.
    """
    params, _ = model.build_params()
    for entry in mapping:
        zoo_path, torch_key = entry[0], entry[1]
        transform = entry[2] if len(entry) > 2 else None
        arr = state_dict[torch_key].detach().cpu().numpy()
        if transform is not None:
            arr = transform(arr)
        node = params
        *parents, leaf = zoo_path.split("/")
        for p in parents:
            node = node[p]
        if node[leaf].shape != arr.shape:
            raise ValueError(
                f"{zoo_path}: shape {node[leaf].shape} != torch "
                f"{torch_key} {arr.shape}"
            )
        node[leaf] = jnp.asarray(arr)
    model.params = params
    return params
