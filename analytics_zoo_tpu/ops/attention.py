"""Attention ops — the single entry point every attention layer routes
through, so kernel upgrades (Pallas flash attention, ring attention over the
``seq`` mesh axis) swap in under one signature.

Reference behavior being covered: the O(L²) ``multiHeadSelfAttention`` inside
TransformerLayer.scala:137 and BERT.scala's attention with additive mask.
The reference materializes the full (L, L) score matrix per head on CPU; here
the default path is a blockwise-friendly jnp einsum that XLA fuses, and the
hot path is served by a Pallas kernel (ops/pallas) on TPU — including the
*training* configuration (attention dropout on, padded batch with a BERT
(B, 1, 1, L) additive mask): dropout lowers into the kernel via a
counter-based hash PRNG and broadcastable masks stream blockwise, so the
realistic path never falls back to the dense O(L²) route.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _flash_backend_ok() -> bool:
    # single source of truth for the backend gate (incl. the
    # ZOO_FLASH_INTERPRET CI knob) lives next to the kernel
    from analytics_zoo_tpu.ops.pallas.flash_attention import (
        _pallas_available,
    )

    return _pallas_available()


def flash_eligible(q_shape, mask_shape, mask_ndim, dropout_p, has_rng,
                   k_len, use_flash="auto"):
    """Pure routing predicate (backend check excluded) — unit-testable.

    Args mirror what :func:`dot_product_attention` sees: ``mask_shape`` is
    None or the mask's shape; flash handles masks broadcastable to
    (B|1, H|1, Lq|1, Lk).  Dropout needs an rng to derive the kernel seed.
    """
    if use_flash == False:  # noqa: E712
        return False
    b, h_, lq, d = q_shape[-4], q_shape[-3], q_shape[-2], q_shape[-1]
    # d % 64: the kernel sustains 76.7 TFLOP/s at head_dim 64
    # (FLASH_r03.json), which covers BERT-base/GPT-base head sizes
    if lq < 256 or d % 64 != 0:
        return False
    if dropout_p > 0.0 and not has_rng:
        return False
    if mask_shape is not None:
        if mask_ndim != 4:
            return False
        if (mask_shape[0] not in (1, b) or mask_shape[1] not in (1, h_)
                or mask_shape[2] not in (1, lq)
                or mask_shape[3] != k_len):
            return False
    return True


def dot_product_attention(q, k, v, mask=None, dropout_p=0.0, rng=None,
                          causal=False, scale=None, use_flash="auto"):
    """Batched multi-head attention.

    Args:
      q, k, v: (B, H, L, D) arrays.
      mask: optional additive mask broadcastable to (B, H, Lq, Lk) — 0 for
        keep, large-negative for drop (reference BERT attention_mask
        convention) — or a boolean mask (True = keep).
      dropout_p: attention-prob dropout (reference attnPDrop).
      causal: lower-triangular masking (reference TransformerLayer
        bidirectional=false path).
      scale: score scale; defaults to 1/sqrt(D).
    """
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    # Kernel plane: a plan's kernel_rules override the auto heuristic —
    # "xla" pins the dense jnp path, "flash" asks for the kernel (the
    # eligibility/backend checks still gate it: an ineligible shape
    # falls through to jnp rather than failing).  No active plan or no
    # "attention" rule leaves use_flash as passed.
    if use_flash == "auto":
        from analytics_zoo_tpu.parallel.plan import resolve_kernel

        pick = resolve_kernel("attention")
        if pick == "xla":
            use_flash = False
        elif pick == "flash":
            use_flash = True
    # Route big attention — masked, dropout, or clean — through the Pallas
    # flash kernel on TPU (O(L·D) HBM traffic); the jnp path serves small /
    # oddly-shaped cases and non-TPU backends.
    if _flash_backend_ok() and flash_eligible(
            q.shape, None if mask is None else mask.shape,
            None if mask is None else mask.ndim, dropout_p,
            rng is not None, k.shape[-2], use_flash):
        from analytics_zoo_tpu.ops.pallas.flash_attention import (
            _NEG,
            flash_attention,
        )

        bias = None
        if mask is not None:
            if mask.dtype == jnp.bool_:
                bias = jnp.where(mask, 0.0, _NEG).astype(jnp.float32)
            else:
                bias = mask.astype(jnp.float32)
        return flash_attention(
            q, k, v, causal, scale, bias=bias,
            dropout_p=float(dropout_p),
            dropout_seed=rng if dropout_p > 0.0 else None)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        causal_mask = jnp.tril(jnp.ones((lq, lk), bool), lk - lq)
        scores = jnp.where(causal_mask, scores, jnp.finfo(scores.dtype).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        else:
            scores = scores + mask
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_p > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def split_heads(x, n_heads):
    """(B, L, H*D) -> (B, H, L, D)."""
    b, l, hd = x.shape
    d = hd // n_heads
    return x.reshape(b, l, n_heads, d).transpose(0, 2, 1, 3)


def merge_heads(x):
    """(B, H, L, D) -> (B, L, H*D)."""
    b, h, l, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, l, h * d)
