"""Attention ops — the single entry point every attention layer routes
through, so kernel upgrades (Pallas flash attention, ring attention over the
``seq`` mesh axis) swap in under one signature.

Reference behavior being covered: the O(L²) ``multiHeadSelfAttention`` inside
TransformerLayer.scala:137 and BERT.scala's attention with additive mask.
The reference materializes the full (L, L) score matrix per head on CPU; here
the default path is a blockwise-friendly jnp einsum that XLA fuses, and the
hot path can be served by a Pallas kernel (ops/pallas) on TPU.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def dot_product_attention(q, k, v, mask=None, dropout_p=0.0, rng=None,
                          causal=False, scale=None, use_flash="auto"):
    """Batched multi-head attention.

    Args:
      q, k, v: (B, H, L, D) arrays.
      mask: optional additive mask broadcastable to (B, H, Lq, Lk) — 0 for
        keep, large-negative for drop (reference BERT attention_mask
        convention) — or a boolean mask (True = keep).
      dropout_p: attention-prob dropout (reference attnPDrop).
      causal: lower-triangular masking (reference TransformerLayer
        bidirectional=false path).
      scale: score scale; defaults to 1/sqrt(D).
    """
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    # Route big unmasked/causal attention through the Pallas flash kernel on
    # TPU (O(L·D) HBM traffic); the jnp path serves masked/dropout/small
    # cases and non-TPU backends.
    if (use_flash != False and mask is None and dropout_p == 0.0  # noqa: E712
            and q.shape[-2] >= 256 and d % 128 == 0
            and jax.default_backend() == "tpu"):
        from analytics_zoo_tpu.ops.pallas.flash_attention import (
            flash_attention,
        )

        return flash_attention(q, k, v, causal, scale)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        causal_mask = jnp.tril(jnp.ones((lq, lk), bool), lk - lq)
        scores = jnp.where(causal_mask, scores, jnp.finfo(scores.dtype).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        else:
            scores = scores + mask
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_p > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def split_heads(x, n_heads):
    """(B, L, H*D) -> (B, H, L, D)."""
    b, l, hd = x.shape
    d = hd // n_heads
    return x.reshape(b, l, n_heads, d).transpose(0, 2, 1, 3)


def merge_heads(x):
    """(B, H, L, D) -> (B, L, H*D)."""
    b, h, l, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, l, h * d)
