# zoolint: disable-file=raw-pallas-call -- ops/pallas/ is the one home
# for raw pl.pallas_call; everything here ships a jnp fallback oracle and
# lowers under a kernel_* label through the compile choke point.
"""Weight-stationary int8 matmul with per-channel scales.

The serving tier's weight-only quantization
(:func:`analytics_zoo_tpu.pipeline.inference.quantize.quantize_params_for_plan`)
stores int8 values + a per-output-channel f32 scale.  Without a kernel
the only consumer path is dequantize-then-dot: the int8 weight is
expanded to f32 in HBM (4x the traffic the quantization just saved)
before a plain f32 matmul.  This kernel keeps the weight int8 through
HBM *and* VMEM — blocks are cast in-register on their way into the MXU
and the per-channel scale is applied once to the f32 accumulator — so
weight traffic stays at 1 byte/param.

``int8_matmul(x, values, scale)``: x (M, K) f32/bf16, values (K, N)
int8, scale (N,) f32 → (M, N) in x's dtype.  The jnp fallback
(dequantize + f32 dot, scale applied after) is the numerical oracle;
tolerance ~1e-5 relative (accumulation order).  CPU runs the fallback,
``ZOO_KERNEL_INTERPRET=1`` forces the kernel in interpret mode.
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp

_BLOCK_M = 128
_BLOCK_N = 128
_BLOCK_K = 256

invocation_counts = {"pallas": 0, "fallback": 0}


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0")


def _interpret_forced() -> bool:
    return _env_flag("ZOO_KERNEL_INTERPRET")


def _pallas_available() -> bool:
    return (jax.default_backend() == "tpu" or _interpret_forced()
            or _env_flag("ZOO_KERNEL_FORCE_PALLAS"))


_warned_fallback = False


def _warn_fallback_once():
    global _warned_fallback
    if not _warned_fallback:
        _warned_fallback = True
        logging.getLogger("analytics_zoo_tpu").exception(
            "Pallas int8-matmul kernel failed on TPU; falling back to "
            "dequantize-then-dot. THIS IS A PERFORMANCE BUG.")


def _reference(x, values, scale):
    out = jnp.dot(x.astype(jnp.float32), values.astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    return (out * scale.astype(jnp.float32)[None, :]).astype(x.dtype)


def _mm_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, n_k):
    import jax.experimental.pallas as pl

    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # int8 → f32 happens HERE, in-register: the weight block arrived in
    # VMEM still 1 byte/param
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _emit():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


def _matmul_pallas(x, values, scale, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m0, k0 = x.shape
    _, n0 = values.shape
    bm = min(_BLOCK_M, -(-m0 // 8) * 8)
    bn = min(_BLOCK_N, -(-n0 // 128) * 128)
    bk = min(_BLOCK_K, -(-k0 // 128) * 128)
    m = -(-m0 // bm) * bm
    n = -(-n0 // bn) * bn
    k = -(-k0 // bk) * bk
    if (m, k) != (m0, k0):
        x = jnp.pad(x, ((0, m - m0), (0, k - k0)))
    if (k, n) != values.shape:
        values = jnp.pad(values, ((0, k - k0), (0, n - n0)))
    if n != n0:
        scale = jnp.pad(scale, (0, n - n0))
    grid = (m // bm, n // bn, k // bk)
    out = pl.pallas_call(
        functools.partial(_mm_kernel, n_k=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, values, scale.astype(jnp.float32).reshape(1, -1))
    return out[:m0, :n0]


def int8_matmul(x, values, scale):
    """``(x @ dequantize(values, scale))`` with the weight kept int8
    through HBM and VMEM.  x (M, K) float, values (K, N) int8, scale
    (N,) f32 per-output-channel; returns (M, N) in x's dtype."""
    if _pallas_available():
        try:
            out = _matmul_pallas(x, values, scale,
                                 interpret=_interpret_forced())
            invocation_counts["pallas"] += 1
            return out
        except Exception:
            _warn_fallback_once()
    invocation_counts["fallback"] += 1
    return _reference(x, values, scale)
