# zoolint: disable-file=raw-pallas-call -- ops/pallas/ is the one home
# for raw pl.pallas_call; everything here ships a jnp fallback oracle and
# lowers under a kernel_* label through the compile choke point.
"""Fused log-softmax + sparse cross-entropy — forward and backward
Pallas kernels that never materialize the ``[B, vocab]`` probability
tensor in HBM.

The unfused chain (``log_softmax`` then ``take_along_axis``) writes the
full (B, V) log-prob array to HBM and reads it back; for a 32k vocab
that is the dominant loss-path traffic.  The forward kernel streams
vocab blocks through VMEM with the online max/sum-exp recurrence (the
flash-attention trick applied to the classifier head) and emits only
the per-example loss and logsumexp — HBM traffic ``4·B·V`` read +
``O(B)`` write instead of ``3·4·B·V``.  The backward rebuilds
``softmax - onehot`` blockwise from the saved logsumexp, so the (B, V)
gradient is written exactly once with no probability intermediate.

``softmax_xent(logits, labels)`` → per-example loss, (B,) f32, wrapped
in ``jax.custom_vjp`` (labels get a float0 cotangent).  The pure-jnp
fallback is the numerical oracle: CPU runs it automatically,
``ZOO_KERNEL_INTERPRET=1`` forces the Pallas kernels in interpret mode
(CI kernel-path coverage).  Tolerance vs the fallback: ~1e-5 absolute
on the loss (different reduction order over vocab blocks).

Bytes accessed by the forward custom_call is exactly
``4·B·V + 4·B + 8·B`` (logits + labels in, loss + lse out), which is
what :func:`analytics_zoo_tpu.analysis.costmodel.kernel_bytes`
predicts and the bench's cross-lowered HLO measurement checks.
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -1e30
_BLOCK_B = 128
_BLOCK_V = 512

# Trace-time routing counters (tests assert the kernel fires; jit traces
# once so these count compilations).
invocation_counts = {"pallas": 0, "fallback": 0}


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0")


def _interpret_forced() -> bool:
    return _env_flag("ZOO_KERNEL_INTERPRET")


def _pallas_available() -> bool:
    return (jax.default_backend() == "tpu" or _interpret_forced()
            or _env_flag("ZOO_KERNEL_FORCE_PALLAS"))


_warned_fallback = False


def _warn_fallback_once():
    global _warned_fallback
    if not _warned_fallback:
        _warned_fallback = True
        logging.getLogger("analytics_zoo_tpu").exception(
            "Pallas fused softmax-xent kernel failed on TPU; falling "
            "back to the unfused jnp path. THIS IS A PERFORMANCE BUG.")


# ---------------------------------------------------------------------------
# jnp reference (CPU fallback + test oracle)
# ---------------------------------------------------------------------------


def _reference_fwd(logits, labels):
    x = logits.astype(jnp.float32)
    m = jnp.max(x, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m[:, None]), axis=-1))
    picked = jnp.take_along_axis(
        x, labels.astype(jnp.int32)[:, None], axis=-1)[:, 0]
    return lse - picked, lse


def _reference_bwd(logits, labels, lse, g):
    x = logits.astype(jnp.float32)
    probs = jnp.exp(x - lse[:, None])
    onehot = jax.nn.one_hot(labels, x.shape[-1], dtype=jnp.float32)
    return (g[:, None] * (probs - onehot)).astype(logits.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, lbl_ref, loss_ref, lse_ref, m_ref, s_ref, pick_ref,
                *, block_v, n_v):
    import jax.experimental.pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        s_ref[...] = jnp.zeros_like(s_ref)
        pick_ref[...] = jnp.zeros_like(pick_ref)

    x = x_ref[...].astype(jnp.float32)
    bm = jnp.max(x, axis=1, keepdims=True)
    m_old = m_ref[...]
    m_new = jnp.maximum(m_old, bm)
    s_ref[...] = (s_ref[...] * jnp.exp(m_old - m_new)
                  + jnp.sum(jnp.exp(x - m_new), axis=1, keepdims=True))
    m_ref[...] = m_new
    # the label column, if it lives in this vocab block
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, x.shape, 1)
    hit = cols == lbl_ref[...]
    pick_ref[...] += jnp.sum(jnp.where(hit, x, 0.0), axis=1,
                             keepdims=True)

    @pl.when(j == n_v - 1)
    def _emit():
        lse = m_ref[...] + jnp.log(s_ref[...])
        lse_ref[...] = lse
        loss_ref[...] = lse - pick_ref[...]


def _bwd_kernel(x_ref, lbl_ref, lse_ref, g_ref, dx_ref, *, block_v):
    import jax.experimental.pallas as pl

    j = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    probs = jnp.exp(x - lse_ref[...])
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == lbl_ref[...]).astype(jnp.float32)
    dx_ref[...] = (g_ref[...] * (probs - onehot)).astype(dx_ref.dtype)


def _pad_inputs(logits, labels):
    """Pad B to a multiple of 8 and V to a multiple of the vocab block.
    No-op (and a pure-custom_call lowering) for aligned shapes."""
    b, v = logits.shape
    block_v = min(_BLOCK_V, -(-v // 128) * 128)
    bp = -(-b // 8) * 8
    vp = -(-v // block_v) * block_v
    if (bp, vp) != (b, v):
        logits = jnp.pad(logits, ((0, bp - b), (0, vp - v)),
                         constant_values=_NEG)
        labels = jnp.pad(labels, (0, bp - b))
    return logits, labels, block_v, b


def _fwd_pallas(logits, labels, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    logits, labels, block_v, b0 = _pad_inputs(logits, labels)
    b, v = logits.shape
    block_b = min(_BLOCK_B, b)
    n_b, n_v = b // block_b, v // block_v
    col = pl.BlockSpec((block_b, 1), lambda i, j: (i, 0),
                       memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((b, 1), jnp.float32)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=block_v, n_v=n_v),
        grid=(n_b, n_v),
        in_specs=[
            pl.BlockSpec((block_b, block_v), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            col,
        ],
        out_specs=[col, col],
        out_shape=[out_shape, out_shape],
        scratch_shapes=[
            pltpu.VMEM((block_b, 1), jnp.float32),
            pltpu.VMEM((block_b, 1), jnp.float32),
            pltpu.VMEM((block_b, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(logits, labels.astype(jnp.int32).reshape(-1, 1))
    return loss[:b0, 0], lse[:b0, 0]


def _bwd_pallas(logits, labels, lse, g, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b0, v0 = logits.shape
    logits_p, labels_p, block_v, _ = _pad_inputs(logits, labels)
    b, v = logits_p.shape
    lse_p = jnp.pad(lse, (0, b - b0))
    g_p = jnp.pad(g, (0, b - b0))
    block_b = min(_BLOCK_B, b)
    n_b, n_v = b // block_b, v // block_v
    col = pl.BlockSpec((block_b, 1), lambda i, j: (i, 0),
                       memory_space=pltpu.VMEM)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, block_v=block_v),
        grid=(n_b, n_v),
        in_specs=[
            pl.BlockSpec((block_b, block_v), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            col, col, col,
        ],
        out_specs=pl.BlockSpec((block_b, block_v), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, v), logits.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(logits_p, labels_p.astype(jnp.int32).reshape(-1, 1),
      lse_p.astype(jnp.float32).reshape(-1, 1),
      g_p.astype(jnp.float32).reshape(-1, 1))
    return dx[:b0, :v0]


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------


def _fwd_impl(logits, labels):
    if _pallas_available():
        try:
            res = _fwd_pallas(logits, labels,
                              interpret=_interpret_forced())
            invocation_counts["pallas"] += 1
            return res
        except Exception:
            _warn_fallback_once()
    invocation_counts["fallback"] += 1
    return _reference_fwd(logits, labels)


@jax.custom_vjp
def softmax_xent(logits, labels):
    """Per-example sparse softmax cross-entropy, (B,) f32.

    ``logits``: (B, V) float; ``labels``: (B,) int.  Numerically equal
    to ``logsumexp(logits) - logits[label]`` computed in f32.
    """
    return _fwd_impl(logits, labels)[0]


def _vjp_fwd(logits, labels):
    loss, lse = _fwd_impl(logits, labels)
    return loss, (logits, labels, lse)


def _vjp_bwd(res, g):
    logits, labels, lse = res
    if _pallas_available():
        try:
            dx = _bwd_pallas(logits, labels, lse, g,
                             interpret=_interpret_forced())
            invocation_counts["pallas"] += 1
        except Exception:
            _warn_fallback_once()
            dx = None
    else:
        dx = None
    if dx is None:
        invocation_counts["fallback"] += 1
        dx = _reference_bwd(logits, labels, lse, g)
    dlabels = np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dx, dlabels


softmax_xent.defvjp(_vjp_fwd, _vjp_bwd)
