"""Flash attention — Pallas TPU kernel with streaming softmax.

The hot op behind TransformerLayer/BERT (reference materializes the full
(L, L) score matrix per head, TransformerLayer.scala:137).  This kernel
tiles Q over the grid and streams K/V blocks through VMEM with the
numerically-stable online-softmax accumulation, so HBM traffic is O(L·D)
per head instead of O(L²), and the score block lives only in VMEM where the
MXU consumes it.

Semantics: causal masking is *end-aligned* for lq != lk (query i sees keys
0..(lk-lq)+i), matching the jnp path in ops/attention.py — the decode-style
convention where q is the tail of the key sequence.

Gradient support: ``flash_attention`` is wrapped in jax.custom_vjp; the
backward recomputes attention **blockwise** with a lax.scan over key blocks
(O(Lq·block_k) live memory, the standard flash rematerialisation strategy),
so long-context training never materializes the (L, L) matrix.  On CPU
(tests) the forward falls back to the jnp path automatically.
"""

from __future__ import annotations

import functools
import logging
import math

import jax
import jax.numpy as jnp

_NEG = -1e30


def _attention_reference(q, k, v, causal, scale):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool), lk - lq)
        scores = jnp.where(mask, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    if causal:
        # keyless rows (lq > lk end-aligned) output zero, matching the
        # streaming kernel's acc/max(l, eps) and the blockwise backward —
        # not softmax's uniform distribution over fully-masked rows
        any_key = jnp.any(mask, axis=-1)
        probs = jnp.where(any_key[..., None], probs, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                      interpret=False):
    """Streaming forward: K/V blocks are a GRID dimension.

    grid = (b, h, n_q, n_k) with the key-block index innermost; Pallas's
    pipeline DMAs exactly one (block_k, d) K and V tile into VMEM per grid
    step (double-buffered against compute), so VMEM holds O(block_q·d +
    block_k·d) — never the whole (lk, d) K/V — and max sequence length is
    bounded by HBM, not VMEM.  Softmax running stats (m, l) and the output
    accumulator persist across the ki steps in VMEM scratch.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    offset = lk - lq  # end-aligned causal diagonal
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    n_q = pl.cdiv(lq, block_q)
    n_k = pl.cdiv(lk, block_k)

    def kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref):
        qi = pl.program_id(2)
        ki = pl.program_id(3)

        @pl.when(ki == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, _NEG)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        q_start = qi * block_q
        k_start = ki * block_k

        def compute():
            qb = q_ref[0, 0].astype(jnp.float32)
            kb = k_ref[0, 0].astype(jnp.float32)
            vb = v_ref[0, 0].astype(jnp.float32)
            # Zero padded key rows (lk % block_k != 0): OOB block reads are
            # unspecified, and a NaN there would poison p @ v even with
            # p == 0 at those columns (0 * NaN = NaN).
            k_live = (
                k_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_k, 1), 0) < lk
            )
            kb = jnp.where(k_live, kb, 0.0)
            vb = jnp.where(k_live, vb, 0.0)
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            # mask padded key rows (lk % block_k != 0) and, if causal, the
            # end-aligned upper triangle
            live = k_pos < lk
            if causal:
                live = live & (q_pos + offset >= k_pos)
            s = jnp.where(live, s, _NEG)
            m, l = m_ref[...], l_ref[...]
            new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m - new_m)
            p = jnp.where(live, jnp.exp(s - new_m), 0.0)
            m_ref[...] = new_m
            l_ref[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        if causal:
            # Skip compute for key blocks fully above this query block's
            # diagonal (their DMA is still pipelined, but no MXU work).
            pl.when(k_start <= q_start + block_q - 1 + offset)(compute)
        else:
            compute()

        @pl.when(ki == n_k - 1)
        def _emit():
            o_ref[0, 0] = (
                acc_ref[...] / jnp.maximum(l_ref[...], 1e-20)
            ).astype(o_ref.dtype)

    grid = (b, h, n_q, n_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


def _resolve_blocks(lq: int, block_q, block_k) -> tuple[int, int]:
    """Tuned defaults (v5e sweep, FLASH_r03.json): big blocks amortize
    grid-step overhead; VMEM caps block_q at 1024 once lq >= 8192."""
    if block_q is None:
        block_q = 2048 if lq <= 4096 else 1024
    if block_k is None:
        block_k = 1024
    return block_q, block_k


def _pallas_available() -> bool:
    return jax.default_backend() == "tpu"


_warned_fallback = False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None):
    """Fused attention: Pallas kernel on TPU, jnp fallback elsewhere.

    Default blocks are tuned from the v5e sweep in FLASH_r03.json:
    (2048, 1024) sustains 112 TF vs 24 TF at 256x256 (grid-step overheads
    dominate small blocks), but the scoped-VMEM budget caps block_q at
    1024 for sequences >= 8192 — ``_resolve_blocks`` encodes both."""
    scale = 1.0 / math.sqrt(q.shape[-1]) if scale is None else scale
    block_q, block_k = _resolve_blocks(q.shape[2], block_q, block_k)
    if _pallas_available():
        try:
            return _flash_fwd_pallas(q, k, v, causal, scale, block_q,
                                     block_k)
        except Exception:
            # Do NOT silently degrade to the O(L²) path on TPU: warn loudly
            # (once) with the actual kernel error so a broken kernel is
            # visible in logs and benchmarks.
            global _warned_fallback
            if not _warned_fallback:
                _warned_fallback = True
                logging.getLogger("analytics_zoo_tpu").exception(
                    "Pallas flash-attention kernel failed on TPU; falling "
                    "back to the O(L^2) jnp path. THIS IS A PERFORMANCE BUG."
                )
    return _attention_reference(q, k, v, causal, scale)


def _fwd(q, k, v, causal, scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v, out)


def _block_mask(q_pos, k_pos, lk, offset, causal):
    live = k_pos[None, :] < lk
    if causal:
        live = live & (q_pos[:, None] + offset >= k_pos[None, :])
    return live  # (lq, block_k)


def _bwd(causal, scale, block_q, block_k, res, g):
    """Blockwise flash backward: lax.scan over key blocks, recomputing each
    (lq, block_k) score tile from q/k (rematerialisation).  Live memory is
    O(lq·block_k + lk·d); the (lq, lk) matrix is never materialized."""
    q, k, v, out = res
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale_v = 1.0 / math.sqrt(d) if scale is None else scale
    offset = lk - lq
    # The backward keeps its own 256 default: its scan materializes
    # (b, h, lq, bk) f32 score/grad tiles in HBM, so the forward kernel's
    # 1024 tuning would quadruple live memory and can OOM long-context
    # training.  An explicit block_k still applies to both directions.
    bk = min(block_k if block_k is not None else 256, lk)
    n_k = -(-lk // bk)
    pad = n_k * bk - lk

    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0))).astype(jnp.float32)
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0))).astype(jnp.float32)
    # (n_k, b, h, bk, d) so scan iterates key blocks
    kb_s = jnp.moveaxis(kp.reshape(b, h, n_k, bk, d), 2, 0)
    vb_s = jnp.moveaxis(vp.reshape(b, h, n_k, bk, d), 2, 0)
    kpos_s = jnp.arange(n_k * bk, dtype=jnp.int32).reshape(n_k, bk)
    q_pos = jnp.arange(lq, dtype=jnp.int32)

    # pass 1: streaming softmax stats (m, l) per query row
    def stats_step(carry, xs):
        m, l = carry
        kb, kpos = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * scale_v
        live = _block_mask(q_pos, kpos, lk, offset, causal)
        s = jnp.where(live, s, _NEG)
        new_m = jnp.maximum(m, jnp.max(s, axis=-1))
        l = l * jnp.exp(m - new_m) + jnp.sum(
            jnp.where(live, jnp.exp(s - new_m[..., None]), 0.0), axis=-1)
        return (new_m, l), None

    m0 = jnp.full((b, h, lq), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    (m, l), _ = jax.lax.scan(stats_step, (m0, l0), (kb_s, kpos_s))
    l_safe = jnp.maximum(l, 1e-20)
    # D_i = sum_j P_ij (dO_i · V_j) = dO_i · O_i  (flash-bwd identity)
    D = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # (b, h, lq)

    # pass 2: accumulate dQ; emit per-block dK/dV
    def grad_step(dq, xs):
        kb, vb, kpos = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * scale_v
        live = _block_mask(q_pos, kpos, lk, offset, causal)
        p = jnp.where(live, jnp.exp(s - m[..., None]), 0.0) / l_safe[
            ..., None]
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vb)
        ds = p * (dp - D[..., None]) * scale_v
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kb)
        dkb = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        dvb = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        return dq, (dkb, dvb)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_s, dv_s) = jax.lax.scan(grad_step, dq0, (kb_s, vb_s, kpos_s))
    dk = jnp.moveaxis(dk_s, 0, 2).reshape(b, h, n_k * bk, d)[:, :, :lk]
    dv = jnp.moveaxis(dv_s, 0, 2).reshape(b, h, n_k * bk, d)[:, :, :lk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)
