# zoolint: disable-file=raw-pallas-call -- ops/pallas/ is the one home
# for raw pl.pallas_call; everything here ships a jnp fallback oracle and
# lowers under a kernel_* label through the compile choke point.
"""Flash attention — Pallas TPU kernel with streaming softmax.

The hot op behind TransformerLayer/BERT (reference materializes the full
(L, L) score matrix per head, TransformerLayer.scala:137).  This kernel
tiles Q over the grid and streams K/V blocks through VMEM with the
numerically-stable online-softmax accumulation, so HBM traffic is O(L·D)
per head instead of O(L²), and the score block lives only in VMEM where the
MXU consumes it.

Training-path features (so real TransformerLayer/BERT training — dropout
on, padded batches — lowers to this kernel instead of the dense path):

* **additive bias/mask**: any shape broadcastable as (B|1, H|1, Lq|1, Lk)
  — covers the BERT (B, 1, 1, L) padding-mask convention (BERT.scala:66)
  and full (B, H, Lq, Lk) biases, streamed blockwise;
* **segment ids**: (B, Lq)/(B, Lk) int arrays; attention is masked where
  q/k segments differ (packed-sequence training);
* **attention dropout**: computed *inside* the kernel from a counter-based
  hash PRNG (`_keep_bits`) keyed on (seed, b, h, q_pos, k_pos).  The same
  pure function runs in the Pallas forward, the jnp fallback forward, and
  the blockwise backward, so the dropout mask is bit-identical across
  forward/backward without ever being materialized in HBM.

Semantics: causal masking is *end-aligned* for lq != lk (query i sees keys
0..(lk-lq)+i), matching the jnp path in ops/attention.py — the decode-style
convention where q is the tail of the key sequence.

Gradient support: ``flash_attention`` is wrapped in jax.custom_vjp.  The
forward saves its softmax stats (m, l), so the backward needs no
stats-recompute pass; on TPU the backward runs as two Pallas kernels
(``_flash_bwd_pallas``: a dq kernel streaming K/V blocks past each q
block, and a dk/dv/dbias kernel streaming q blocks past each K/V block)
whose rematerialized score tiles never leave VMEM.  Elsewhere — CPU, a
full (Lq, Lk) bias that needs its own O(Lq·Lk) gradient, or kernel
failure — a blockwise lax.scan over key blocks serves as fallback and
oracle (O(Lq·block_k) live memory).  Either way long-context training
never materializes the (L, L) matrix.  On CPU (tests) the forward falls
back to the jnp path automatically; set ``ZOO_FLASH_INTERPRET=1`` to
force the actual Pallas kernels in interpret mode on CPU (CI routing +
grad-oracle tests).
"""

from __future__ import annotations

import functools
import logging
import math
import os

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -1e30

# Trace-time routing counters (tests assert the kernel actually fires for
# training-shaped inputs; jit traces once so these count compilations).
invocation_counts = {"pallas": 0, "fallback": 0}

# ---------------------------------------------------------------------------
# Counter-based dropout hash.  splitmix32-style finalizer over a position/
# seed counter: stateless, identical in Pallas and jnp, so fwd/bwd agree.
# ---------------------------------------------------------------------------
_C1 = np.uint32(0x9E3779B9)
_C2 = np.uint32(0x85EBCA6B)
_C3 = np.uint32(0xC2B2AE35)
_C4 = np.uint32(0x27D4EB2F)


def _mix32(x):
    x = x ^ (x >> 16)
    x = x * np.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * np.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _keep_bits(seed0, seed1, b, h, q_pos, k_pos):
    """uint32 hash tile; shape follows broadcasting of q_pos × k_pos."""
    def u(t):
        return jnp.asarray(t).astype(jnp.uint32)

    x = (u(q_pos) * _C1) ^ (u(k_pos) * _C2)
    x = x ^ (u(b) * _C3) ^ (u(h) * _C4)
    x = x ^ u(seed0) ^ (u(seed1) * _C2)
    return _mix32(x)


def _drop_threshold(dropout_p):
    return np.uint32(min(int(dropout_p * 4294967296.0), 4294967295))


def _normalize_seed(seed):
    """Accept int, PRNG key, or int array; return (2,) int32."""
    if seed is None:
        return None
    if isinstance(seed, int):
        return jnp.asarray([seed, 0], jnp.int32)
    seed = jnp.asarray(seed)
    if jnp.issubdtype(seed.dtype, jax.dtypes.prng_key):
        seed = jax.random.key_data(seed)
    seed = seed.reshape(-1)
    if seed.dtype != jnp.int32:
        seed = jax.lax.bitcast_convert_type(seed.astype(jnp.uint32),
                                            jnp.int32)
    if seed.shape[0] == 1:
        seed = jnp.concatenate([seed, jnp.zeros((1,), jnp.int32)])
    return seed[:2]


# ---------------------------------------------------------------------------
# Dense reference (CPU fallback + test oracle)
# ---------------------------------------------------------------------------


def _attention_reference(q, k, v, causal, scale, bias=None, q_seg=None,
                         kv_seg=None, dropout_p=0.0, seed=None):
    scores = jnp.einsum("bhqd,bhkd->bhqk",
                        q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    lq, lk = scores.shape[-2], scores.shape[-1]
    live = None
    if causal:
        live = jnp.tril(jnp.ones((lq, lk), bool), lk - lq)[None, None]
    if q_seg is not None:
        seg_live = (q_seg[:, None, :, None] == kv_seg[:, None, None, :])
        live = seg_live if live is None else live & seg_live
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    if live is not None:
        scores = jnp.where(live, scores, _NEG)
    # softmax with the kernel's exact semantics: the running-max floor at
    # _NEG means rows that are fully masked (by `live` OR by a large
    # negative bias) produce zero output, not softmax's uniform row
    m2 = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), _NEG)
    p = jnp.exp(scores - m2)
    if live is not None:
        p = jnp.where(live, p, 0.0)
    probs = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-20)
    if dropout_p > 0.0:
        b, h = scores.shape[0], scores.shape[1]
        bits = _keep_bits(
            seed[0], seed[1],
            jnp.arange(b, dtype=jnp.int32)[:, None, None, None],
            jnp.arange(h, dtype=jnp.int32)[None, :, None, None],
            jnp.arange(lq, dtype=jnp.int32)[None, None, :, None],
            jnp.arange(lk, dtype=jnp.int32)[None, None, None, :])
        keep = bits >= _drop_threshold(dropout_p)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


def _attention_stats_reference(q, k, v, causal, scale, mask=None):
    """(out, m, l) with the kernel's exact streaming semantics — the
    combinable-partial form used by ring attention's inner blocks.
    ``mask``: optional boolean keep-mask broadcastable to the score shape
    (ring attention's per-hop global-position mask); combines with
    ``causal``."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    live = mask
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        tri = jnp.tril(jnp.ones((lq, lk), bool), lk - lq)[None, None]
        live = tri if live is None else live & tri
    if live is not None:
        scores = jnp.where(live, scores, _NEG)
    m = jnp.maximum(jnp.max(scores, axis=-1), _NEG)
    p = jnp.exp(scores - m[..., None])
    if live is not None:
        p = jnp.where(live, p, 0.0)
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)) \
        / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype), m, l


def attention_stats(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None):
    """Partial attention with running-softmax stats: returns
    ``(out, m, l)`` where ``out * l[..., None]`` is the unnormalized
    accumulator — two partials over disjoint key sets combine exactly via
    the flash update (ring attention's inner kernel).  Pallas on TPU, jnp
    elsewhere.  NOT differentiable on the TPU path — callers (ring
    attention) wrap it in their own custom_vjp."""
    scale = 1.0 / math.sqrt(q.shape[-1]) if scale is None else scale
    block_q, block_k = _resolve_blocks(block_q, block_k)
    if _pallas_available() and q.shape[-1] % 64 == 0 \
            and q.shape[2] >= 128 and k.shape[2] >= 128:
        try:
            out = _flash_fwd_pallas(q, k, v, causal, scale, block_q,
                                    block_k, interpret=_interpret_forced(),
                                    return_stats=True)
            invocation_counts["pallas"] += 1
            return out
        except Exception:
            global _warned_fallback
            if not _warned_fallback:
                _warned_fallback = True
                logging.getLogger("analytics_zoo_tpu").exception(
                    "Pallas attention_stats kernel failed; jnp fallback. "
                    "THIS IS A PERFORMANCE BUG.")
    invocation_counts["fallback"] += 1
    return _attention_stats_reference(q, k, v, causal, scale)


# ---------------------------------------------------------------------------
# Pallas forward
# ---------------------------------------------------------------------------


def _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                      interpret=False, bias=None, q_seg=None, kv_seg=None,
                      dropout_p=0.0, seed=None, return_stats=False):
    """Streaming forward: K/V blocks are a GRID dimension.

    grid = (b, h, n_q, n_k) with the key-block index innermost; Pallas's
    pipeline DMAs exactly one (block_k, d) K and V tile into VMEM per grid
    step (double-buffered against compute), so VMEM holds O(block_q·d +
    block_k·d) — never the whole (lk, d) K/V — and max sequence length is
    bounded by HBM, not VMEM.  Softmax running stats (m, l) and the output
    accumulator persist across the ki steps in VMEM scratch.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    offset = lk - lq  # end-aligned causal diagonal
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    n_q = pl.cdiv(lq, block_q)
    n_k = pl.cdiv(lk, block_k)
    has_bias = bias is not None
    has_seg = q_seg is not None
    has_drop = dropout_p > 0.0
    if has_bias:
        bb, bh, bq, _ = bias.shape
        bq_blk = block_q if bq > 1 else 1

    def kernel(*refs):
        i = 3
        q_ref, k_ref, v_ref = refs[:3]
        if has_bias:
            bias_ref = refs[i]
            i += 1
        if has_seg:
            qseg_ref, kseg_ref = refs[i:i + 2]
            i += 2
        if has_drop:
            seed_ref = refs[i]
            i += 1
        if return_stats:
            o_ref, m_out_ref, l_out_ref = refs[i:i + 3]
            i += 3
        else:
            o_ref = refs[i]
            i += 1
        m_ref, l_ref, acc_ref = refs[i:i + 3]

        bi = pl.program_id(0)
        hi = pl.program_id(1)
        qi = pl.program_id(2)
        ki = pl.program_id(3)

        @pl.when(ki == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, _NEG)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        q_start = qi * block_q
        k_start = ki * block_k

        def compute():
            qb = q_ref[0, 0].astype(jnp.float32)
            kb = k_ref[0, 0].astype(jnp.float32)
            vb = v_ref[0, 0].astype(jnp.float32)
            # Zero padded key rows (lk % block_k != 0): OOB block reads are
            # unspecified, and a NaN there would poison p @ v even with
            # p == 0 at those columns (0 * NaN = NaN).
            k_live = (
                k_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_k, 1), 0) < lk
            )
            kb = jnp.where(k_live, kb, 0.0)
            vb = jnp.where(k_live, vb, 0.0)
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            if has_bias:
                s = s + bias_ref[0, 0].astype(jnp.float32)
            # mask padded key rows (lk % block_k != 0), if causal the
            # end-aligned upper triangle, and cross-segment pairs
            live = k_pos < lk
            if causal:
                live = live & (q_pos + offset >= k_pos)
            if has_seg:
                # q_seg rides as (B, Lq, 8) and kv_seg as (B, 8, Lk): a bare
                # (B, L) operand would need block (1, block) whose
                # second-to-last dim violates Mosaic's (8, 128)-or-full-dim
                # block rule on real TPU (interpret mode does not check).
                sq = qseg_ref[0][:, :1]            # (block_q, 1)
                sk = kseg_ref[0][:1, :]            # (1, block_k)
                live = live & (sq == sk)
            s = jnp.where(live, s, _NEG)
            m, l = m_ref[...], l_ref[...]
            new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m - new_m)
            p = jnp.where(live, jnp.exp(s - new_m), 0.0)
            m_ref[...] = new_m
            # l is the full softmax denominator (pre-dropout), so the final
            # acc / l division reproduces dropout-after-softmax semantics
            l_ref[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            if has_drop:
                bits = _keep_bits(seed_ref[0], seed_ref[1], bi, hi,
                                  q_pos, k_pos)
                p = jnp.where(bits >= _drop_threshold(dropout_p),
                              p * (1.0 / (1.0 - dropout_p)), 0.0)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        if causal:
            # Skip compute for key blocks fully above this query block's
            # diagonal (their DMA is still pipelined, but no MXU work).
            pl.when(k_start <= q_start + block_q - 1 + offset)(compute)
        else:
            compute()

        @pl.when(ki == n_k - 1)
        def _emit():
            o_ref[0, 0] = (
                acc_ref[...] / jnp.maximum(l_ref[...], 1e-20)
            ).astype(o_ref.dtype)
            if return_stats:
                m_out_ref[0, 0] = m_ref[...]
                l_out_ref[0, 0] = l_ref[...]

    in_specs = [
        pl.BlockSpec((1, 1, block_q, d),
                     lambda bi, hi, qi, ki: (bi, hi, qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki: (bi, hi, ki, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki: (bi, hi, ki, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [q, k, v]
    if has_bias:
        in_specs.append(pl.BlockSpec(
            (1, 1, bq_blk, block_k),
            lambda bi, hi, qi, ki, _bb=bb, _bh=bh, _bq=bq: (
                bi if _bb > 1 else 0, hi if _bh > 1 else 0,
                qi if _bq > 1 else 0, ki),
            memory_space=pltpu.VMEM))
        args.append(bias.astype(jnp.float32))
    if has_seg:
        in_specs.append(pl.BlockSpec(
            (1, block_q, 8), lambda bi, hi, qi, ki: (bi, qi, 0),
            memory_space=pltpu.VMEM))
        in_specs.append(pl.BlockSpec(
            (1, 8, block_k), lambda bi, hi, qi, ki: (bi, 0, ki),
            memory_space=pltpu.VMEM))
        args.append(jnp.broadcast_to(
            q_seg.astype(jnp.int32)[:, :, None], (b, lq, 8)))
        args.append(jnp.broadcast_to(
            kv_seg.astype(jnp.int32)[:, None, :], (b, 8, lk)))
    if has_drop:
        in_specs.append(pl.BlockSpec(
            (2,), lambda bi, hi, qi, ki: (0,),
            memory_space=pltpu.SMEM))
        args.append(seed.astype(jnp.int32))

    grid = (b, h, n_q, n_k)
    out_specs = pl.BlockSpec((1, 1, block_q, d),
                             lambda bi, hi, qi, ki: (bi, hi, qi, 0),
                             memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    if return_stats:
        stat_spec = pl.BlockSpec((1, 1, block_q, 1),
                                 lambda bi, hi, qi, ki: (bi, hi, qi, 0),
                                 memory_space=pltpu.VMEM)
        stat_shape = jax.ShapeDtypeStruct((b, h, lq, 1), jnp.float32)
        out_specs = [out_specs, stat_spec, stat_spec]
        out_shape = [out_shape, stat_shape, stat_shape]
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    if return_stats:
        out, m, l = res
        return out, m[..., 0], l[..., 0]
    return res


def _resolve_blocks(block_q, block_k,
                    full_bias: bool = False,
                    dropout: bool = False) -> tuple[int, int]:
    """Block defaults sized against the v5e ~16 MB scoped-VMEM budget.

    The dominant live buffers are the (block_q, block_k) f32 score and
    prob tiles; in-kernel dropout adds a PRNG-bits tile of the same shape
    and a full (…, Lq, Lk) bias streams an extra f32 tile.  The r03-tuned
    2048-row blocks left <1% headroom and went over once those operands
    landed (measured: 16.09M/16M clean @4k d=64, 22.73M/16M dropout @2k
    d=128 — both hard compile failures on the chip), so: 1024x1024 clean
    (~10 MB live), block_k 512 under dropout/full-bias (~8 MB live).
    Explicit block_q/block_k arguments always win."""
    if full_bias:
        return block_q or 512, block_k or 512
    if block_q is None:
        block_q = 1024
    if block_k is None:
        block_k = 512 if dropout else 1024
    return block_q, block_k


def _resolve_bwd_blocks(block_q, block_k, lq, lk) -> tuple[int, int]:
    """Backward blocks: 512x512 keeps both kernels' live VMEM ~7 MB at
    d=128 with dropout (f32 q/g/k/v casts + up to four (bq, bk) f32
    score/prob/grad tiles + the PRNG-bits tile + (bq|bk, d) accumulators),
    well under the measured ~16 MB scoped budget that burned the 1024-row
    forward tuning (see _resolve_blocks).  A caller's SMALLER explicit
    blocks are honored (the VMEM-pressure escape hatch); anything larger —
    including the forward's resolved 1024 defaults flowing through
    _flash_core — is capped at 512 because the backward holds roughly
    twice the forward's live tiles per step."""
    return (min(block_q or 512, 512, lq),
            min(block_k or 512, 512, lk))


def _flash_bwd_pallas(q, k, v, g, out, m, l, causal, scale,
                      block_q=None, block_k=None, interpret=False,
                      bias=None, q_seg=None, kv_seg=None, dropout_p=0.0,
                      seed=None):
    """Pallas flash backward: two kernels, both O(block²) VMEM.

    dq kernel: grid (b, h, n_q, n_k) — a q block accumulates dq across
    streamed K/V blocks.  dk/dv kernel: grid (b, h, n_k, n_q) — a K/V
    block accumulates dk/dv (and its bias-grad tile) across streamed q
    blocks.  Score tiles are rematerialized from q/k in VMEM (standard
    flash strategy) using the forward's saved softmax stats (m, l), so
    no stats-recompute pass exists and nothing O(Lq·Lk) ever reaches
    HBM.  Dropout re-derives the forward's exact keep mask from the
    `_keep_bits` position hash.

    Bias gradients are emitted per (b, h) as (b, h, 1, lk) partials and
    reduced outside to the bias's broadcast shape; full (…, Lq, Lk)
    biases are NOT handled here (their db is itself O(Lq·Lk) — callers
    fall back to the jnp blockwise path).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    offset = lk - lq
    invocation_counts["pallas"] += 1
    bq, bk = _resolve_bwd_blocks(block_q, block_k, lq, lk)
    n_q = pl.cdiv(lq, bq)
    n_k = pl.cdiv(lk, bk)
    has_bias = bias is not None
    has_seg = q_seg is not None
    has_drop = dropout_p > 0.0
    if has_bias:
        bb, bh, bq_dim, _ = bias.shape
        if bq_dim > 1:
            raise ValueError("full (Lq, Lk) bias backward not supported "
                             "in the Pallas path")

    gf = g.astype(jnp.float32)
    # D_i = dO_i · O_i (flash-bwd identity; holds under dropout because
    # O already contains the dropped probabilities)
    D = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # (b, h, lq)
    m4 = m.astype(jnp.float32)[..., None]               # (b, h, lq, 1)
    l4 = jnp.maximum(l.astype(jnp.float32), 1e-20)[..., None]
    D4 = D[..., None]

    thr = _drop_threshold(dropout_p) if has_drop else None
    inv_keep = 1.0 / (1.0 - dropout_p) if has_drop else None

    def tiles(q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, d_ref, bias_ref,
              qseg_ref, kseg_ref, seed_ref, bi, hi, qi, ki):
        """Shared per-(q block, k block) recompute: returns
        (p_t, ds_raw, ds, qb, kb, gb) — all f32 tiles.  bi/hi/qi/ki are
        program ids read OUTSIDE any pl.when branch (program_id inside a
        cond branch cannot lower in interpret mode)."""
        q_start = qi * bq
        k_start = ki * bk
        qb = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        gb = g_ref[0, 0].astype(jnp.float32)
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        q_live = q_pos < lq
        k_live = k_pos < lk
        # zero padded rows: OOB block reads are unspecified and a NaN
        # would poison the accumulations through 0 * NaN
        qb = jnp.where(q_live, qb, 0.0)
        gb = jnp.where(q_live, gb, 0.0)
        # column-oriented mask built directly from iota: reshaping the
        # (1, bk) i1 vector is a Mosaic "insert minor dim" op that only
        # lowers for 32-bit types on real TPU
        k_live_col = (k_start + jax.lax.broadcasted_iota(
            jnp.int32, (bk, 1), 0)) < lk
        kb = jnp.where(k_live_col, kb, 0.0)
        vb = jnp.where(k_live_col, vb, 0.0)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        live = q_live & k_live
        if causal:
            live = live & (q_pos + offset >= k_pos)
        if has_seg:
            live = live & (qseg_ref[0][:, :1] == kseg_ref[0][:1, :])
        mb = m_ref[0, 0]  # (bq, 1) f32
        lb = l_ref[0, 0]
        db_row = d_ref[0, 0]
        # division and D-subtraction INSIDE the where: padded q rows read
        # OOB stats (NaN/0 in interpret mode, unspecified on hardware) and
        # the dk/dv kernel CONTRACTS over q rows — a NaN there would
        # poison every output element, so masked entries must be exact 0s
        p = jnp.where(live, jnp.exp(s - mb) / lb, 0.0)
        dp = jax.lax.dot_general(
            gb, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if has_drop:
            bits = _keep_bits(seed_ref[0], seed_ref[1], bi, hi,
                              q_pos, k_pos)
            t = jnp.where(bits >= thr, inv_keep, 0.0)
            p_t = p * t
            ds_raw = jnp.where(live, p * (t * dp - db_row), 0.0)
        else:
            p_t = p
            ds_raw = jnp.where(live, p * (dp - db_row), 0.0)
        return p_t, ds_raw, ds_raw * scale, qb, kb, gb

    # ---- dq kernel: grid (b, h, n_q, n_k), key blocks innermost --------
    def dq_kernel(*refs):
        i = 7
        q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, d_ref = refs[:7]
        bias_ref = qseg_ref = kseg_ref = seed_ref = None
        if has_bias:
            bias_ref = refs[i]
            i += 1
        if has_seg:
            qseg_ref, kseg_ref = refs[i:i + 2]
            i += 2
        if has_drop:
            seed_ref = refs[i]
            i += 1
        dq_ref, acc_ref = refs[i], refs[i + 1]
        bi = pl.program_id(0)
        hi = pl.program_id(1)
        qi = pl.program_id(2)
        ki = pl.program_id(3)

        @pl.when(ki == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        def compute():
            _, _, ds, _, kb, _ = tiles(
                q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, d_ref,
                bias_ref, qseg_ref, kseg_ref, seed_ref, bi, hi, qi, ki)
            acc_ref[...] += jax.lax.dot_general(
                ds, kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            pl.when(ki * bk <= qi * bq + bq - 1 + offset)(compute)
        else:
            compute()

        @pl.when(ki == n_k - 1)
        def _emit():
            dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)

    # ---- dk/dv kernel: grid (b, h, n_k, n_q), q blocks innermost -------
    def dkv_kernel(*refs):
        i = 7
        q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, d_ref = refs[:7]
        bias_ref = qseg_ref = kseg_ref = seed_ref = None
        if has_bias:
            bias_ref = refs[i]
            i += 1
        if has_seg:
            qseg_ref, kseg_ref = refs[i:i + 2]
            i += 2
        if has_drop:
            seed_ref = refs[i]
            i += 1
        if has_bias:
            dk_ref, dv_ref, db_ref = refs[i:i + 3]
            dk_acc, dv_acc, db_acc = refs[i + 3:i + 6]
        else:
            dk_ref, dv_ref = refs[i:i + 2]
            dk_acc, dv_acc = refs[i + 2:i + 4]
            db_ref = db_acc = None
        bi = pl.program_id(0)
        hi = pl.program_id(1)
        ki = pl.program_id(2)
        qi = pl.program_id(3)

        @pl.when(qi == 0)
        def _init():
            dk_acc[...] = jnp.zeros_like(dk_acc)
            dv_acc[...] = jnp.zeros_like(dv_acc)
            if has_bias:
                db_acc[...] = jnp.zeros_like(db_acc)

        def compute():
            p_t, ds_raw, ds, qb, _, gb = tiles(
                q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, d_ref,
                bias_ref, qseg_ref, kseg_ref, seed_ref, bi, hi, qi, ki)
            dk_acc[...] += jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dv_acc[...] += jax.lax.dot_general(
                p_t, gb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if has_bias:
                db_acc[...] += jnp.sum(ds_raw, axis=0, keepdims=True)

        if causal:
            pl.when(ki * bk <= qi * bq + bq - 1 + offset)(compute)
        else:
            compute()

        @pl.when(qi == n_q - 1)
        def _emit():
            dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
            dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)
            if has_bias:
                db_ref[0, 0] = db_acc[...]

    def common_specs(order):
        """In-specs for q/g/m/l/D + k/v + optionals; ``order`` maps grid
        ids -> (qi, ki) for the kernel's grid layout."""
        def im_q(bi, hi, g2, g3):
            return (bi, hi, order(g2, g3)[0], 0)

        def im_k(bi, hi, g2, g3):
            return (bi, hi, order(g2, g3)[1], 0)

        def im_row(bi, hi, g2, g3):
            return (bi, hi, order(g2, g3)[0], 0)

        specs = [
            pl.BlockSpec((1, 1, bq, d), im_q, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d), im_k, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d), im_k, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, d), im_q, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, 1), im_row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, 1), im_row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, 1), im_row, memory_space=pltpu.VMEM),
        ]
        args = [q, k, v, gf.astype(q.dtype), m4, l4, D4]
        if has_bias:
            specs.append(pl.BlockSpec(
                (1, 1, 1, bk),
                lambda bi, hi, g2, g3, _bb=bb, _bh=bh: (
                    bi if _bb > 1 else 0, hi if _bh > 1 else 0, 0,
                    order(g2, g3)[1]),
                memory_space=pltpu.VMEM))
            args.append(bias.astype(jnp.float32))
        if has_seg:
            specs.append(pl.BlockSpec(
                (1, bq, 8),
                lambda bi, hi, g2, g3: (bi, order(g2, g3)[0], 0),
                memory_space=pltpu.VMEM))
            specs.append(pl.BlockSpec(
                (1, 8, bk),
                lambda bi, hi, g2, g3: (bi, 0, order(g2, g3)[1]),
                memory_space=pltpu.VMEM))
            args.append(jnp.broadcast_to(
                q_seg.astype(jnp.int32)[:, :, None], (b, lq, 8)))
            args.append(jnp.broadcast_to(
                kv_seg.astype(jnp.int32)[:, None, :], (b, 8, lk)))
        if has_drop:
            specs.append(pl.BlockSpec(
                (2,), lambda bi, hi, g2, g3: (0,),
                memory_space=pltpu.SMEM))
            args.append(seed.astype(jnp.int32))
        return specs, args

    params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"))

    dq_specs, dq_args = common_specs(lambda g2, g3: (g2, g3))
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, n_q, n_k),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=params,
        interpret=interpret,
    )(*dq_args)

    kv_specs, kv_args = common_specs(lambda g2, g3: (g3, g2))
    kv_out_specs = [
        pl.BlockSpec((1, 1, bk, d),
                     lambda bi, hi, ki, qi: (bi, hi, ki, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, bk, d),
                     lambda bi, hi, ki, qi: (bi, hi, ki, 0),
                     memory_space=pltpu.VMEM),
    ]
    kv_out_shape = [jax.ShapeDtypeStruct(k.shape, k.dtype),
                    jax.ShapeDtypeStruct(v.shape, v.dtype)]
    kv_scratch = [pltpu.VMEM((bk, d), jnp.float32),
                  pltpu.VMEM((bk, d), jnp.float32)]
    if has_bias:
        kv_out_specs.append(pl.BlockSpec(
            (1, 1, 1, bk), lambda bi, hi, ki, qi: (bi, hi, 0, ki),
            memory_space=pltpu.VMEM))
        kv_out_shape.append(
            jax.ShapeDtypeStruct((b, h, 1, n_k * bk), jnp.float32))
        kv_scratch.append(pltpu.VMEM((1, bk), jnp.float32))
    res = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, n_k, n_q),
        in_specs=kv_specs,
        out_specs=kv_out_specs,
        out_shape=kv_out_shape,
        scratch_shapes=kv_scratch,
        compiler_params=params,
        interpret=interpret,
    )(*kv_args)
    if has_bias:
        dk, dv, db_part = res
        db = db_part[..., :lk]  # (b, h, 1, lk) per-(b,h) partials
        if bb == 1:
            db = jnp.sum(db, axis=0, keepdims=True)
        if bh == 1:
            db = jnp.sum(db, axis=1, keepdims=True)
        dbias = db.astype(bias.dtype)
    else:
        dk, dv = res
        dbias = None
    return dq, dk, dv, dbias


def _env_flag(name: str) -> bool:
    # same convention as engine.py's ZOO_SHARD_OPTIMIZER: "0"/"" are false
    return os.environ.get(name, "") not in ("", "0")


def _interpret_forced() -> bool:
    return _env_flag("ZOO_FLASH_INTERPRET")


def _pallas_available() -> bool:
    # ZOO_FLASH_FORCE_PALLAS routes to the REAL (non-interpret) kernels on
    # any backend — lowering-only CI: tracing + lower(platforms=("tpu",))
    # then goes through genuine Mosaic lowering with no chip (interpret
    # mode lowers to plain jax ops and exercises none of it; the round-4
    # backward cross-lowering guard was vacuous for exactly that reason).
    # Executing under this knob off-TPU will fail — lower, don't run.
    return (jax.default_backend() == "tpu" or _interpret_forced()
            or _env_flag("ZOO_FLASH_FORCE_PALLAS"))


_warned_fallback = False


# ---------------------------------------------------------------------------
# custom_vjp core: array args explicit so bias/segments/seed differentiate
# (or get float0 cotangents) correctly.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _flash_core(q, k, v, bias, q_seg, kv_seg, seed, causal, scale,
                dropout_p, block_q, block_k):
    return _forward_impl(q, k, v, bias, q_seg, kv_seg, seed, causal, scale,
                         dropout_p, block_q, block_k)


def _warn_fallback_once():
    # Do NOT silently degrade to the O(L²) path on TPU: warn loudly
    # (once) with the actual kernel error so a broken kernel is
    # visible in logs and benchmarks.
    global _warned_fallback
    if not _warned_fallback:
        _warned_fallback = True
        logging.getLogger("analytics_zoo_tpu").exception(
            "Pallas flash-attention kernel failed on TPU; falling "
            "back to the O(L^2) jnp path. THIS IS A PERFORMANCE BUG."
        )


def _forward_impl(q, k, v, bias, q_seg, kv_seg, seed, causal, scale,
                  dropout_p, block_q, block_k, return_stats=False):
    if _pallas_available():
        try:
            res = _flash_fwd_pallas(
                q, k, v, causal, scale, block_q, block_k,
                interpret=_interpret_forced(), bias=bias, q_seg=q_seg,
                kv_seg=kv_seg, dropout_p=dropout_p, seed=seed,
                return_stats=return_stats)
            invocation_counts["pallas"] += 1
            return res
        except Exception:
            _warn_fallback_once()
    invocation_counts["fallback"] += 1
    out = _attention_reference(q, k, v, causal, scale, bias=bias,
                               q_seg=q_seg, kv_seg=kv_seg,
                               dropout_p=dropout_p, seed=seed)
    return (out, None, None) if return_stats else out


def _fwd(q, k, v, bias, q_seg, kv_seg, seed, causal, scale, dropout_p,
         block_q, block_k):
    # Save the softmax stats (m, l) alongside the output: the backward
    # then needs no stats-recompute pass (a full extra QK^T sweep).
    out, m, l = _forward_impl(q, k, v, bias, q_seg, kv_seg, seed, causal,
                              scale, dropout_p, block_q, block_k,
                              return_stats=True)
    return out, (q, k, v, bias, q_seg, kv_seg, seed, out, m, l)


def _bwd(causal, scale, dropout_p, block_q, block_k, res, g):
    """Flash backward.  On TPU (stats saved by the Pallas forward):
    `_flash_bwd_pallas` — two streaming kernels whose score tiles never
    leave VMEM.  Otherwise (CPU, full-(Lq,Lk)-bias grad, or kernel
    failure): blockwise lax.scan over key blocks, recomputing each
    (lq, block_k) score tile from q/k (rematerialisation).  Live memory is
    O(lq·block_k + lk·d) either way; the (lq, lk) matrix is never
    materialized.  Dropout is re-derived from the same `_keep_bits` hash
    the forward used, so no mask is stored."""
    q, k, v, bias, q_seg, kv_seg, seed, out, m_s, l_s = res
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale_v = 1.0 / math.sqrt(d) if scale is None else scale
    offset = lk - lq
    has_bias = bias is not None
    has_seg = q_seg is not None
    has_drop = dropout_p > 0.0

    dseg_q = (np.zeros(q_seg.shape, dtype=jax.dtypes.float0)
              if has_seg else None)
    dseg_kv = (np.zeros(kv_seg.shape, dtype=jax.dtypes.float0)
               if has_seg else None)
    dseed = (np.zeros(seed.shape, dtype=jax.dtypes.float0)
             if seed is not None else None)

    full_bias = has_bias and bias.shape[2] > 1
    if m_s is not None and _pallas_available() and not full_bias:
        try:
            dq, dk, dv, dbias = _flash_bwd_pallas(
                q, k, v, g, out, m_s, l_s, causal, scale_v,
                block_q=block_q, block_k=block_k,
                interpret=_interpret_forced(), bias=bias, q_seg=q_seg,
                kv_seg=kv_seg, dropout_p=dropout_p, seed=seed)
            return (dq, dk, dv, dbias, dseg_q, dseg_kv, dseed)
        except Exception:
            _warn_fallback_once()
    # The fallback scan keeps its own 256 cap: it materializes
    # (b, h, lq, bk) f32 score/grad tiles in HBM, so the forward kernel's
    # 1024 tuning would quadruple live memory and can OOM long-context
    # training.  A caller's SMALLER explicit block_k is honored.
    bk = min(block_k or 256, 256, lk)
    n_k = -(-lk // bk)
    pad = n_k * bk - lk

    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0))).astype(jnp.float32)
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0))).astype(jnp.float32)
    # (n_k, b, h, bk, d) so scan iterates key blocks
    kb_s = jnp.moveaxis(kp.reshape(b, h, n_k, bk, d), 2, 0)
    vb_s = jnp.moveaxis(vp.reshape(b, h, n_k, bk, d), 2, 0)
    kpos_s = jnp.arange(n_k * bk, dtype=jnp.int32).reshape(n_k, bk)
    q_pos = jnp.arange(lq, dtype=jnp.int32)
    if has_bias:
        bb, bh, bq, _ = bias.shape
        bias_p = jnp.pad(bias.astype(jnp.float32),
                         ((0, 0), (0, 0), (0, 0), (0, pad)))
        bias_s = jnp.moveaxis(bias_p.reshape(bb, bh, bq, n_k, bk), 3, 0)
    else:
        bias_s = jnp.zeros((n_k, 1, 1, 1, 1), jnp.float32)
    if has_seg:
        kseg_p = jnp.pad(kv_seg.astype(jnp.int32), ((0, 0), (0, pad)),
                         constant_values=-1)
        kseg_s = jnp.moveaxis(kseg_p.reshape(b, n_k, bk), 1, 0)
        qseg = q_seg.astype(jnp.int32)
    else:
        kseg_s = jnp.zeros((n_k, 1, 1), jnp.int32)
        qseg = None

    def block_scores(kb, kpos, bias_blk, kseg_blk):
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * scale_v
        if has_bias:
            s = s + bias_blk
        live = (kpos < lk)[None, :]  # (1, bk) -> broadcast (lq, bk)
        if causal:
            live = live & (q_pos[:, None] + offset >= kpos[None, :])
        live = live[None, None]  # (1, 1, lq, bk)
        if has_seg:
            live = live & (qseg[:, None, :, None] ==
                           kseg_blk[:, None, None, :])
        return jnp.where(live, s, _NEG), live

    # pass 1: streaming softmax stats (m, l) per query row
    def stats_step(carry, xs):
        m, l = carry
        kb, kpos, bias_blk, kseg_blk = xs
        s, live = block_scores(kb, kpos, bias_blk, kseg_blk)
        new_m = jnp.maximum(m, jnp.max(s, axis=-1))
        l = l * jnp.exp(m - new_m) + jnp.sum(
            jnp.where(live, jnp.exp(s - new_m[..., None]), 0.0), axis=-1)
        return (new_m, l), None

    if m_s is not None:
        # forward already saved the softmax stats — pass 1 unnecessary
        m = m_s.astype(jnp.float32)
        l = l_s.astype(jnp.float32)
    else:
        m0 = jnp.full((b, h, lq), _NEG, jnp.float32)
        l0 = jnp.zeros((b, h, lq), jnp.float32)
        (m, l), _ = jax.lax.scan(stats_step, (m0, l0),
                                 (kb_s, kpos_s, bias_s, kseg_s))
    l_safe = jnp.maximum(l, 1e-20)
    # D_i = sum_j P~_ij (dO_i · V_j) = dO_i · O_i  (flash-bwd identity;
    # holds with dropout because O already contains the dropped P~)
    D = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # (b, h, lq)
    if has_drop:
        thr = _drop_threshold(dropout_p)
        inv_keep = 1.0 / (1.0 - dropout_p)
        b_idx = jnp.arange(b, dtype=jnp.int32)[:, None, None, None]
        h_idx = jnp.arange(h, dtype=jnp.int32)[None, :, None, None]

    # pass 2: accumulate dQ; emit per-block dK/dV (and dbias tiles)
    def grad_step(dq, xs):
        kb, vb, kpos, bias_blk, kseg_blk = xs
        s, live = block_scores(kb, kpos, bias_blk, kseg_blk)
        p = jnp.where(live, jnp.exp(s - m[..., None]), 0.0) / l_safe[
            ..., None]
        if has_drop:
            bits = _keep_bits(seed[0], seed[1], b_idx, h_idx,
                              q_pos[None, None, :, None],
                              kpos[None, None, None, :])
            t = jnp.where(bits >= thr, inv_keep, 0.0)
            p_t = p * t
        else:
            p_t = p
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vb)
        # softmax jacobian: dL/ds = P (t·dp − D); the q·k scale folds into
        # dq/dk below, while dbias takes the unscaled dL/ds
        ds_raw = p * ((dp * t if has_drop else dp) - D[..., None])
        ds = ds_raw * scale_v
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kb)
        dkb = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        dvb = jnp.einsum("bhqk,bhqd->bhkd", p_t, gf)
        if has_bias:
            db = ds_raw
            if bb == 1:
                db = jnp.sum(db, axis=0, keepdims=True)
            if bh == 1:
                db = jnp.sum(db, axis=1, keepdims=True)
            if bq == 1:
                db = jnp.sum(db, axis=2, keepdims=True)
        else:
            db = jnp.zeros((1, 1, 1, bk), jnp.float32)
        return dq, (dkb, dvb, db)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_s, dv_s, db_s) = jax.lax.scan(
        grad_step, dq0, (kb_s, vb_s, kpos_s, bias_s, kseg_s))
    dk = jnp.moveaxis(dk_s, 0, 2).reshape(b, h, n_k * bk, d)[:, :, :lk]
    dv = jnp.moveaxis(dv_s, 0, 2).reshape(b, h, n_k * bk, d)[:, :, :lk]
    if has_bias:
        dbias = jnp.moveaxis(db_s, 0, 3).reshape(
            bb, bh, bq, n_k * bk)[..., :lk].astype(bias.dtype)
    else:
        dbias = None
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias, dseg_q, dseg_kv, dseed)


_flash_core.defvjp(_fwd, _bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, *, bias=None, q_segment_ids=None,
                    kv_segment_ids=None, dropout_p=0.0, dropout_seed=None):
    """Fused attention: Pallas kernel on TPU, jnp fallback elsewhere.

    Args:
      q, k, v: (B, H, L, D).
      bias: optional additive f32 mask/bias, shape (B|1, H|1, Lq|1, Lk) —
        the BERT (B, 1, 1, L) padding mask streams as (1, block_k) tiles.
      q_segment_ids / kv_segment_ids: optional (B, Lq)/(B, Lk) int arrays;
        attention masked where segments differ (packed sequences).
      dropout_p: attention-prob dropout; requires ``dropout_seed`` (int,
        PRNG key, or (2,) int array).  The mask is hash-derived in-kernel.

    Default blocks come from ``_resolve_blocks``: 1024x1024 (clean),
    1024x512 (dropout), 512x512 (full (Lq, Lk) bias), sized against the
    v5e ~16 MB scoped-VMEM budget — see that function's docstring for the
    measured limits that set them."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    if bias is not None:
        bias = jnp.asarray(bias)
        if bias.ndim != 4 or bias.shape[3] != lk or \
                bias.shape[0] not in (1, b) or bias.shape[1] not in (1, h) \
                or bias.shape[2] not in (1, lq):
            raise ValueError(
                f"bias shape {bias.shape} not broadcastable to "
                f"({b}|1, {h}|1, {lq}|1, {lk})")
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("q_segment_ids and kv_segment_ids must be given "
                         "together")
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    seed = _normalize_seed(dropout_seed) if dropout_p > 0.0 else None
    full_bias = bias is not None and bias.shape[2] > 1
    block_q, block_k = _resolve_blocks(block_q, block_k, full_bias,
                                       dropout=dropout_p > 0.0)
    return _flash_core(q, k, v, bias, q_segment_ids, kv_segment_ids, seed,
                       causal, scale, float(dropout_p), block_q, block_k)


_STEP_FNS: dict = {}


def flash_attention_step(q, k, v, causal=False):
    """:func:`flash_attention` compiled through the choke point.

    Eager callers (bench legs, serving paths outside a train step) get
    the kernel-plane contract: the program lowers via ``compile_step``/
    ``timed_compile`` under the ``kernel_flash_attention`` label, so the
    persistent cache, ``zoo_compile_seconds`` and the HLO feature pipe
    all see it.  ``causal`` selects a separate cached program —
    PlannedStep keys python scalars by type only, so it must not be a
    traced argument."""
    from analytics_zoo_tpu.ops.pallas import kernel_step

    causal = bool(causal)
    fn = _STEP_FNS.get(causal)
    if fn is None:
        def fn(q, k, v, _causal=causal):
            return flash_attention(q, k, v, causal=_causal)

        _STEP_FNS[causal] = fn
    name = "flash_attention_causal" if causal else "flash_attention"
    return kernel_step(name, fn)(q, k, v)
