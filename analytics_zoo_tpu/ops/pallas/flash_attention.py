"""Flash attention — Pallas TPU kernel with streaming softmax.

The hot op behind TransformerLayer/BERT (reference materializes the full
(L, L) score matrix per head, TransformerLayer.scala:137).  This kernel
tiles Q over the grid and streams K/V blocks through VMEM with the
numerically-stable online-softmax accumulation, so HBM traffic is O(L·D)
per head instead of O(L²), and the score block lives only in VMEM where the
MXU consumes it.

Gradient support: ``flash_attention`` is wrapped in jax.custom_vjp; the
backward pass recomputes attention blockwise with jnp (rematerialisation —
the standard flash backward strategy) so training works everywhere while the
forward runs the Pallas kernel on TPU.  On CPU (tests) the forward falls
back to the jnp path automatically.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_NEG = -1e30


def _attention_reference(q, k, v, causal, scale):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool), lk - lq)
        scores = jnp.where(mask, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    n_k = pl.cdiv(lk, block_k)

    def kernel(q_ref, k_ref, v_ref, o_ref):
        # q_ref: (block_q, d); k_ref/v_ref: (lk, d) resident in VMEM
        qi = pl.program_id(2)
        qb = q_ref[0, 0].astype(jnp.float32)
        m = jnp.full((block_q, 1), _NEG, jnp.float32)
        l = jnp.zeros((block_q, 1), jnp.float32)
        acc = jnp.zeros((block_q, d), jnp.float32)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)

        def body(ki, carry):
            m, l, acc = carry
            kb = k_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(
                jnp.float32)
            vb = v_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(
                jnp.float32)
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if causal:
                k_pos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (1, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, _NEG)
            new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m - new_m)
            p = jnp.exp(s - new_m)
            if causal:
                p = jnp.where(q_pos >= k_pos, p, 0.0)
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return new_m, l, acc

        if causal:
            # skip key blocks entirely after this query block
            n_live = jax.lax.div(
                (qi + 1) * block_q + block_k - 1, block_k
            )
            n_live = jnp.minimum(n_live, n_k)
        else:
            n_live = n_k
        m, l, acc = jax.lax.fori_loop(0, n_live, body, (m, l, acc))
        o_ref[0, 0] = (acc / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)

    grid = (b, h, pl.cdiv(lq, block_q))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, lk, d), lambda bi, hi, qi: (bi, hi, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, lk, d), lambda bi, hi, qi: (bi, hi, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
    )(q, k, v)


def _pallas_available() -> bool:
    return jax.default_backend() == "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=256,
                    block_k=256):
    """Fused attention: Pallas kernel on TPU, jnp fallback elsewhere."""
    scale = 1.0 / math.sqrt(q.shape[-1]) if scale is None else scale
    if _pallas_available():
        try:
            return _flash_fwd_pallas(q, k, v, causal, scale, block_q,
                                     block_k)
        except Exception:
            pass
    return _attention_reference(q, k, v, causal, scale)


def _fwd(q, k, v, causal, scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    scale_v = 1.0 / math.sqrt(q.shape[-1]) if scale is None else scale

    def ref(q, k, v):
        return _attention_reference(q, k, v, causal, scale_v)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
