"""The Pallas kernel plane — hand-tuned kernels behind the compile
choke point.

Every kernel in this package follows one contract (docs/performance.md
"Kernel plane"):

* a pure-jnp/XLA **fallback** that is the numerical oracle — CPU runs
  it automatically, ``ZOO_KERNEL_INTERPRET=1`` forces the Pallas path
  in interpret mode for kernel-path CI coverage, and
  ``ZOO_KERNEL_FORCE_PALLAS=1`` routes to the real kernels for
  lowering-only checks (trace + ``lower(platforms=("tpu",))``, no chip);
* eager compiles route through :func:`kernel_step` so they lower via
  ``compile_step``/``timed_compile`` under a ``kernel_<name>`` label —
  persistent cache, ``zoo_compile_seconds`` and the HLO feature pipe
  see every kernel;
* selection is the plan's business, not the call site's: the
  ``kernel_rules`` table on :class:`ShardingPlan` (fifth rule table)
  maps scopes to kernel names, and consumers ask
  ``resolve_kernel(scope)`` — ``"xla"`` means the fallback, always.

This ``__init__`` must stay import-light: it is pulled in by
``ops/attention.py`` on every call and the negative pin asserts that
without ``ZOO_USE_PALLAS`` no kernel MODULE below it is imported.
"""

from __future__ import annotations

import sys

# kernel name -> module path, for the invocation-count aggregator; only
# modules ALREADY imported are consulted (the negative pin's contract)
_KERNEL_MODULES = {
    "flash_attention": "analytics_zoo_tpu.ops.pallas.flash_attention",
    "fused_adam": "analytics_zoo_tpu.ops.pallas.fused_adam",
    "fused_softmax_xent":
        "analytics_zoo_tpu.ops.pallas.fused_softmax_xent",
    "int8_matmul": "analytics_zoo_tpu.ops.pallas.int8_matmul",
}

_PLANNED_STEPS: dict = {}


def kernel_step(name: str, fn):
    """Compile ``fn`` through the choke point under the
    ``kernel_<name>`` label and cache the :class:`PlannedStep`.

    This is how EAGER kernel invocations (bench legs, serving helpers)
    get the same treatment as a train step: persistent-cache
    hit/miss counters, ``zoo_compile_seconds{label="kernel_<name>"}``,
    the HLO lint/feature pipe and flight records.  Calls from inside a
    trace must NOT come here — they inline into the enclosing step's
    program and are already covered by its label."""
    key = (name, fn)
    step = _PLANNED_STEPS.get(key)
    if step is None:
        from analytics_zoo_tpu.parallel.plan import compile_step

        step = compile_step(fn, label=f"kernel_{name}")
        _PLANNED_STEPS[key] = step
    return step


def kernel_invocation_counts() -> dict:
    """Per-kernel ``{"pallas": n, "fallback": n}`` routing counters,
    aggregated over the kernel modules that are actually imported —
    an unimported kernel contributes nothing (so the ZOO_USE_PALLAS
    negative pin can assert absence here too)."""
    out = {}
    for name, modpath in _KERNEL_MODULES.items():
        mod = sys.modules.get(modpath)
        counts = getattr(mod, "invocation_counts", None)
        if counts:
            out[name] = dict(counts)
    return out


def record_kernel_bytes(label: str, measured_bytes: int,
                        predicted_bytes: int | None = None) -> dict:
    """Publish the ``zoo_kernel_*bytes*`` gauges for one kernel label —
    closing the bytes loop the way ``record_mem_gauges`` does for chip
    memory: measured HLO bytes-accessed (hlo.py's custom_call
    attribution) vs the cost model's analytic prediction."""
    from analytics_zoo_tpu.metrics import get_registry

    reg = get_registry()
    lab = ("label",)
    reg.gauge("zoo_kernel_measured_bytes",
              "measured HLO bytes-accessed for a kernel label",
              lab).labels(label=label).set(int(measured_bytes))
    doc = {"measured_bytes": int(measured_bytes)}
    if predicted_bytes is not None:
        reg.gauge("zoo_kernel_predicted_bytes",
                  "cost-model predicted bytes for a kernel label",
                  lab).labels(label=label).set(int(predicted_bytes))
        doc["predicted_bytes"] = int(predicted_bytes)
        if predicted_bytes > 0:
            rel = abs(measured_bytes - predicted_bytes) / predicted_bytes
            reg.gauge("zoo_kernel_bytes_rel_error",
                      "|measured - predicted| / predicted bytes for a "
                      "kernel label", lab).labels(label=label).set(rel)
            doc["rel_error"] = rel
    return doc
