# zoolint: disable-file=raw-pallas-call -- ops/pallas/ is the one home
# for raw pl.pallas_call; everything here ships a jnp fallback oracle and
# lowers under a kernel_* label through the compile choke point.
"""Fused Adam — one Pallas kernel per param block instead of optax's
unfused elementwise chain.

``optax.adam`` lowers to ~10 separate elementwise HLO ops per leaf
(two moment EMAs, two bias corrections, rsqrt, scale) and XLA's fusion
usually — but not contractually — merges them.  This kernel does the
whole update (moment update + bias correction + param delta) in a
single HBM round-trip per block: read (g, mu, nu), write (upd, mu',
nu').  Bytes accessed per step is exactly ``24·N`` (6 f32 arrays of N
params) plus the scalar block, which is what
:func:`analytics_zoo_tpu.analysis.costmodel.kernel_bytes` predicts and
the bench's cross-lowered HLO measurement checks against.

Exposed as an optax-compatible ``GradientTransformation`` so the
estimator swaps it in transparently under a plan whose ``kernel_rules``
map ``optimizer.adam`` to ``fused_adam``:

* ``init`` delegates to the inner ``optax.adam`` — the optimizer state
  STRUCTURE (``ScaleByAdamState`` + lr-scaling state) is identical, so
  checkpoints, ZeRO sharding rules and ``opt_rules`` regexes all apply
  unchanged.
* On the fallback path ``update`` delegates to the inner optax chain
  verbatim — BITWISE identical to ``optax.adam`` by construction (the
  "bitwise where achievable" contract; the bench records it).
* On the Pallas path (TPU, or ``ZOO_KERNEL_INTERPRET=1`` interpret
  mode) f32 leaves run the fused kernel; the bias corrections
  ``1 - b**t`` are computed once outside the kernel and passed through
  SMEM with the other scalars.  Tolerance vs optax: ~1e-6 relative
  (same formula, different fma association).

Schedule semantics match ``optax.scale_by_schedule``: a callable
learning rate is evaluated at the PRE-increment count.
"""

from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp
import optax

# Trace/dispatch-time routing counters (tests + zoo_kernel_invocations
# read these; jit traces once so the pallas counter counts compilations).
invocation_counts = {"pallas": 0, "fallback": 0}

_LANES = 128
_BLOCK_ROWS = 512


def _env_flag(name: str) -> bool:
    # same convention as engine.py's ZOO_SHARD_OPTIMIZER: "0"/"" are false
    return os.environ.get(name, "") not in ("", "0")


def _interpret_forced() -> bool:
    return _env_flag("ZOO_KERNEL_INTERPRET")


def _pallas_available() -> bool:
    # ZOO_KERNEL_FORCE_PALLAS routes to the REAL (non-interpret) kernel on
    # any backend — lowering-only CI: trace + lower(platforms=("tpu",))
    # goes through genuine Mosaic lowering with no chip.  Executing under
    # this knob off-TPU will fail — lower, don't run.
    return (jax.default_backend() == "tpu" or _interpret_forced()
            or _env_flag("ZOO_KERNEL_FORCE_PALLAS"))


_warned_fallback = False


def _warn_fallback_once():
    global _warned_fallback
    if not _warned_fallback:
        _warned_fallback = True
        logging.getLogger("analytics_zoo_tpu").exception(
            "Pallas fused-adam kernel failed on TPU; falling back to "
            "the unfused optax chain. THIS IS A PERFORMANCE BUG.")


def _adam_kernel(scal_ref, g_ref, mu_ref, nu_ref,
                 upd_ref, mu_out_ref, nu_out_ref):
    """One block: read (g, mu, nu), write (upd, mu', nu').

    scal_ref (SMEM, (6,) f32): lr, b1, b2, eps, bc1, bc2 where
    bc* = 1 - beta***count_inc (computed outside — scalar transcendental
    on a traced int has no business on the VPU's hot path).
    """
    lr = scal_ref[0]
    b1 = scal_ref[1]
    b2 = scal_ref[2]
    eps = scal_ref[3]
    bc1 = scal_ref[4]
    bc2 = scal_ref[5]
    g = g_ref[...]
    mu = b1 * mu_ref[...] + (1.0 - b1) * g
    nu = b2 * nu_ref[...] + (1.0 - b2) * g * g
    # optax order: mu_hat/(sqrt(nu_hat + eps_root=0) + eps), scaled -lr.
    # zero padding is benign: 0 / (sqrt(0) + eps) = 0.
    upd_ref[...] = -lr * (mu / bc1) / (jnp.sqrt(nu / bc2) + eps)
    mu_out_ref[...] = mu
    nu_out_ref[...] = nu


def _adam_leaf_pallas(g, mu, nu, scalars, interpret):
    """Run the fused kernel on one flattened f32 leaf.

    The leaf is padded to a (rows, 128) layout with rows a multiple of
    the block size — min f32 tile is (8, 128) and _BLOCK_ROWS is
    8-aligned, so padding once covers both constraints.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = g.size
    rows = max(-(-n // _LANES), 1)
    block_rows = min(_BLOCK_ROWS, -(-rows // 8) * 8)
    n_blocks = -(-rows // block_rows)
    total = n_blocks * block_rows * _LANES

    def prep(a):
        flat = a.astype(jnp.float32).reshape(-1)
        return jnp.pad(flat, (0, total - n)).reshape(-1, _LANES)

    block = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    shape = jax.ShapeDtypeStruct((total // _LANES, _LANES), jnp.float32)
    upd, mu2, nu2 = pl.pallas_call(
        _adam_kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((6,), lambda i: (0,),
                         memory_space=pltpu.SMEM),
            block, block, block,
        ],
        out_specs=[block, block, block],
        out_shape=[shape, shape, shape],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(scalars, prep(g), prep(mu), prep(nu))

    def unprep(a):
        return a.reshape(-1)[:n].reshape(g.shape)

    return unprep(upd), unprep(mu2), unprep(nu2)


def _adam_leaf_reference(g, mu, nu, scalars):
    """jnp oracle with the kernel's exact formula (per-leaf tests)."""
    lr, b1, b2, eps, bc1, bc2 = [scalars[i] for i in range(6)]
    g = g.astype(jnp.float32)
    mu2 = b1 * mu + (1.0 - b1) * g
    nu2 = b2 * nu + (1.0 - b2) * g * g
    upd = -lr * (mu2 / bc1) / (jnp.sqrt(nu2 / bc2) + eps)
    return upd, mu2, nu2


def _fused_update(updates, state, b1, b2, eps, lr_fn):
    """The fused tree update: pallas for f32 leaves, the reference
    formula (identical math) for everything else."""
    adam_state, *rest = state
    count_inc = optax.safe_int32_increment(adam_state.count)
    # scale_by_schedule evaluates at the PRE-increment count
    lr = jnp.asarray(lr_fn(adam_state.count), jnp.float32)
    bc1 = 1.0 - jnp.asarray(b1, jnp.float32) ** count_inc
    bc2 = 1.0 - jnp.asarray(b2, jnp.float32) ** count_inc
    scalars = jnp.stack([
        lr, jnp.float32(b1), jnp.float32(b2), jnp.float32(eps), bc1, bc2])
    interpret = _interpret_forced()

    def leaf(g, mu, nu):
        if g.dtype == jnp.float32 and g.size >= _LANES:
            return _adam_leaf_pallas(g, mu, nu, scalars, interpret)
        return _adam_leaf_reference(g, mu, nu, scalars)

    g_leaves, treedef = jax.tree_util.tree_flatten(updates)
    triples = [leaf(g, m, n) for g, m, n in zip(
        g_leaves,
        jax.tree_util.tree_leaves(adam_state.mu),
        jax.tree_util.tree_leaves(adam_state.nu))]
    upd = treedef.unflatten([t[0] for t in triples])
    mu2 = treedef.unflatten([t[1] for t in triples])
    nu2 = treedef.unflatten([t[2] for t in triples])
    new_adam = adam_state._replace(count=count_inc, mu=mu2, nu=nu2)
    # the lr-scaling tail state: EmptyState for a constant lr,
    # ScaleByScheduleState(count) for a schedule — keep its count in
    # lockstep so checkpoints resume identically either way
    new_rest = tuple(
        r._replace(count=count_inc)
        if "count" in getattr(r, "_fields", ()) else r
        for r in rest)
    return upd, (new_adam, *new_rest)


def fused_adam(learning_rate=0.001, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-8) -> optax.GradientTransformation:
    """Optax-compatible fused Adam (drop-in for ``optax.adam``).

    ``learning_rate`` may be a float or an optax schedule (callable of
    the step count), exactly like ``optax.adam``.  State structure and
    the fallback trajectory are identical to ``optax.adam`` — the
    kernel only changes HOW the same numbers move through HBM.
    """
    inner = optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
    lr_fn = learning_rate if callable(learning_rate) \
        else (lambda _count, _lr=learning_rate: _lr)

    def init_fn(params):
        return inner.init(params)

    def update_fn(updates, state, params=None):
        if not _pallas_available():
            invocation_counts["fallback"] += 1
            return inner.update(updates, state, params)
        if not (isinstance(state, tuple) and len(state) >= 1
                and hasattr(state[0], "mu")):
            # unexpected state structure (wrapped/injected) — the inner
            # chain is the contract, never guess
            invocation_counts["fallback"] += 1
            return inner.update(updates, state, params)
        try:
            out = _fused_update(updates, state, b1, b2, eps, lr_fn)
            invocation_counts["pallas"] += 1
            return out
        except Exception:
            _warn_fallback_once()
            invocation_counts["fallback"] += 1
            return inner.update(updates, state, params)

    return optax.GradientTransformation(init_fn, update_fn)
