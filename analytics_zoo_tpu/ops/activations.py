"""Activation registry — reference keras ``Activation`` layer supports these
by name (pipeline/api/keras/layers/Activation and KerasUtils string mapping).
All map to jax.nn primitives so XLA fuses them into the surrounding matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "log_softmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.swish,
    "silu": jax.nn.silu,
    "linear": lambda x: x,
    None: lambda x: x,
}


class NamedActivation:
    """Picklable by-name activation (model save/load keeps the name, the
    function is resolved at call time)."""

    def __init__(self, name):
        self.name = name

    def __call__(self, x):
        return _ACTIVATIONS[self.name](x)

    def __repr__(self):
        return f"activation({self.name})"


def get_activation(identifier):
    if identifier is None:
        return NamedActivation(None)
    if callable(identifier):
        return identifier
    key = identifier.lower() if isinstance(identifier, str) else identifier
    if key in _ACTIVATIONS:
        return NamedActivation(key)
    raise ValueError(f"unknown activation {identifier!r}")
