from analytics_zoo_tpu.ops.activations import get_activation  # noqa: F401
