"""Routed mixture-of-experts feed-forward for the Keras model surface.

The reference framework has no MoE at all (its TransformerLayer.scala:137
feed-forward is a dense 4x MLP); SURVEY.md §2.4 makes expert parallelism a
first-class axis of this framework, and round 4 landed the *strategies*
level (``parallel.strategies.moe_mlp_topk``: shard_map + ``all_to_all``
dispatch).  This module is the model-surface counterpart: the same
GShard/Switch top-k + capacity semantics expressed as **dense one-hot
dispatch einsums**, so it composes with the estimator's single GSPMD
``jit`` train step (no ``shard_map`` axis context needed — XLA partitions
the expert dimension and inserts the all_to_all from the sharding
constraint below).

Capacity semantics (GShard): every token proposes its top-k experts; the
assignment stream is priority-ordered (all 1st choices outrank any 2nd
choice) and each expert accepts at most ``C = ceil(cf * k * S / E)``
tokens per group (group = one batch row).  Over-capacity assignments
contribute ZERO to the expert output — callers MUST place this op behind
a residual connection (as ``_TransformerCore._block_forward_aux`` does)
so a dropped token degrades to identity, never to a zeroed activation.
``tests/test_moe_layer.py::test_skewed_routing_*`` pins exactly that.

The auxiliary load-balancing loss is the GShard/Switch one:
``E * sum_e mean_prob_e * frac_first_choice_e`` — ~1.0 when balanced,
up to ~E when collapsed onto one expert.  Under the GSPMD step the batch
means are global (jit sees global shapes), so no pmean is needed.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def collect_aux_cost(state):
    """Sum every ``moe_aux_cost`` leaf in a model state tree: the
    pre-weighted auxiliary losses MoE stacks report through the layer
    state channel (keras/layers/self_attention.py ``_moe_state``).  Every
    train-step builder that computes a loss from ``model.forward`` must
    add this to the task loss, or a collapsed router trains unpenalized."""
    total = jnp.zeros((), jnp.float32)
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        last = path[-1]
        key = getattr(last, "key", getattr(last, "name", None))
        if key == "moe_aux_cost":
            total = total + leaf.astype(jnp.float32)
    return total


def _constrain_expert_axis(x):
    """Pin the leading (expert) dim of ``x`` to the mesh ``expert`` axis
    when the active context mesh has one — this is what turns the dispatch
    einsum into an all_to_all + per-shard expert MLP under GSPMD."""
    try:
        from analytics_zoo_tpu.common.engine import (
            EXPERT_AXIS,
            get_zoo_context,
        )

        mesh = get_zoo_context().mesh
    except Exception:
        return x
    if dict(mesh.shape).get(EXPERT_AXIS, 1) <= 1:
        return x
    from jax.sharding import NamedSharding, PartitionSpec as P

    # inside a shard_map body (manual axes) constraints over mesh axes
    # are rejected at lowering — there the caller's own specs govern
    # layout and the expert compute runs shard-local; the constraint is
    # only for the GSPMD (estimator) path
    if EXPERT_AXIS in getattr(jax.sharding.get_abstract_mesh(),
                              "manual_axes", ()):
        return x
    spec = P(EXPERT_AXIS, *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def routed_ffn(h, gate_w, w1, b1, w2, b2, *, top_k=2, capacity_factor=1.25,
               activation=jax.nn.gelu, renormalize=False):
    """Top-k routed MoE feed-forward on ``(B, S, D)`` activations.

    Args:
      h: (B, S, D) tokens.
      gate_w: (D, E) router.
      w1: (E, D, F), b1: (E, F), w2: (E, F, D), b2: (D,).
      top_k: experts per token.
      capacity_factor: per-expert capacity multiplier (C = ceil(cf*k*S/E)).
      renormalize: rescale the k gate values to sum to 1 (GShard top-2
        convention); default False (Switch: raw softmax probs).

    Returns ``(y, aux, drop_fraction)``: y (B, S, D) — ZERO rows for
    fully-dropped tokens (use behind a residual); aux — the f32 scalar
    load-balancing loss; drop_fraction — f32 scalar fraction of the k*B*S
    assignments that exceeded capacity.
    """
    b, s, d = h.shape
    e = gate_w.shape[-1]
    if top_k > e:
        raise ValueError(f"top_k={top_k} > n_experts={e}")
    cap = int(math.ceil(capacity_factor * top_k * s / e))
    cap = max(1, min(cap, s))

    # routing in f32 regardless of compute dtype (tiny, precision-critical)
    probs = jax.nn.softmax(
        h.astype(jnp.float32) @ gate_w.astype(jnp.float32),
        axis=-1)                                          # (B, S, E)
    top_vals, top_idx = jax.lax.top_k(probs, top_k)       # (B, S, k)
    if renormalize:
        top_vals = top_vals / jnp.sum(top_vals, -1, keepdims=True)

    # priority-ordered capacity race: choice j's position within an expert
    # counts every earlier token's j-th choice AND all previous choices
    counts = jnp.zeros((b, 1, e), jnp.float32)
    dispatch = jnp.zeros((b, s, e, cap), h.dtype)
    combine = jnp.zeros((b, s, e, cap), h.dtype)
    kept = jnp.zeros((), jnp.float32)
    for j in range(top_k):
        m = jax.nn.one_hot(top_idx[..., j], e, dtype=jnp.float32)
        pos = jnp.cumsum(m, axis=1) - 1.0 + counts        # (B, S, E)
        keep = m * (pos < cap)
        slot = jax.nn.one_hot(jnp.clip(pos, 0, cap - 1).astype(jnp.int32),
                              cap, dtype=jnp.float32)     # (B, S, E, C)
        dc = (keep[..., None] * slot).astype(h.dtype)
        dispatch = dispatch + dc
        combine = combine + dc * top_vals[..., j, None, None].astype(h.dtype)
        counts = counts + jnp.sum(m, axis=1, keepdims=True)
        kept = kept + jnp.sum(keep)

    # gather each expert's C tokens per group: (E, B, C, D) -> (E, B*C, D)
    xin = jnp.einsum("bsec,bsd->ebcd", dispatch, h)
    xin = _constrain_expert_axis(xin.reshape(e, b * cap, d))
    h1 = activation(jnp.einsum("etd,edf->etf", xin, w1) + b1[:, None, :])
    # b2 joins INSIDE the expert output (before the gate-weighted
    # combine): a fully-dropped token's row stays exactly zero even after
    # b2 trains away from zero — the residual-passthrough contract.  For
    # kept tokens the bias arrives scaled by the gate sum, and with
    # top_k=E full dispatch this reduces to +b2 (probs sum to 1), so the
    # dense-mixture oracle is unchanged.
    ye = (jnp.einsum("etf,efd->etd", h1, w2)
          + b2[None, None, :]).reshape(e, b, cap, d)
    y = jnp.einsum("bsec,ebcd->bsd", combine, ye)

    # GShard load balance: mean router prob x fraction-of-first-choices
    me = jnp.mean(probs, axis=(0, 1))                           # (E,)
    ce = jnp.mean(jax.nn.one_hot(top_idx[..., 0], e,
                                 dtype=jnp.float32), axis=(0, 1))
    aux = e * jnp.sum(me * ce)
    drop_fraction = 1.0 - kept / float(top_k * b * s)
    return y, aux, drop_fraction
