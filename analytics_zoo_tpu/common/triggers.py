"""Trigger algebra for validation / checkpoint / end-of-training scheduling.

TPU-native re-design of the reference's ``ZooTrigger`` family
(zoo/.../common/ZooTrigger.scala:25-80): triggers are pure predicates over a
``TrainingState`` record, so they compose (`And`/`Or`) and serialize trivially
with checkpoints.  The reference's triggers close over a BigDL optimizer state
table; ours take an explicit immutable state — no hidden mutation, which keeps
the training loop a pure host-side driver around one jitted step.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class TrainingState:
    """Host-side training progress record checked by triggers."""

    epoch: int = 1           # 1-based current epoch
    iteration: int = 0       # global step count (optimizer updates)
    epoch_finished: bool = False  # True exactly when an epoch boundary was hit
    loss: float | None = None
    score: float | None = None    # last validation score (higher is better)
    records_in_epoch: int = 0


class ZooTrigger:
    """Base trigger: callable ``trigger(state) -> bool``.

    Reference: ZooTrigger.scala:25-35.
    """

    def __call__(self, state: TrainingState) -> bool:
        raise NotImplementedError

    def __and__(self, other: "ZooTrigger") -> "ZooTrigger":
        return And(self, other)

    def __or__(self, other: "ZooTrigger") -> "ZooTrigger":
        return Or(self, other)


class EveryEpoch(ZooTrigger):
    """Fires at each epoch boundary (ZooTrigger.scala:42-67)."""

    def __call__(self, state: TrainingState) -> bool:
        return state.epoch_finished


class SeveralIteration(ZooTrigger):
    """Fires every ``interval`` optimizer steps (ZooTrigger.scala:69-80).

    Boundary-crossing semantics: fires when a multiple of ``interval``
    lies in ``(previous observed iteration, current iteration]``.  For
    the classic one-step-at-a-time loop this is exactly the historical
    ``iteration % interval == 0``; under the fused multi-step dispatch
    (``ZOO_STEPS_PER_DISPATCH=K``), where the loop observes iterations
    in strides of K, it keeps the configured cadence (fires at the first
    boundary past each multiple) instead of collapsing to
    ``lcm(K, interval)``.  Re-observing the same iteration (the
    epoch-boundary callback) behaves as before.
    """

    def __init__(self, interval: int):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self.interval = int(interval)
        self._prev: int | None = None

    def __call__(self, state: TrainingState) -> bool:
        it, n = state.iteration, self.interval
        if it <= 0:
            return False
        prev, self._prev = self._prev, it
        if prev is None or it <= prev:
            # first observation (incl. a resume mid-run: no catch-up
            # firing for multiples crossed before this trigger existed)
            # or a same-iteration re-call — historical exact-hit rule
            return it % n == 0
        return (it // n) > (prev // n)


class MaxEpoch(ZooTrigger):
    """End-trigger: stop after ``max_epoch`` epochs complete."""

    def __init__(self, max_epoch: int):
        self.max_epoch = int(max_epoch)

    def __call__(self, state: TrainingState) -> bool:
        return state.epoch > self.max_epoch


class MaxIteration(ZooTrigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = int(max_iteration)

    def __call__(self, state: TrainingState) -> bool:
        return state.iteration >= self.max_iteration


class MinLoss(ZooTrigger):
    def __init__(self, min_loss: float):
        self.min_loss = float(min_loss)

    def __call__(self, state: TrainingState) -> bool:
        return state.loss is not None and state.loss < self.min_loss


class MaxScore(ZooTrigger):
    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def __call__(self, state: TrainingState) -> bool:
        return state.score is not None and state.score > self.max_score


class And(ZooTrigger):
    def __init__(self, first: ZooTrigger, *rest: ZooTrigger):
        self.triggers = (first,) + rest

    def __call__(self, state: TrainingState) -> bool:
        return all(t(state) for t in self.triggers)


class Or(ZooTrigger):
    def __init__(self, first: ZooTrigger, *rest: ZooTrigger):
        self.triggers = (first,) + rest

    def __call__(self, state: TrainingState) -> bool:
        return any(t(state) for t in self.triggers)
