"""Persistent compile plane — pay XLA compilation once, not per process.

Every process start (and every new batch/bucket shape) pays a full XLA
compile before the first useful step; on a big model that is minutes of
dead time, and on this harness's tunneled TPU it is the dominant
time-to-first-step cost (BENCH_r05.json).  This module is the shared
cure, three pieces:

1. :func:`maybe_enable_persistent_cache` — turn on JAX's on-disk
   compilation cache from ``ZOO_COMPILE_CACHE=<dir>`` (or an explicit
   path).  A second process compiling the SAME program (same HLO, same
   shapes/shardings/flags) deserializes the executable instead of
   re-running XLA — the moral equivalent of OpenVINO's saved IR.
2. :func:`timed_compile` — the one choke point every AOT
   ``.lower().compile()`` in the repo goes through: it times the compile
   into ``zoo_compile_seconds{label=...}`` and classifies it as a
   persistent-cache hit or miss (``zoo_compile_cache_hits_total`` /
   ``zoo_compile_cache_misses_total``), so cold-vs-warm shows up in
   ``/varz`` instead of being folded invisibly into the first step.
3. AOT warmup callers — ``Estimator.warmup(batch)`` and
   ``InferenceModel.warmup(...)`` lower+compile their steps through this
   module BEFORE the first real batch/request, so user-visible latency
   starts at step one, not compile one.

Hit/miss classification is observational: a compile that completes
without adding an entry under the enabled cache directory was served
from it (every compile is persisted — ``min_compile_time_secs`` is
pinned to 0).  With no cache dir enabled every compile counts as a miss.
"""

from __future__ import annotations

import logging
import os
import threading
import time

logger = logging.getLogger("analytics_zoo_tpu")

_LOCK = threading.Lock()
_ENABLED_DIR: str | None = None  # guarded-by: _LOCK

# XLA flags the bench's probe-subprocess path validated and adopted for
# this process (latency-hiding scheduler set, sweep winners).  Purely a
# provenance registry: the flags were already applied via XLA_FLAGS /
# jax config by the adopter — recording them here stamps every
# subsequent compile's hlo report (meta["xla_flags"]) so a cost-model
# training row says WHICH scheduler produced its graph.
_ADOPTED_FLAGS: tuple = ()  # guarded-by: _LOCK


def record_adopted_flags(flags) -> tuple:
    """Register XLA flags adopted for this process (idempotent,
    order-preserving union); returns the full adopted set.  Called by
    the bench's probe-validated adoption paths — see
    ``bench.adopt_sweep_flags`` / ``bench.adopt_latency_hiding_flags``.
    """
    global _ADOPTED_FLAGS
    with _LOCK:
        merged = list(_ADOPTED_FLAGS)
        for f in flags:
            f = str(f)
            if f not in merged:
                merged.append(f)
        _ADOPTED_FLAGS = tuple(merged)
        return _ADOPTED_FLAGS


def adopted_flags() -> tuple:
    """The XLA flags recorded via :func:`record_adopted_flags` (empty
    tuple when none were adopted)."""
    return _ADOPTED_FLAGS

# Histogram bounds shaped for compile times: sub-second CPU toys through
# multi-minute TPU programs.
COMPILE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, 120.0, 300.0)


def cache_dir() -> str | None:
    """The enabled persistent-cache directory, or None."""
    return _ENABLED_DIR


def maybe_enable_persistent_cache(path: str | None = None) -> str | None:
    """Enable JAX's persistent compilation cache; idempotent.

    Resolution: explicit ``path`` > ``ZOO_COMPILE_CACHE`` env.  Returns
    the enabled directory, or None when neither is set (no-op — the
    in-memory jit cache still applies).  Safe to call from every train /
    predict entry point: the first call wins and later calls with the
    same (or no) path are no-ops; a later call with a DIFFERENT explicit
    path re-points the cache and logs the switch.
    """
    global _ENABLED_DIR
    if path is None and _ENABLED_DIR is not None:
        # no-arg call after an explicit enable: the first call won —
        # do NOT let the env re-point a deliberately chosen directory
        return _ENABLED_DIR
    resolved = path or os.environ.get("ZOO_COMPILE_CACHE") or None
    if resolved is None:
        return _ENABLED_DIR
    resolved = os.path.abspath(resolved)
    with _LOCK:
        if _ENABLED_DIR == resolved:
            return _ENABLED_DIR
        import jax

        os.makedirs(resolved, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", resolved)
        # Persist EVERYTHING: the default min-compile-time/min-entry-size
        # heuristics would skip exactly the small-but-frequent programs a
        # dispatch-bound harness recompiles most.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:  # knob absent on some jax versions
            pass
        try:
            # The cache singleton initializes LAZILY on the first compile
            # — if any jit ran before this call (context init, PRNG
            # helpers), it memoized "no cache dir" and would silently
            # ignore the directory we just configured.  Reset so the next
            # compile re-initializes against it.
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:  # pragma: no cover - private-ish surface moved
            logger.warning(
                "could not reset jax compilation cache; persistent cache "
                "may stay inactive if jit ran before enablement",
                exc_info=True)
        if _ENABLED_DIR is not None:
            logger.info("compile cache re-pointed %s -> %s",
                        _ENABLED_DIR, resolved)
        else:
            logger.info("persistent compile cache enabled at %s", resolved)
        _ENABLED_DIR = resolved
    return _ENABLED_DIR


def disable_persistent_cache() -> None:
    """Turn the persistent cache back off (tests; symmetric teardown for
    :func:`maybe_enable_persistent_cache`)."""
    global _ENABLED_DIR
    with _LOCK:
        if _ENABLED_DIR is None:
            return
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:  # pragma: no cover
            pass
        _ENABLED_DIR = None


def _cache_entries() -> int | None:
    """Number of executable entries in the enabled cache dir (None when
    disabled).  Only ``*-cache`` payload files count — the ``*-atime``
    companions are touched on reads and would misclassify hits."""
    if _ENABLED_DIR is None:
        return None
    try:
        return sum(1 for f in os.listdir(_ENABLED_DIR)
                   if f.endswith("-cache"))
    except OSError:
        return None


def _metrics(label: str):
    from analytics_zoo_tpu.metrics import get_registry

    reg = get_registry()
    return (
        reg.histogram("zoo_compile_seconds",
                      "wall time of AOT lower().compile() calls",
                      ("label",), buckets=COMPILE_BUCKETS)
        .labels(label=label),
        reg.counter("zoo_compile_cache_hits_total",
                    "AOT compiles served from the persistent cache",
                    ("label",)).labels(label=label),
        reg.counter("zoo_compile_cache_misses_total",
                    "AOT compiles that ran XLA (no persistent-cache "
                    "entry)", ("label",)).labels(label=label),
    )


def timed_compile(lowered, label: str, meta: dict | None = None):
    """``lowered.compile()`` with the compile plane's telemetry.

    Records ``zoo_compile_seconds{label=}`` and increments the
    hit/miss counter pair; returns the compiled executable.  ``lowered``
    is whatever ``jax.jit(f).lower(*args)`` returned.

    The HLO graph lint (``analytics_zoo_tpu.analysis.hlo``) rides this
    choke point: the lowered module text is inspected BEFORE the
    compile — f64 ops / host callbacks / unexpected all-gathers /
    oversized baked constants become logged findings, and the analytic
    cost features (matmul FLOPs, bytes, collective count/bytes,
    fused-dispatch count) land in ``zoo_hlo_*{label=}`` metrics, the
    flight recorder and the optional ``ZOO_HLO_REPORT_DIR`` JSON
    report.  Linting before compiling means a crash during XLA
    compilation still leaves "what was being compiled" in the flight
    ring; the JSON report alone is written AFTER the compile so the
    ``zoo-hlo-report/2`` row carries the measured compile
    wall-seconds.  ``meta`` is the compile context the lowered text
    cannot show (``plan`` / ``mesh_shape`` / ``steps_per_dispatch``),
    stamped into the report for the cost model's training join; any
    flags registered via :func:`record_adopted_flags` are stamped in
    as ``xla_flags`` automatically.
    Disable with ``ZOO_HLO_LINT=0``; lint errors never propagate into
    the compile.
    """
    from analytics_zoo_tpu.analysis.hlo import (
        maybe_lint_lowered,
        maybe_write_report,
    )

    if _ADOPTED_FLAGS:
        meta = dict(meta or {})
        meta.setdefault("xla_flags", _ADOPTED_FLAGS)
    rpt = maybe_lint_lowered(lowered, label, meta=meta,
                             defer_report=True)
    hist, hits, misses = _metrics(label)
    before = _cache_entries()
    t0 = time.perf_counter()
    exe = lowered.compile()
    dt = time.perf_counter() - t0
    hist.observe(dt)
    maybe_write_report(rpt, compile_seconds=dt)
    after = _cache_entries()
    # A true hit deserializes an EXISTING entry, so the dir must be
    # non-empty and unchanged.  (Residual blind spot: a cache dir whose
    # writes fail mid-stream — e.g. volume filled up after some entries
    # landed — still classifies later full compiles as hits; jax logs
    # the write failures.)
    hit = before is not None and after == before and (after or 0) > 0
    if hit:
        hits.inc()
    else:
        misses.inc()
    logger.debug("compile[%s]: %.3fs (%s)", label, dt,
                 "cache hit" if hit else "miss")
    return exe
