from analytics_zoo_tpu.common.engine import (  # noqa: F401
    ZooConfig,
    ZooContext,
    get_zoo_context,
    init_zoo_context,
)
from analytics_zoo_tpu.common.triggers import (  # noqa: F401
    And,
    EveryEpoch,
    MaxEpoch,
    MaxIteration,
    MaxScore,
    MinLoss,
    Or,
    SeveralIteration,
    ZooTrigger,
)
