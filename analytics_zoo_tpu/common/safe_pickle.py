"""Whitelisted deserialization for model/checkpoint files.

Reference parity: ``CheckedObjectInputStream`` (zoo
common/CheckedObjectInputStream.scala) — the reference's one hardening
guard — refuses to deserialize classes outside an expected set, because a
serialized model file is attacker-controlled input.  Python pickle is
worse than Java serialization here (a ``__reduce__`` payload executes
arbitrary callables at load time), so every ``pickle.load`` of a model,
weights treedef, or checkpoint in this framework goes through
:func:`safe_load` / :func:`safe_loads` instead.

Policy: this package's classes, an EXACT list of the reconstruction
entry points that pickles of weight/optimizer pytrees actually reference
(probed empirically from every save path: numpy array/scalar/dtype
reconstruction, jax array/PyTreeDef, optax ``*State`` namedtuples), and a
small closed set of builtins.  Broad module-root allowances are
deliberately NOT used: numpy/jax contain exec-equivalent callables (e.g.
``numpy.testing``'s ``runstring``) that a ``__reduce__`` payload could
name, so anything outside the exact surface — including other
numpy/jax/optax functions, ``os.system``, ``builtins.eval`` — raises
``UnpicklingError``.
"""

from __future__ import annotations

import io
import pickle

# (module, qualname) reconstruction entry points legitimately referenced
# by pickles of parameter/optimizer pytrees + this framework's model
# blobs.  Probed by instrumenting find_class over every save format;
# the numpy.core variants cover files written by numpy < 2.
_ALLOWED_EXACT = {
    ("builtins", "complex"),
    ("builtins", "frozenset"),
    ("builtins", "set"),
    ("builtins", "slice"),
    ("builtins", "range"),
    ("builtins", "bytearray"),
    ("collections", "OrderedDict"),
    ("collections", "defaultdict"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("jax._src.array", "_reconstruct_array"),
    ("jax._src.tree_util", "default_registry"),
    ("jaxlib._jax.pytree", "PyTreeDef"),
}

_JNP_DTYPES = frozenset({
    "bfloat16", "float16", "float32", "float64",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_", "complex64",
})


def _allowed(module: str, name: str) -> bool:
    if module.split(".", 1)[0] == "analytics_zoo_tpu":
        return True
    if (module, name) in _ALLOWED_EXACT:
        return True
    if module == "jax.numpy" and name in _JNP_DTYPES:
        return True
    # optax optimizer-state namedtuples (ScaleByAdamState, TraceState,
    # EmptyState, ScaleByScheduleState, ...): constructing a namedtuple
    # executes no user code
    if module.startswith("optax.") and name.endswith("State"):
        return True
    return False


class CheckedUnpickler(pickle.Unpickler):
    """pickle.Unpickler with a class whitelist (reference
    CheckedObjectInputStream semantics)."""

    def find_class(self, module, name):
        if _allowed(module, name):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"refusing to deserialize {module}.{name}: not in the "
            f"analytics_zoo_tpu allowlist (untrusted model/checkpoint "
            f"file?)"
        )


def safe_load(file):
    return CheckedUnpickler(file).load()


def safe_loads(data: bytes):
    return CheckedUnpickler(io.BytesIO(data)).load()
