"""Whitelisted deserialization for model/checkpoint files.

Reference parity: ``CheckedObjectInputStream`` (zoo
common/CheckedObjectInputStream.scala) — the reference's one hardening
guard — refuses to deserialize classes outside an expected set, because a
serialized model file is attacker-controlled input.  Python pickle is
worse than Java serialization here (a ``__reduce__`` payload executes
arbitrary callables at load time), so every ``pickle.load`` of a model,
weights treedef, or checkpoint in this framework goes through
:func:`safe_load` / :func:`safe_loads` instead.

Policy: this package's classes, an EXACT list of the reconstruction
entry points that pickles of weight/optimizer pytrees actually reference
(probed empirically from every save path: numpy array/scalar/dtype
reconstruction, jax array/PyTreeDef, optax ``*State`` namedtuples), and a
small closed set of builtins.  Broad module-root allowances are
deliberately NOT used: numpy/jax contain exec-equivalent callables (e.g.
``numpy.testing``'s ``runstring``) that a ``__reduce__`` payload could
name, so anything outside the exact surface — including other
numpy/jax/optax functions, ``os.system``, ``builtins.eval`` — raises
``UnpicklingError``.
"""

from __future__ import annotations

import io
import pickle

# (module, qualname) reconstruction entry points legitimately referenced
# by pickles of parameter/optimizer pytrees + this framework's model
# blobs.  Probed by instrumenting find_class over every save format;
# the numpy.core variants cover files written by numpy < 2.
_ALLOWED_EXACT = {
    ("builtins", "complex"),
    ("builtins", "frozenset"),
    ("builtins", "set"),
    ("builtins", "slice"),
    ("builtins", "range"),
    ("builtins", "bytearray"),
    ("collections", "OrderedDict"),
    ("collections", "defaultdict"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("jax._src.array", "_reconstruct_array"),
    ("jax._src.tree_util", "default_registry"),
}

# PyTreeDef's home module drifts across jaxlib versions
# (jaxlib.xla_extension.pytree -> jaxlib._jax.pytree -> ...).  Known
# historical homes are allowed so checkpoints written under one jaxlib
# still load under another that keeps the old module as an alias; the
# CURRENT home is probed from the live class the first time it is
# needed, so the allowlist tracks whatever this environment's jaxlib
# calls it without a per-version table.  Only the exact (module,
# "PyTreeDef") pair is allowed — never a jaxlib module root.
_PYTREEDEF_KNOWN = {
    "jaxlib._jax.pytree",
    "jaxlib.xla_extension.pytree",
}
_pytreedef_live: tuple[str, str] | None = None


def _pytreedef_entry() -> tuple[str, str]:
    """(module, qualname) of THIS environment's PyTreeDef, cached."""
    global _pytreedef_live
    if _pytreedef_live is None:
        try:
            import jax

            cls = type(jax.tree_util.tree_structure(0))
            _pytreedef_live = (cls.__module__, cls.__qualname__)
        except Exception:  # jax unavailable: fall back to the known set
            _pytreedef_live = ("jaxlib._jax.pytree", "PyTreeDef")
    return _pytreedef_live

_JNP_DTYPES = frozenset({
    "bfloat16", "float16", "float32", "float64",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_", "complex64",
})


def _allowed(module: str, name: str) -> bool:
    if module.split(".", 1)[0] == "analytics_zoo_tpu":
        return True
    if (module, name) in _ALLOWED_EXACT:
        return True
    if name == "PyTreeDef" and (
            module in _PYTREEDEF_KNOWN or
            (module, name) == _pytreedef_entry()):
        return True
    if module == "jax.numpy" and name in _JNP_DTYPES:
        return True
    # optax optimizer-state namedtuples (ScaleByAdamState, TraceState,
    # EmptyState, ScaleByScheduleState, ...): constructing a namedtuple
    # executes no user code
    if module.startswith("optax.") and name.endswith("State"):
        return True
    return False


class CheckedUnpickler(pickle.Unpickler):
    """pickle.Unpickler with a class whitelist (reference
    CheckedObjectInputStream semantics)."""

    def find_class(self, module, name):
        if _allowed(module, name):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"refusing to deserialize {module}.{name}: not in the "
            f"analytics_zoo_tpu allowlist (untrusted model/checkpoint "
            f"file?)"
        )


def safe_load(file):
    return CheckedUnpickler(file).load()


def safe_loads(data: bytes):
    return CheckedUnpickler(io.BytesIO(data)).load()
