"""Engine & context runtime — the TPU-native equivalent of the reference's
``NNContext`` layer (reference zoo/.../common/NNContext.scala:133-149 creates a
SparkContext + BigDL ``Engine.init``; pyzoo/zoo/common/nncontext.py:104-124 is
the Python twin).

Instead of a SparkContext over a cluster, the runtime here owns a
``jax.sharding.Mesh`` over the TPU slice.  Mesh axes are first-class: ``data``
(DP — the reference's only strategy), plus ``model`` (TP), ``seq`` (SP/CP) and
``expert`` (EP) axes the reference never had (SURVEY.md §2.4).  Everything that
trains or predicts asks this module for the current mesh; tests force an
8-device CPU mesh via ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
(the analogue of the reference's local[4] Spark testing trick, SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import threading
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("analytics_zoo_tpu")

if not hasattr(jax, "shard_map"):  # pragma: no branch
    # Compat: this image ships jax 0.4.x, where shard_map lives in
    # jax.experimental with `check_rep` instead of the later `check_vma`
    # keyword.  The framework is written against the public jax.shard_map
    # surface; adapt here ONCE (engine is imported before any parallel
    # module) instead of forking every call site.
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(f, mesh, in_specs, out_specs, check_vma=True,
                          **kwargs):
        kwargs.setdefault("check_rep", check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)

    # marker for call sites that must fail LOUDLY where the 0.4.x
    # semantics are known not to match (parallel/pipeline.py hetero+DP)
    _compat_shard_map._zoo_compat_04x = True
    jax.shard_map = _compat_shard_map

try:
    # Same 0.4.x-era rename: pallas-TPU CompilerParams was
    # TPUCompilerParams (same dataclass fields).
    from jax.experimental.pallas import tpu as _pltpu

    if not hasattr(_pltpu, "CompilerParams") \
            and hasattr(_pltpu, "TPUCompilerParams"):
        _pltpu.CompilerParams = _pltpu.TPUCompilerParams
except Exception:  # pragma: no cover - pallas absent on some builds
    pass

# Canonical mesh-axis names, ordered outermost-first.  DCN-crossing axes
# (multi-slice data parallelism) must come first so that XLA lays collectives
# on ICI for the inner axes.
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"
ALL_AXES = (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS, PIPE_AXIS)


def _parse_bytes(raw, name: str) -> int:
    """Byte-count knob parser: plain int, or a K/M/G (binary) suffix —
    ``"512M"`` reads as 512 MiB.  Errors name the knob."""
    s = str(raw).strip()
    mult = 1
    if s and s[-1].upper() in "KMG":
        mult = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30}[s[-1].upper()]
        s = s[:-1]
    try:
        val = int(float(s) * mult)
    except (ValueError, OverflowError):  # 'inf' overflows int(), not
        #                                  float() — same bad-knob error
        raise ValueError(
            f"{name} must be a byte count (integer, optionally with a "
            f"K/M/G suffix), got {raw!r}") from None
    if val < 1:
        raise ValueError(f"{name} must be >= 1 byte, got {raw!r}")
    return val


@dataclasses.dataclass
class ZooConfig:
    """Typed engine configuration — the reference's three-tier conf system
    (packaged conf file merged into SparkConf + JVM sysprops + env vars,
    NNContext.scala:188-237) collapsed into one dataclass with a documented
    env tier.

    Precedence: explicit ``init_zoo_context`` arguments / conf dict >
    environment variables > dataclass defaults.

    Environment tier (the reference's sysprop/env knobs):
      ZOO_COMPUTE_DTYPE        "bf16" | "f32" | "f16" (platform default:
                               bf16 on TPU, f32 elsewhere)
      ZOO_FAILURE_RETRY_TIMES  retry-from-checkpoint budget (reference
                               ``bigdl.failure.retryTimes``, default 5)
      ZOO_PROFILE_DIR          when set, the Estimator captures ONE
                               jax.profiler trace of ``profile_steps``
                               train steps per fit() into this directory
      ZOO_PROFILE_STEPS        steps per captured trace (default 5)
      ZOO_INFEED_DEPTH         host->device feeder queue depth (default 2)
      ZOO_PREFETCH_WORKERS     > 0: the estimator fit loop wraps the train
                               set in the parallel host data plane
                               (FeatureSet.prefetch — feature/prefetch.py)
                               with this many pool workers; 0 (default)
                               keeps the serial path.  Delivery is ordered,
                               so the batch stream is byte-identical
                               either way.
      ZOO_PREFETCH_DEPTH       bounded prefetch queue depth when the
                               data plane is on (default 4)
      ZOO_STEPS_PER_DISPATCH   K > 1: the estimator fuses K train steps
                               into ONE jitted dispatch (jax.lax.scan
                               over a K-stacked super-batch) — amortizes
                               the Python→device round-trip when the
                               harness is dispatch-bound.  Loss
                               trajectory is bit-identical to K=1;
                               checkpoints/validation/TB move to K-step
                               boundaries (docs/performance.md).
                               Default 1 (off).
      ZOO_COMPILE_CACHE        persistent XLA compilation cache dir
                               (common/compile_cache.py): a second
                               process start / warmup() of the same
                               program skips XLA — cold-vs-warm shows in
                               zoo_compile_* metrics
      ZOO_SHARD_OPTIMIZER      "1": ZeRO-1 — shard optimizer state over
                               the data axis (1/n memory + update compute
                               per chip; params stay replicated).  Legacy
                               spelling of ZOO_SHARDING_PLAN=zero1.
      ZOO_SHARDING_PLAN        named sharding plan for training
                               (parallel/plan.py; docs/parallelism.md):
                               "dp" (replicate — default), "zero1"
                               (optimizer state sharded over data),
                               "zero2" (zero1 + gradients
                               reduce-scattered to per-chip shards),
                               "zero3"/"fsdp" (params + optimizer
                               state sharded over data; gather-on-use
                               / reduce-scatter — ~1/n param+opt bytes
                               per chip at a bit-identical loss
                               trajectory; zero3 also shards the
                               gradient tree in-graph).  Any of
                               zero1/zero2/zero3/fsdp also accepts a
                               "+overlap" suffix (e.g.
                               "zero2+overlap"): gradient collectives
                               are bucketed behind backward compute
                               and fsdp gathers double-buffered —
                               same bitwise trajectory, less exposed
                               collective time (docs/performance.md
                               "Latency hiding").  fit(
                               plan="auto") asks the oracle to sweep
                               these × remat policies against the HBM
                               budget.  Tensor-parallel and pipeline
                               plans carry a rule table, so they are
                               passed as objects (fit(plan=
                               tensor_parallel(rules))), not named
                               here.
      ZOO_DTYPE_POLICY         precision plane (parallel/plan.py
                               dtype_rules; docs/parallelism.md
                               "Precision plane"): "f32" (no-op
                               default), "bf16_mixed" (bf16 compute
                               params + f32 masters / f32 grad and
                               collective accumulation — the canned
                               mixed_precision() plan overlay),
                               "int8_serving" (weights marked for the
                               plan-aware weight-only int8 serving
                               path), "auto" (plan="auto" sweeps dtype
                               alongside sharding × remat against the
                               HBM budget), or an explicit
                               "pattern=role,..." rule string (roles
                               f32/bf16/f16/int8/keep).  Validated
                               EAGERLY at context init naming this
                               var.  A plan passed with its own
                               dtype_rules wins over this env tier.
      ZOO_USE_PALLAS           "1": kernel plane (parallel/plan.py
                               kernel_rules; docs/performance.md
                               "Kernel plane") — overlay the default
                               kernel table (attention=flash,
                               optimizer.adam=fused_adam,
                               loss.softmax_xent=fused_softmax_xent,
                               serving.int8_matmul=int8_matmul) on the
                               resolved plan, adding the "+kernels"
                               name suffix.  A plan passed with its own
                               kernel_rules wins over this env tier.
                               Unset: no ops/pallas kernel module is
                               even imported and the trajectory is
                               bit-identical (flash attention keeps its
                               pre-existing eligibility routing either
                               way).  Validated as a boolean eagerly.
      ZOO_DTYPE_RESUME         "cast": resuming a checkpoint whose
                               recorded dtype policy differs from the
                               current plan's casts deliberately
                               (with a warning) instead of failing
                               loudly
      ZOO_OVERLAP_BUCKET_BYTES target gradient-bucket size (bytes) for
                               "+overlap" plans — each bucket's
                               reduce-scatter/all-reduce is issued as
                               its backward segment completes
                               (parallel/plan.py
                               default_bucket_bytes; default 4 MiB).
                               Grouping is part of the plan cache key,
                               so changing it recompiles but never
                               changes the trajectory.
      ZOO_ASYNC_CHECKPOINT     "0" forces checkpoint saves back onto
                               the train thread (gather + serialize +
                               atomic rename inline).  Default on:
                               saves snapshot on-device, then gather/
                               serialize/rename on a daemon writer
                               thread — fit stalls only for the
                               snapshot (zoo_ckpt_stall_seconds vs
                               zoo_ckpt_write_seconds), a kill mid-
                               write leaves the previous complete
                               checkpoint loadable.
      ZOO_DCN_AXIS             mesh axis that crosses the data-center
                               network when parallel.plan.build_mesh
                               assembles a hybrid ICI x DCN mesh from a
                               bare slice count (default "data"; a name
                               not in the ICI axes, e.g. "dcn", is
                               prepended as a new outermost axis)
      ZOO_METRICS_PORT         serve /metrics /varz /trace /healthz
                               /flightz over HTTP from the serving loop /
                               estimator fit (metrics/http.py; bind
                               address via ZOO_METRICS_HOST)
      ZOO_FLIGHT_DIR           arm the crash flight recorder's dump
                               (metrics/flight.py; ZOO_FLIGHT=0 disables,
                               ZOO_FLIGHT_EVENTS caps the ring)
      ZOO_HLO_LINT             "0" disables the HLO graph lint + cost
                               extraction riding every timed_compile
                               (analysis/hlo.py; default on — zoo_hlo_*
                               metrics, flight hlo_lint events)
      ZOO_HLO_REPORT_DIR       when set, every compile additionally
                               writes a zoo-hlo-report/2 JSON file with
                               the analytic features + findings plus
                               compile wall-seconds, plan, mesh shape,
                               K and a dtype histogram — one row is a
                               self-contained cost-model training
                               example (docs/static-analysis.md)
      ZOO_ORACLE               "0" disables the predictive compile
                               plane (analysis/oracle.py; default on):
                               the autotuner's K search falls back to
                               the blind hill-climb (plan="auto" still
                               predicts — it is an explicit request)
      ZOO_ORACLE_PEAKS         JSON object overriding PeakTable fields
                               (flops, hbm_bytes_per_s,
                               link_bytes_per_s, dispatch_overhead_s,
                               hbm_bytes) over the per-platform
                               defaults — calibrate the roofline, or
                               pin the HBM budget plan="auto" fits
                               against (docs/performance.md)
      ZOO_TUNE_LOG_DIR         when set, the autotuner persists its
                               decision log there as JSONL (decision +
                               settle records; the settle rows carry
                               the measured per-K cost curve the
                               oracle's residual model trains on);
                               size-capped by ZOO_TUNE_LOG_MAX_BYTES
                               (default 4M) with one rotated
                               predecessor
      ZOO_SAN                  "1": install the runtime concurrency
                               sanitizer at package import — wraps the
                               package's locks (lockdep cycle detection
                               with both stacks), validates guarded-by
                               annotations on attribute writes, flags
                               blocking calls under a held lock
                               (analysis/sanitizer.py; zoo_san_* metrics
                               + san_finding flight events).  Unset:
                               nothing is patched, zero overhead.
      ZOO_SAN_STRICT           "1": the pytest session fails if
                               sanitizer findings are left un-drained
                               at session end (tests/conftest.py)
      ZOO_AUTOTUNE             "1": closed-loop autotuning
                               (feature/autotune.py) — a controller
                               thread resizes the prefetch worker pool,
                               queue depth and shard read-ahead online
                               from the zoo_data_prefetch_* telemetry
                               (consumer-wait p50 → 0 under the RAM
                               budget) and hill-climbs
                               steps_per_dispatch over {1,2,4,8,16}
                               from measured per-dispatch time.  Loss
                               trajectory stays bit-identical; every
                               decision lands in zoo_autotune_*
                               metrics, the flight ring, and /varz.
                               Unset: zero new threads, zero overhead.
      ZOO_AUTOTUNE_RAM_BUDGET  host-RAM budget (bytes; K/M/G suffixes
                               accepted, e.g. "512M") for the prefetch
                               window the autotuner may grow into
                               (default 2G)
      ZOO_AUTOTUNE_INTERVAL    controller tick seconds (default 0.25)
      ZOO_AUTOTUNE_MAX_WORKERS cap on the autotuned worker pool
                               (default min(8, 4 x cpu count) — prefetch
                               workers scale GIL-releasing IO/decode,
                               so cores only floor the cap)
      ZOO_SERVING_BATCH_BUDGET_MS
                               continuous-batching latency budget (ms)
                               for claim-mode (fleet) serving: a PARTIAL
                               shape bucket waits at most this long for
                               co-batchable arrivals before predict — a
                               lone request is served within the budget,
                               a trickle coalesces into one padded
                               predict.  0 flushes every claim batch
                               immediately.  Default 25.
      ZOO_SLO_P99_MS           the serving fleet's p99 latency SLO (ms,
                               default 500): the autoscaler scales up
                               when its estimated tail sojourn (predict
                               p99 + backlog/service-rate) sustainedly
                               exceeds this, down on sustained slack
                               (serving/scaler.py)
      ZOO_FLEET_MIN_REPLICAS   autoscaler floor (default 1)
      ZOO_FLEET_MAX_REPLICAS   autoscaler ceiling (default 4)
      ZOO_FLEET_INTERVAL       scaler window/tick seconds (default 1.0)
      ZOO_FLEET_LEASE_MS       work-claim lease (ms, default 10000): a
                               replica silent this long forfeits its
                               claimed-but-unserved records to the
                               surviving replicas (exactly-once via
                               lease expiry; serving/broker.py)
      ZOO_ELASTIC              enable the elastic training runtime
                               (default off): fit() joins the broker-
                               backed membership ledger and yields at
                               step barriers on generation changes
                               (elastic/; docs/elastic-training.md)
      ZOO_ELASTIC_LEASE_MS     membership lease (ms, default 3000): a
                               training worker whose keepalive is
                               silent this long is declared dead and
                               the generation counter increments —
                               shorter detects faults faster, longer
                               tolerates GC/compile pauses
      ZOO_ELASTIC_MIN_WORKERS  cohort floor (default 1): the supervisor
                               holds training (no chief assignment)
                               while fewer members are live
      ZOO_ELASTIC_GRACE_MS     shutdown grace (ms, default 5000): bound
                               on the SIGTERM-path flush of the async
                               checkpoint writer before the flight
                               dump, and on a worker's SIGTERM->SIGKILL
                               escalation
      ZOO_SCRAPE_TARGETS       static scrape list for the zoowatch
                               federation tier (metrics/scrape.py):
                               comma/space-separated host:port, URL, or
                               name=url entries; a VarzScraper built
                               without explicit targets adopts them
      ZOO_SCRAPE_INTERVAL      scrape cadence seconds (default 1.0,
                               floor 0.05)
      ZOO_SCRAPE_STALE_AFTER   a target silent this many seconds is
                               stale: its health verdict flips and the
                               aggregator labels its samples
                               ``stale="true"`` (default 10.0)
      ZOO_SLO_OBJECTIVE        default SLO objective for the burn-rate
                               engine (metrics/slo.py): fraction of
                               good events promised, in (0, 1)
                               (default 0.99)
      ZOO_SLO_SHORT_WINDOW     burn-rate fast window seconds (default
                               30): both windows must burn above the
                               threshold for an alert to fire
      ZOO_SLO_LONG_WINDOW      burn-rate slow window seconds (default
                               300); must exceed the short window
      ZOO_SLO_BURN_THRESHOLD   burn-rate multiple that fires an alert
                               (default 1.0 = burning budget exactly
                               at the objective's sustainable rate)

    ``ZOO_PREFETCH_WORKERS`` / ``ZOO_PREFETCH_DEPTH`` /
    ``ZOO_STEPS_PER_DISPATCH`` are validated EAGERLY here: a
    non-integer or out-of-range value fails at context init with an
    error naming the env var, never from deep inside the pipeline.
    """

    app_name: str = "analytics-zoo-tpu"
    seed: int = 0
    mesh_shape: Mapping[str, int] | None = None
    mesh_axes: Sequence[str] = (DATA_AXIS, MODEL_AXIS)
    platform: str | None = None
    compute_dtype: object = None
    # None = "not explicitly set": resolved env > default in __post_init__,
    # so an explicit value always beats the environment (the documented
    # precedence) even when it equals the default.
    failure_retry_times: int | None = None
    profile_dir: str | None = None
    profile_steps: int | None = None
    infeed_depth: int | None = None
    # Parallel host data plane (feature/prefetch.py): workers > 0 makes
    # the estimator prefetch the train set; env ZOO_PREFETCH_WORKERS /
    # ZOO_PREFETCH_DEPTH.
    prefetch_workers: int | None = None
    prefetch_depth: int | None = None
    # Fused multi-step dispatch: K > 1 runs K train steps inside one
    # jitted lax.scan per host round-trip (bit-identical trajectory;
    # K-boundary callbacks).  Env: ZOO_STEPS_PER_DISPATCH.
    steps_per_dispatch: int | None = None
    # Persistent XLA compile cache dir (common/compile_cache.py).
    # Env: ZOO_COMPILE_CACHE.
    compile_cache: str | None = None
    # ZeRO-1: shard optimizer state (Adam moments) over the data axis via
    # GSPMD sharding constraints — 1/n optimizer memory and update compute
    # per chip; parameters stay replicated.  Env: ZOO_SHARD_OPTIMIZER=1.
    # (Legacy spelling of sharding_plan="zero1".)
    shard_optimizer: bool | None = None
    # Unified partitioner (parallel/plan.py): named sharding plan for
    # every fit ("dp" | "zero1" | "zero2" | "zero3" | "fsdp"); None = dp
    # (or zero1 when the legacy shard_optimizer flag is set).
    # Env: ZOO_SHARDING_PLAN.
    sharding_plan: str | None = None
    # Precision plane (parallel/plan.py dtype_rules): named dtype policy
    # ("f32" | "bf16_mixed" | "int8_serving" | "auto") or an explicit
    # "pattern=role,..." rule string overlaid on the resolved plan.
    # Env: ZOO_DTYPE_POLICY.
    dtype_policy: str | None = None
    # Kernel plane (parallel/plan.py kernel_rules): overlay the default
    # pallas kernel table on the resolved plan.  Env: ZOO_USE_PALLAS=1.
    use_pallas: bool | None = None
    # Hybrid ICI x DCN meshes (plan.build_mesh): which axis crosses the
    # DCN when given a bare slice count.  Env: ZOO_DCN_AXIS.
    dcn_axis: str | None = None
    # Closed-loop autotuning (feature/autotune.py): resize the prefetch
    # plane online and hill-climb steps_per_dispatch from telemetry.
    # Env: ZOO_AUTOTUNE=1 plus the budget knobs below.
    autotune: bool | None = None
    autotune_ram_budget: int | None = None
    autotune_interval: float | None = None
    autotune_max_workers: int | None = None
    # Serving fleet (serving/fleet.py): continuous-batching budget, p99
    # SLO target, and autoscaler bounds.  Env: ZOO_SERVING_BATCH_BUDGET_MS,
    # ZOO_SLO_P99_MS, ZOO_FLEET_MIN/MAX_REPLICAS, ZOO_FLEET_INTERVAL,
    # ZOO_FLEET_LEASE_MS.
    serving_batch_budget_ms: float | None = None
    slo_p99_ms: float | None = None
    fleet_min_replicas: int | None = None
    fleet_max_replicas: int | None = None
    fleet_interval: float | None = None
    fleet_lease_ms: int | None = None
    # Predictive serving plane (serving/router.py, serving/admission.py):
    # front-door admission control and the multi-tenant model roster
    # ("name=slo_p99_ms[@offered_rate],..." — one oracle-primed fleet
    # per entry).  Env: ZOO_ADMISSION=1, ZOO_SERVING_MODELS.
    admission: bool | None = None
    serving_models: str | None = None
    # Elastic training runtime (elastic/): membership lease, cohort
    # floor, and shutdown grace.  Env: ZOO_ELASTIC,
    # ZOO_ELASTIC_LEASE_MS, ZOO_ELASTIC_MIN_WORKERS,
    # ZOO_ELASTIC_GRACE_MS.
    elastic: bool | None = None
    elastic_lease_ms: int | None = None
    elastic_min_workers: int | None = None
    elastic_grace_ms: int | None = None
    # Zoowatch federation tier (metrics/scrape.py, metrics/slo.py):
    # static scrape targets, cadence, staleness threshold, and the
    # burn-rate engine's default objective/windows.  Env:
    # ZOO_SCRAPE_TARGETS, ZOO_SCRAPE_INTERVAL, ZOO_SCRAPE_STALE_AFTER,
    # ZOO_SLO_OBJECTIVE, ZOO_SLO_SHORT/LONG_WINDOW,
    # ZOO_SLO_BURN_THRESHOLD.
    scrape_targets: str | None = None
    scrape_interval: float | None = None
    scrape_stale_after: float | None = None
    slo_objective: float | None = None
    slo_short_window: float | None = None
    slo_long_window: float | None = None
    slo_burn_threshold: float | None = None

    def __post_init__(self):
        env = os.environ

        def resolve(value, env_key, default, cast=int):
            if value is not None:
                return value
            if env_key in env:
                return cast(env[env_key])
            return default

        def resolve_int(value, env_key, default, minimum):
            """Eager-validated integer knob: a bad value fails HERE with
            an error naming its source (env var or field), not from
            deep inside the pipeline/estimator it configures."""
            if value is not None:
                src, raw = "ZooConfig " + env_key[4:].lower(), value
            elif env_key in env:
                src, raw = env_key, env[env_key]
            else:
                return default
            try:
                out = int(str(raw))
            except (TypeError, ValueError):
                raise ValueError(
                    f"{src} must be an integer >= {minimum}, "
                    f"got {raw!r}") from None
            if out < minimum:
                raise ValueError(
                    f"{src} must be >= {minimum}, got {out}")
            return out

        self.failure_retry_times = resolve(
            self.failure_retry_times, "ZOO_FAILURE_RETRY_TIMES", 5)
        self.profile_steps = resolve(
            self.profile_steps, "ZOO_PROFILE_STEPS", 5)
        self.infeed_depth = resolve(
            self.infeed_depth, "ZOO_INFEED_DEPTH", 2)
        # 0 = prefetch off (the documented default); depth/K floor at 1
        self.prefetch_workers = resolve_int(
            self.prefetch_workers, "ZOO_PREFETCH_WORKERS", 0, minimum=0)
        self.prefetch_depth = resolve_int(
            self.prefetch_depth, "ZOO_PREFETCH_DEPTH", 4, minimum=1)
        self.steps_per_dispatch = resolve_int(
            self.steps_per_dispatch, "ZOO_STEPS_PER_DISPATCH", 1,
            minimum=1)
        self.shard_optimizer = bool(resolve(
            self.shard_optimizer, "ZOO_SHARD_OPTIMIZER", False))
        self.sharding_plan = resolve(
            self.sharding_plan, "ZOO_SHARDING_PLAN", None, cast=str)
        if self.sharding_plan is not None:
            # eager validation (the resolve_int contract): a typo'd plan
            # name fails at context init naming the knob, not from the
            # first fit()
            from analytics_zoo_tpu.parallel.plan import (
                DTYPE_ROLES,
                PLAN_NAMES,
            )

            valid = tuple(PLAN_NAMES) + ("auto",)
            name = str(self.sharding_plan).strip().lower()
            # kernel plane: "+kernels" is appended last by with_kernels,
            # so it strips first — mirroring resolve_plan's parse order
            if name.endswith("+kernels"):
                name = name[:-len("+kernels")]
            # precision plane: any plan also accepts a trailing dtype-
            # role suffix ("zero1+overlap+bf16") — strip it before the
            # name check, mirroring resolve_plan's parse order
            for role in DTYPE_ROLES:
                if name.endswith("+" + role):
                    name = name[:-len("+" + role)]
                    break
            base = name[:-len("+overlap")] \
                if name.endswith("+overlap") else name
            overlappable = ("zero1", "zero2", "zero3", "fsdp")
            ok = name in valid or (name.endswith("+overlap")
                                   and base in overlappable)
            if not ok:
                raise ValueError(
                    f"ZOO_SHARDING_PLAN must be one of "
                    f"{', '.join(valid)} (zero1/zero2/zero3/fsdp also "
                    f"accept a '+overlap' suffix, and any plan a "
                    f"trailing dtype-role suffix like '+bf16'); "
                    f"got {self.sharding_plan!r}")
        self.dtype_policy = resolve(
            self.dtype_policy, "ZOO_DTYPE_POLICY", None, cast=str)
        if self.dtype_policy is not None:
            # eager validation (the resolve_int contract): a typo'd
            # policy fails at context init naming the knob, not from
            # the first fit()'s plan resolution
            from analytics_zoo_tpu.parallel.plan import resolve_dtype_rules

            policy = str(self.dtype_policy).strip().lower()
            if policy != "auto":
                try:
                    resolve_dtype_rules(self.dtype_policy)
                except ValueError as e:
                    raise ValueError(
                        f"ZOO_DTYPE_POLICY: {e}") from None
        self.dcn_axis = resolve(
            self.dcn_axis, "ZOO_DCN_AXIS", None, cast=str)
        if self.dcn_axis is not None and not str(self.dcn_axis).strip():
            raise ValueError("ZOO_DCN_AXIS must be a mesh axis name")
        def bool_parser(var):
            def parse(raw):
                s = str(raw).strip().lower()
                if s in ("1", "true", "yes", "on"):
                    return True
                if s in ("", "0", "false", "no", "off"):
                    return False
                # 'false'-alikes must never silently ENABLE a feature;
                # anything unrecognized fails loudly naming the var
                raise ValueError(
                    f"{var} must be a boolean "
                    f"(1/0/true/false/yes/no/on/off), got {raw!r}")
            return parse

        parse_bool = bool_parser("ZOO_AUTOTUNE")
        self.use_pallas = bool(resolve(
            self.use_pallas, "ZOO_USE_PALLAS", False,
            cast=bool_parser("ZOO_USE_PALLAS")))
        self.autotune = bool(resolve(
            self.autotune, "ZOO_AUTOTUNE", False, cast=parse_bool))
        if self.autotune_ram_budget is None:
            raw = env.get("ZOO_AUTOTUNE_RAM_BUDGET")
            if raw:
                self.autotune_ram_budget = _parse_bytes(
                    raw, "ZOO_AUTOTUNE_RAM_BUDGET")
        elif self.autotune_ram_budget < 1:
            raise ValueError(
                f"ZooConfig autotune_ram_budget must be >= 1 byte, "
                f"got {self.autotune_ram_budget}")
        self.autotune_interval = resolve(
            self.autotune_interval, "ZOO_AUTOTUNE_INTERVAL", 0.25,
            cast=float)
        if self.autotune_interval <= 0:
            raise ValueError(
                f"ZOO_AUTOTUNE_INTERVAL must be > 0, "
                f"got {self.autotune_interval}")
        self.autotune_max_workers = resolve_int(
            self.autotune_max_workers, "ZOO_AUTOTUNE_MAX_WORKERS", None,
            minimum=1)

        def resolve_float(value, env_key, default, minimum):
            """Eager-validated float knob — same contract as
            resolve_int: fails here naming the env var or field."""
            if value is not None:
                src, raw = "ZooConfig " + env_key[4:].lower(), value
            elif env_key in env:
                src, raw = env_key, env[env_key]
            else:
                return default
            try:
                out = float(str(raw))
            except (TypeError, ValueError):
                raise ValueError(
                    f"{src} must be a number >= {minimum}, "
                    f"got {raw!r}") from None
            if out < minimum:
                raise ValueError(
                    f"{src} must be >= {minimum}, got {out}")
            return out

        # Serving-fleet tier: budgets/SLO validated eagerly so a bad
        # knob fails at context init, not from inside a serving replica
        self.serving_batch_budget_ms = resolve_float(
            self.serving_batch_budget_ms, "ZOO_SERVING_BATCH_BUDGET_MS",
            25.0, minimum=0.0)
        self.slo_p99_ms = resolve_float(
            self.slo_p99_ms, "ZOO_SLO_P99_MS", 500.0, minimum=1.0)
        self.fleet_min_replicas = resolve_int(
            self.fleet_min_replicas, "ZOO_FLEET_MIN_REPLICAS", 1,
            minimum=1)
        self.fleet_max_replicas = resolve_int(
            self.fleet_max_replicas, "ZOO_FLEET_MAX_REPLICAS", 4,
            minimum=1)
        if self.fleet_max_replicas < self.fleet_min_replicas:
            raise ValueError(
                f"ZOO_FLEET_MAX_REPLICAS ({self.fleet_max_replicas}) must "
                f"be >= ZOO_FLEET_MIN_REPLICAS "
                f"({self.fleet_min_replicas})")
        self.fleet_interval = resolve_float(
            self.fleet_interval, "ZOO_FLEET_INTERVAL", 1.0, minimum=0.01)
        self.fleet_lease_ms = resolve_int(
            self.fleet_lease_ms, "ZOO_FLEET_LEASE_MS", 10_000,
            minimum=100)
        self.admission = bool(resolve(
            self.admission, "ZOO_ADMISSION", False,
            cast=bool_parser("ZOO_ADMISSION")))
        self.serving_models = resolve(
            self.serving_models, "ZOO_SERVING_MODELS", None, cast=str)
        if self.serving_models is not None:
            # eager validation (the resolve_int contract): a malformed
            # model roster fails at context init naming the env var,
            # not from the router's first tenant build.  Lazy import —
            # serving.modelspec is pure stdlib, but keep engine's
            # import graph serving-free (the parallel.plan precedent).
            from analytics_zoo_tpu.serving.modelspec import (
                parse_model_specs,
            )

            parse_model_specs(self.serving_models,
                              source="ZOO_SERVING_MODELS")

        # Elastic-training tier (elastic/): validated eagerly so a bad
        # knob fails at context init, never from inside a training
        # worker mid-rejoin (the PR 7/8 contract).
        def parse_elastic_bool(raw):
            s = str(raw).strip().lower()
            if s in ("1", "true", "yes", "on"):
                return True
            if s in ("", "0", "false", "no", "off"):
                return False
            raise ValueError(
                f"ZOO_ELASTIC must be a boolean "
                f"(1/0/true/false/yes/no/on/off), got {raw!r}")

        self.elastic = bool(resolve(
            self.elastic, "ZOO_ELASTIC", False, cast=parse_elastic_bool))
        self.elastic_lease_ms = resolve_int(
            self.elastic_lease_ms, "ZOO_ELASTIC_LEASE_MS", 3_000,
            minimum=100)
        self.elastic_min_workers = resolve_int(
            self.elastic_min_workers, "ZOO_ELASTIC_MIN_WORKERS", 1,
            minimum=1)
        self.elastic_grace_ms = resolve_int(
            self.elastic_grace_ms, "ZOO_ELASTIC_GRACE_MS", 5_000,
            minimum=0)

        # Zoowatch federation tier (metrics/scrape.py, metrics/slo.py):
        # same eager-validation contract — a typo'd objective fails at
        # context init, never from the first burn-rate evaluation.
        self.scrape_targets = resolve(
            self.scrape_targets, "ZOO_SCRAPE_TARGETS", None, cast=str)
        self.scrape_interval = resolve_float(
            self.scrape_interval, "ZOO_SCRAPE_INTERVAL", 1.0,
            minimum=0.05)
        self.scrape_stale_after = resolve_float(
            self.scrape_stale_after, "ZOO_SCRAPE_STALE_AFTER", 10.0,
            minimum=0.05)
        self.slo_objective = resolve_float(
            self.slo_objective, "ZOO_SLO_OBJECTIVE", 0.99, minimum=0.0)
        if not 0.0 < self.slo_objective < 1.0:
            raise ValueError(
                f"ZOO_SLO_OBJECTIVE must be in (0, 1) — the fraction "
                f"of good events promised — got {self.slo_objective}")
        self.slo_short_window = resolve_float(
            self.slo_short_window, "ZOO_SLO_SHORT_WINDOW", 30.0,
            minimum=0.1)
        self.slo_long_window = resolve_float(
            self.slo_long_window, "ZOO_SLO_LONG_WINDOW", 300.0,
            minimum=0.1)
        if self.slo_long_window <= self.slo_short_window:
            raise ValueError(
                f"ZOO_SLO_LONG_WINDOW ({self.slo_long_window}) must be "
                f"> ZOO_SLO_SHORT_WINDOW ({self.slo_short_window}) — "
                f"multi-window burn-rate alerting needs a slow window "
                f"to confirm the fast one")
        self.slo_burn_threshold = resolve_float(
            self.slo_burn_threshold, "ZOO_SLO_BURN_THRESHOLD", 1.0,
            minimum=0.0)
        if self.slo_burn_threshold <= 0:
            raise ValueError(
                f"ZOO_SLO_BURN_THRESHOLD must be > 0, "
                f"got {self.slo_burn_threshold}")
        if self.profile_dir is None:
            self.profile_dir = env.get("ZOO_PROFILE_DIR") or None
        if self.compile_cache is None:
            self.compile_cache = env.get("ZOO_COMPILE_CACHE") or None


@dataclasses.dataclass
class ZooContext:
    """Runtime context: the device mesh plus engine-level knobs.

    The reference's ``NNContext.initNNContext`` returns a SparkContext after
    tuning executor env (KMP_AFFINITY / OMP_NUM_THREADS,
    NNContext.scala:209-237).  The TPU equivalent owns the mesh and global
    numerics policy instead.
    """

    mesh: Mesh
    platform: str
    seed: int = 0
    # Forward/backward math dtype (params-in-compute); None = full f32.
    # Master params, optimizer state and loss stay f32 — the standard TPU
    # mixed-precision recipe that keeps the MXU at bf16 rate.
    compute_dtype: object = None
    config: "ZooConfig" = dataclasses.field(default_factory=lambda: ZooConfig())
    _step_rng: jax.Array | None = None

    @property
    def num_devices(self) -> int:
        return self.mesh.size

    @property
    def data_parallel_size(self) -> int:
        return self.mesh.shape.get(DATA_AXIS, 1)

    def axis_size(self, axis: str) -> int:
        return self.mesh.shape.get(axis, 1)

    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding on this context's mesh for a PartitionSpec."""
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, ndim: int,
                       axes: Sequence[str] = (DATA_AXIS,)) -> NamedSharding:
        """Shard the leading (batch) dim over ``axes`` (default the data
        axis — a hybrid-mesh plan may pass ``("dcn", "data")``),
        replicate the rest.  Scalars (ndim 0) are replicated."""
        if ndim == 0:
            return self.replicated()
        lead = axes[0] if len(axes) == 1 else tuple(axes)
        return NamedSharding(self.mesh, P(lead, *([None] * (ndim - 1))))

    def shard_batch(self, tree, axes: Sequence[str] = (DATA_AXIS,)):
        """Device-put a host batch pytree sharded over the data axis.

        This is the per-chip host infeed replacing the reference's
        RDD-partition → task iterator feed (FeatureSet.scala:240-289).

        Single-process: a plain sharded ``device_put`` of the global batch.
        Multi-process (``jax.distributed``): each host holds only ITS slice
        of the global batch (``process_local_batch_slice``) and the global
        array is assembled with ``jax.make_array_from_process_local_data`` —
        the per-partition locality the reference gets from RDD partitioning
        (FeatureSet.scala:240-289); host 0's data never crosses hosts.
        """
        # batch_sharding(0) is replicated, so scalars (n_valid, seeds —
        # same value on every process) and batch arrays go through the
        # same call.
        return self._put_tree(
            tree, lambda ndim: self.batch_sharding(ndim, axes))

    def shard_batch_stacked(self, tree,
                            axes: Sequence[str] = (DATA_AXIS,)):
        """Device-put a K-STACKED super-batch (leading axis = inner step
        index, axis 1 = batch) for the fused multi-step dispatch
        (``ZOO_STEPS_PER_DISPATCH``, Estimator scan-K path).

        Axis 1 is sharded over the data axis — each chip holds the SAME
        rows of every inner batch it would hold under K=1, so the fused
        ``lax.scan`` sees per-step shards identical to K single
        dispatches.  Rank-<2 leaves (stacked per-step scalars like
        ``n_valid`` → shape [K]) are replicated.
        """
        def sharding_of(ndim: int) -> NamedSharding:
            if ndim < 2:
                return self.replicated()
            lead = axes[0] if len(axes) == 1 else tuple(axes)
            return NamedSharding(
                self.mesh, P(None, lead, *([None] * (ndim - 2))))

        return self._put_tree(tree, sharding_of)

    def _put_tree(self, tree, sharding_of):
        """Shared device-put scaffolding for the batch shard paths:
        single-process does a sharded ``device_put`` per leaf;
        multi-process assembles the global array from this host's rows
        via ``jax.make_array_from_process_local_data``.  ``sharding_of``
        maps leaf ndim -> NamedSharding."""
        if jax.process_count() > 1:
            def put(x):
                x = np.asarray(x)
                return jax.make_array_from_process_local_data(
                    sharding_of(np.ndim(x)), x)
            return jax.tree_util.tree_map(put, tree)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                np.asarray(x), sharding_of(np.ndim(x))),
            tree,
        )

    def next_rng(self) -> jax.Array:
        if self._step_rng is None:
            self._step_rng = jax.random.PRNGKey(self.seed)
        self._step_rng, out = jax.random.split(self._step_rng)
        return out


def cast_floats(tree, dtype):
    """Cast floating-point leaves of a pytree to ``dtype`` (None = no-op).

    The mixed-precision primitive: integer leaves (labels, token ids) pass
    through untouched.
    """
    if dtype is None:
        return tree
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(jnp.result_type(a), jnp.floating) else a,
        tree,
    )


def _resolve_compute_dtype(spec, platform: str):
    """Resolve the compute dtype policy.

    Precedence: explicit arg/conf > ZOO_COMPUTE_DTYPE env > platform default
    (bfloat16 on TPU — the MXU's native rate; f32 elsewhere so CPU-mesh tests
    stay bit-accurate vs oracles).
    """
    if spec is None:
        spec = os.environ.get("ZOO_COMPUTE_DTYPE")
    if spec is None:
        return jnp.bfloat16 if platform == "tpu" else None
    if spec in (jnp.bfloat16, jnp.float16, jnp.float32):
        return None if spec == jnp.float32 else spec
    s = str(spec).lower()
    if s in ("float32", "f32", "fp32", "none", ""):
        return None
    if s in ("bfloat16", "bf16"):
        return jnp.bfloat16
    if s in ("float16", "f16", "fp16"):
        return jnp.float16
    raise ValueError(f"unknown compute_dtype {spec!r}")


_LOCK = threading.Lock()
_CONTEXT: ZooContext | None = None  # guarded-by: _LOCK


def _infer_mesh_shape(
    devices: Sequence, axes: Sequence[str], shape: Mapping[str, int] | None
) -> dict[str, int]:
    n = len(devices)
    if shape is None:
        # Default: pure data parallelism — the reference's only inter-node
        # strategy (SURVEY.md §2.4) and the right default for dense training.
        return {a: (n if a == DATA_AXIS else 1) for a in axes}
    out = dict(shape)
    unknown = [a for a in axes if a not in out]
    given = math.prod(out.values())
    if n % given != 0:
        raise ValueError(
            f"mesh shape {out} does not divide device count {n}"
        )
    rest = n // given
    for a in unknown:
        out[a] = 1
    # Fold leftover devices into the data axis.
    if rest != 1:
        out[DATA_AXIS] = out.get(DATA_AXIS, 1) * rest
    return {a: out[a] for a in axes}


def init_zoo_context(
    conf: Mapping[str, object] | str | None = None,
    *,
    mesh_shape: Mapping[str, int] | None = None,
    mesh_axes: Sequence[str] | None = None,
    seed: int | None = None,
    platform: str | None = None,
    compute_dtype=None,
    dcn_shape: Mapping[str, int] | None = None,
    slice_groups=None,
    allow_idle: bool = False,
) -> ZooContext:
    """Initialise (or re-initialise) the global runtime context.

    Mirrors ``init_nncontext`` (reference pyzoo/zoo/common/nncontext.py:104):
    the reference builds a SparkContext with a tuned conf; here we discover
    devices, build a Mesh, and fix numerics policy.

    Args:
      conf: optional dict (or app-name string, accepted for API fidelity with
        ``init_nncontext("app name")``) of engine options: ``seed``,
        ``mesh_shape``, ``platform``.
      mesh_shape: e.g. ``{"data": 8}`` or ``{"data": 4, "model": 2}``; missing
        axes get size 1 and leftover devices fold into ``data``.
      mesh_axes: axis names, outermost first.
      platform: force a jax platform ("cpu", "tpu"); tests use cpu meshes.
      dcn_shape: multi-slice extents, e.g. ``{"data": 2}`` for
        data-parallelism across 2 TPU slices — the mesh is then built by
        :func:`analytics_zoo_tpu.parallel.hybrid_mesh` with ``mesh_shape``
        as the per-slice (ICI) extents, and every ``fit``/``predict``
        through this context trains multi-slice.
      slice_groups: explicit per-slice device groups for ``dcn_shape``
        (CI emulation / exotic topologies; default: ``device.slice_index``).
      allow_idle: let the hybrid mesh leave surplus per-slice devices idle
        (otherwise a per-slice shape smaller than the slice is an error).
    """
    global _CONTEXT
    if isinstance(conf, ZooConfig):
        cfg = dataclasses.replace(conf)  # never mutate the caller's config
    else:
        if isinstance(conf, str):
            conf = {"app_name": conf}
        conf = dict(conf or {})
        known = {f.name for f in dataclasses.fields(ZooConfig)}
        cfg = ZooConfig(**{k: v for k, v in conf.items() if k in known})
        unknown = set(conf) - known
        if unknown:
            raise ValueError(
                f"unknown conf keys {sorted(unknown)}; "
                f"valid: {sorted(known)}")
    # Keyword args use None as the "not given" sentinel, so an explicitly
    # passed kwarg ALWAYS wins over the conf/config value (no ambiguity
    # when the explicit value happens to equal a default).
    if seed is not None:
        cfg.seed = int(seed)
    if mesh_shape is not None:
        cfg.mesh_shape = mesh_shape
    if mesh_axes is not None:
        cfg.mesh_axes = tuple(mesh_axes)
    if platform is not None:
        cfg.platform = platform
    if compute_dtype is not None:
        cfg.compute_dtype = compute_dtype

    devices = jax.devices(cfg.platform) if cfg.platform else jax.devices()
    axes = tuple(cfg.mesh_axes)
    if slice_groups is not None and not dcn_shape:
        raise ValueError("slice_groups requires dcn_shape")
    if dcn_shape:
        # multi-slice: DCN-crossing axis outermost, per-slice ICI extents
        # from mesh_shape (see parallel.multihost.hybrid_mesh).  The FULL
        # axes tuple is kept — unlisted axes get size 1 exactly like the
        # plain path, so PartitionSpecs naming them keep working.
        from analytics_zoo_tpu.parallel.multihost import hybrid_mesh

        ici = dict(cfg.mesh_shape or {})
        if not ici:
            raise ValueError("dcn_shape requires an explicit mesh_shape "
                             "(the per-slice ICI extents)")
        mesh = hybrid_mesh(ici, dict(dcn_shape), axes=axes,
                           devices=devices, slice_groups=slice_groups,
                           allow_idle=allow_idle)
        devices = list(mesh.devices.ravel())
    else:
        shape = _infer_mesh_shape(devices, axes, cfg.mesh_shape)
        n_used = math.prod(shape.values())
        dev_array = np.asarray(devices[:n_used]).reshape(
            [shape[a] for a in axes])
        mesh = Mesh(dev_array, axes)
    ctx = ZooContext(
        mesh=mesh, platform=devices[0].platform, seed=cfg.seed,
        compute_dtype=_resolve_compute_dtype(
            cfg.compute_dtype, devices[0].platform),
        config=cfg,
    )
    with _LOCK:
        _CONTEXT = ctx
    logger.info(
        "init_zoo_context: %d %s device(s), mesh %s",
        len(devices), ctx.platform, dict(mesh.shape),
    )
    return ctx


def get_zoo_context() -> ZooContext:
    """Current context, creating a default (all-devices DP mesh) on demand.

    Matches the reference's lazy ``getOrCreateSparkContext``
    (pyzoo/zoo/common/nncontext.py:127-135).
    """
    global _CONTEXT
    with _LOCK:
        if _CONTEXT is None:
            pass  # created below outside the lock (init takes the lock)
        else:
            return _CONTEXT
    return init_zoo_context()


def num_devices() -> int:
    return get_zoo_context().num_devices
