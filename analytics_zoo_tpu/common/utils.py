"""Small shared utilities: named timers, shape helpers, pytree helpers.

``time_it`` mirrors the reference's lightweight tracing
(``Utils.timeIt`` zoo/.../common/Utils.scala:40, used around TF session calls
at TFNet.scala:176) — elapsed time per named block, logged.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict

logger = logging.getLogger("analytics_zoo_tpu")

def _new_agg() -> dict:
    return {"count": 0, "total": 0.0, "min": float("inf"), "max": 0.0}


# Per-name AGGREGATES (count/total/min/max), not per-call lists: time_it
# wraps every train-step infeed+dispatch, so lists would grow without bound
# over multi-day jobs.
_TIMINGS: dict[str, dict] = defaultdict(_new_agg)


@contextlib.contextmanager
def time_it(name: str, log: bool = False):
    """Time a block; accumulate under ``name`` (Utils.scala:40 equivalent)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        agg = _TIMINGS[name]
        agg["count"] += 1
        agg["total"] += dt
        agg["min"] = min(agg["min"], dt)
        agg["max"] = max(agg["max"], dt)
        if log:
            logger.info("[%s] %.3f ms", name, dt * 1e3)


def get_timings() -> dict[str, dict]:
    """name -> {count, total, min, max} (seconds)."""
    return {k: dict(v) for k, v in _TIMINGS.items()}


def reset_timings() -> None:
    _TIMINGS.clear()


def to_tuple_shape(shape) -> tuple:
    """Normalize a shape argument to a tuple of ints/None."""
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def canonicalize_axis(axis: int, ndim: int) -> int:
    if axis < 0:
        axis += ndim
    if not 0 <= axis < ndim:
        raise ValueError(f"axis {axis} out of range for ndim {ndim}")
    return axis
