"""GANEstimator (reference pyzoo/zoo/tfpark/gan/gan_estimator.py:29-152).

The reference alternates generator/discriminator phases with a TF counter
variable and cond branches inside one exported graph, trained by the Spark
all-reduce.  The TPU-native step keeps the same phase algebra —
``step % (d_steps + g_steps) < d_steps`` selects the discriminator — but as
a single jitted function: ``lax.cond`` picks which parameter group gets the
gradient update, weight sharing is plain functional reuse of the
discriminator net (no variable_scope reuse), and both phases ride the same
SPMD data-parallel mesh.
"""

from __future__ import annotations

import os
import pickle
from analytics_zoo_tpu.common.safe_pickle import (
    safe_load,
)
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.engine import get_zoo_context
from analytics_zoo_tpu.feature.dataset import FeatureSet
from analytics_zoo_tpu.pipeline.api.keras.engine import Input
from analytics_zoo_tpu.pipeline.api.keras.optimizers import get_optimizer
from analytics_zoo_tpu.pipeline.api.keras.topology import Model


def _build_net(fn, *input_shapes):
    """Call a user graph-builder fn on fresh Inputs -> Model."""
    ins = [Input(shape=s) for s in input_shapes]
    out = fn(*ins) if len(ins) > 1 else fn(ins[0])
    return Model(ins if len(ins) > 1 else ins[0], out)


class GANEstimator:
    """Alternating-phase GAN trainer.

    ``generator_fn`` / ``discriminator_fn`` are graph builders over symbolic
    Variables (autograd facade); ``*_loss_fn`` are pure jnp functions —
    ``generator_loss_fn(fake_logits)`` and
    ``discriminator_loss_fn(real_logits, fake_logits)`` — matching the
    reference's TFGAN-style contract.
    """

    def __init__(self, generator_fn, discriminator_fn, generator_loss_fn,
                 discriminator_loss_fn, generator_optimizer,
                 discriminator_optimizer, generator_steps: int = 1,
                 discriminator_steps: int = 1,
                 model_dir: str | None = None):
        self._generator_fn = generator_fn
        self._discriminator_fn = discriminator_fn
        self._generator_loss_fn = generator_loss_fn
        self._discriminator_loss_fn = discriminator_loss_fn
        self._g_opt = get_optimizer(generator_optimizer)
        self._d_opt = get_optimizer(discriminator_optimizer)
        self._g_steps = int(generator_steps)
        self._d_steps = int(discriminator_steps)
        self.checkpoint_path = os.path.join(
            model_dir or tempfile.mkdtemp(), "gan_model")
        self.gen_net = None
        self.disc_net = None
        self._gp = self._dp = None
        self._gs = self._ds = None  # layer states
        self.step = 0

    # ------------------------------------------------------------------
    def _ensure_built(self, noise_shape, real_shape, rng):
        k1, k2 = jax.random.split(rng)
        if self.gen_net is None:
            self.gen_net = _build_net(self._generator_fn, noise_shape)
            self._gp, self._gs = self.gen_net.build_params(k1)
        if self.disc_net is None:
            # generate() may have built only the generator; the
            # discriminator and optimizer states still need initializing
            self.disc_net = _build_net(self._discriminator_fn, real_shape)
            self._dp, self._ds = self.disc_net.build_params(k2)
            self._g_opt_state = self._g_opt.init(self._gp)
            self._d_opt_state = self._d_opt.init(self._dp)

    def _build_step(self):
        gen, disc = self.gen_net, self.disc_net
        g_loss_fn, d_loss_fn = self._generator_loss_fn, \
            self._discriminator_loss_fn
        g_opt, d_opt = self._g_opt, self._d_opt
        period = self._g_steps + self._d_steps
        d_steps = self._d_steps

        # zoolint: disable=raw-jit -- single-device GAN demo path kept off the plan machinery on purpose (alternating G/D carries, no mesh); compile cost is one trace per fit
        @jax.jit
        def train_step(gp, dp, g_os, d_os, gs, ds, step, noise, real, rng):
            k_g, k_d = jax.random.split(rng)

            def fake_of(gp_):
                out, _ = gen.forward(gp_, noise, state=gs, training=True,
                                     rng=k_g)
                return out

            def d_phase(args):
                gp, dp, g_os, d_os = args

                def loss(dp_):
                    fake = fake_of(gp)
                    real_out, _ = disc.forward(dp_, real, state=ds,
                                               training=True, rng=k_d)
                    fake_out, _ = disc.forward(dp_, fake, state=ds,
                                               training=True, rng=k_d)
                    return jnp.mean(d_loss_fn(real_out, fake_out))

                l, grads = jax.value_and_grad(loss)(dp)
                updates, d_os = d_opt.update(grads, d_os, dp)
                dp = optax.apply_updates(dp, updates)
                return (gp, dp, g_os, d_os), l

            def g_phase(args):
                gp, dp, g_os, d_os = args

                def loss(gp_):
                    fake = fake_of(gp_)
                    fake_out, _ = disc.forward(dp, fake, state=ds,
                                               training=True, rng=k_d)
                    return jnp.mean(g_loss_fn(fake_out))

                l, grads = jax.value_and_grad(loss)(gp)
                updates, g_os = g_opt.update(grads, g_os, gp)
                gp = optax.apply_updates(gp, updates)
                return (gp, dp, g_os, d_os), l

            is_d = (step % period) < d_steps
            (gp, dp, g_os, d_os), l = jax.lax.cond(
                is_d, d_phase, g_phase, (gp, dp, g_os, d_os))
            return gp, dp, g_os, d_os, l

        return train_step

    # ------------------------------------------------------------------
    def train(self, dataset, end_trigger=None, steps: int | None = None,
              batch_size: int = 32) -> "GANEstimator":
        """Train for ``steps`` phase-steps (reference train(dataset,
        end_trigger), gan_estimator.py:65).  ``dataset``: FeatureSet or
        (noise, real) arrays — the reference's two dataset tensors."""
        ctx = get_zoo_context()
        if isinstance(dataset, tuple):
            dataset = FeatureSet.of(list(dataset))
        if steps is None:
            steps = getattr(end_trigger, "max_iteration", None) or 100
        if dataset.num_samples < batch_size:
            raise ValueError(
                f"dataset has {dataset.num_samples} samples < batch_size "
                f"{batch_size}; shrink batch_size")
        rng = jax.random.PRNGKey(ctx.seed)
        batch0 = next(dataset.batches(batch_size, shuffle=False,
                                      drop_last=False))
        noise0, real0 = batch0["x"]
        self._ensure_built(tuple(noise0.shape[1:]), tuple(real0.shape[1:]),
                           rng)
        step_fn = self._build_step()
        it = None
        while self.step < steps:
            if it is None:
                it = dataset.batches(batch_size, shuffle=True,
                                     seed=ctx.seed, epoch=self.step)
            batch = next(it, None)
            if batch is None:
                it = None
                continue
            noise, real = batch["x"]
            rng, sub = jax.random.split(rng)
            out = step_fn(self._gp, self._dp, self._g_opt_state,
                          self._d_opt_state, self._gs, self._ds,
                          jnp.asarray(self.step), noise, real, sub)
            self._gp, self._dp, self._g_opt_state, self._d_opt_state, _ = out
            self.step += 1
        self._save()
        return self

    # ------------------------------------------------------------------
    def generate(self, noise, batch_size: int = 256) -> np.ndarray:
        """Sample the generator (the reference exposes this by re-loading
        the generator variable scope from checkpoint)."""
        if self.gen_net is None:
            self.gen_net = _build_net(
                self._generator_fn, tuple(np.asarray(noise).shape[1:]))
            self.gen_net.build_params()
            self._load()
        outs = []
        for lo in range(0, len(noise), batch_size):
            out, _ = self.gen_net.forward(self._gp, noise[lo:lo + batch_size],
                                          state=self._gs, training=False)
            outs.append(np.asarray(out))
        return np.concatenate(outs)

    def _save(self):
        os.makedirs(os.path.dirname(self.checkpoint_path), exist_ok=True)
        blob = {
            "gp": jax.tree_util.tree_map(np.asarray, self._gp),
            "dp": jax.tree_util.tree_map(np.asarray, self._dp),
            "gs": jax.tree_util.tree_map(np.asarray, self._gs),
            "ds": jax.tree_util.tree_map(np.asarray, self._ds),
            "step": self.step,
        }
        with open(self.checkpoint_path, "wb") as f:
            pickle.dump(blob, f)

    def _load(self):
        with open(self.checkpoint_path, "rb") as f:
            blob = safe_load(f)
        self._gp, self._dp = blob["gp"], blob["dp"]
        self._gs, self._ds = blob["gs"], blob["ds"]
        self.step = blob["step"]
