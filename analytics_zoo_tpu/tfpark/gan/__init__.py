from .gan_estimator import GANEstimator

__all__ = ["GANEstimator"]
