"""tfpark.KerasModel (reference pyzoo/zoo/tfpark/model.py:30-315).

The reference wraps a *compiled tf.keras model*: fit routes through
TFOptimizer (graph export + JVM all-reduce), evaluate through TFNet,
predict through TFPredictor.  Here the wrapped model is the framework's own
KerasNet, and all three route through the same jitted SPMD step — the
wrapper exists for API parity (tf.keras-flavoured argument names,
``to_estimator`` interop) and for checkpoint-directory conventions.
"""

from __future__ import annotations

import numpy as np


class KerasModel:
    """tf.keras-style facade over a compiled KerasNet."""

    def __init__(self, model, model_dir: str | None = None):
        self.model = model
        self.model_dir = model_dir
        if model_dir:
            model.set_checkpoint(model_dir)

    def fit(self, x=None, y=None, batch_size=32, epochs=1,
            validation_data=None, distributed=True, **kwargs):
        """Reference model.py:90-161 (``fit`` -> TFOptimizer.optimize)."""
        return self.model.fit(x, y, batch_size=batch_size, nb_epoch=epochs,
                              validation_data=validation_data, **kwargs)

    def evaluate(self, x=None, y=None, batch_per_thread=None,
                 batch_size=32, distributed=True):
        """Reference model.py:220 (``evaluate`` -> TFNet)."""
        return self.model.evaluate(x, y,
                                   batch_size=batch_per_thread or batch_size)

    def predict(self, x, batch_per_thread=None, batch_size=32,
                distributed=True):
        """Reference model.py:294 (``predict`` -> TFPredictor)."""
        return self.model.predict(x, batch_size=batch_per_thread
                                  or batch_size)

    def get_weights(self):
        return self.model.get_weights()

    def set_weights(self, weights):
        self.model.set_weights(weights)

    def save_weights(self, filepath, overwrite=True):
        self.model.save_weights(filepath, over_write=overwrite)

    def load_weights(self, filepath, by_name=False):
        self.model.load_weights(filepath)

    def save_model(self, path, overwrite=True):
        self.model.save(path, over_write=overwrite)

    @staticmethod
    def load_model(path) -> "KerasModel":
        from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet

        return KerasModel(KerasNet.load(path))

    def predict_classes(self, x, batch_size=32) -> np.ndarray:
        return self.model.predict_classes(x, batch_size=batch_size)
