"""tfpark-equivalent high-level APIs (reference pyzoo/zoo/tfpark).

The reference's tfpark exists to train *user-defined TensorFlow graphs* on
the distributed engine: ``KerasModel`` (model.py:30-315), ``TFEstimator``
(estimator.py:84-357, tf.estimator-style model_fn), ``GANEstimator``
(gan/gan_estimator.py:29), BERT estimators and text models.  Its mechanism
— push weights into a TF session, run loss+grads, pull grads back into the
BigDL all-reduce (TFTrainingHelper.scala:188-250) — collapses on TPU into a
single jit-compiled SPMD step (SURVEY.md §3.3), so this package keeps only
the *API shapes*: bring-your-own model function, spec-driven estimators,
alternating GAN optimization, and ready-made text estimators, all building
the framework's own symbolic graph (autograd Variables + keras layers).
"""

from .estimator import TFEstimator, TFEstimatorSpec, ZooOptimizer, sparse_ce
from .gan import GANEstimator
from .model import KerasModel

__all__ = ["KerasModel", "TFEstimator", "TFEstimatorSpec", "ZooOptimizer", "sparse_ce",
           "GANEstimator"]
