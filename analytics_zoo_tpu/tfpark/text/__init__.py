from . import estimator, keras  # noqa: F401
