"""TextKerasModel base (reference pyzoo/zoo/tfpark/text/keras/
text_model.py:21-35).

The reference delegates to nlp-architect "labor" models (tf.keras graphs);
here each text model builds the framework's own functional graph, and this
base wires multi-output training: per-head losses are summed, matching the
reference's tf.keras multi-output compile behaviour.
"""

from __future__ import annotations

import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.objectives import (
    LossFunction,
    get_loss,
)
from analytics_zoo_tpu.pipeline.api.keras.optimizers import get_optimizer
from analytics_zoo_tpu.tfpark.model import KerasModel


class MultiOutputLoss(LossFunction):
    """Sum of per-head losses for multi-output nets (y_true/y_pred lists)."""

    def __init__(self, losses, weights=None):
        self.losses = [get_loss(l) for l in losses]
        self.weights = list(weights) if weights is not None \
            else [1.0] * len(self.losses)
        super().__init__(None, "multi_output")

    def __call__(self, y_true, y_pred):
        total = 0.0
        for loss, w, yt, yp in zip(self.losses, self.weights, y_true,
                                   y_pred):
            total = total + w * loss(yt, yp)
        return total

class TextKerasModel(KerasModel):
    """Base: compile with the right (possibly multi-head) loss, keep the
    reference's fit/evaluate/predict + save/load surface."""

    def __init__(self, model, optimizer=None,
                 losses=("sparse_categorical_crossentropy",)):
        losses = [losses] if isinstance(losses, str) else list(losses)
        loss = MultiOutputLoss(losses) if len(losses) > 1 else \
            get_loss(losses[0])
        model.compile(optimizer=get_optimizer(optimizer or "adam"),
                      loss=loss, metrics=None)
        super().__init__(model)

    def save_model(self, path, overwrite=True):
        self.model.save(path, over_write=overwrite)

    @classmethod
    def load_model(cls, path):
        from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet

        obj = cls.__new__(cls)
        KerasModel.__init__(obj, KerasNet.load(path))
        return obj

    def predict_classes(self, x, batch_size=32) -> np.ndarray:
        probs = self.model.predict(x, batch_size=batch_size)
        if isinstance(probs, list):
            return [np.argmax(np.asarray(p), -1) for p in probs]
        return np.argmax(probs, -1)
