"""NER model (reference pyzoo/zoo/tfpark/text/keras/ner.py:21-70, which
wraps nlp-architect's NERCRF: word + char inputs, Bi-LSTM tagger).

Inputs: word indices (B, L) and char indices (B, L, word_length).
Output: entity tag distribution (B, L, num_entities).

TPU notes: the char feature extractor is an embedding + masked mean over
the word's characters (a fused, scan-free reduction instead of the
reference's per-word char Bi-LSTM — the tagger Bi-LSTM stays); the CRF
output layer of the reference is replaced by per-token softmax (``crf_mode``
is accepted for API parity and ignored), which keeps the whole tagger a
single fused XLA program.
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.autograd import AutoGrad
from analytics_zoo_tpu.pipeline.api.keras.engine import Input
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Bidirectional,
    Dense,
    Dropout,
    Embedding,
    LSTM,
    Reshape,
)
from analytics_zoo_tpu.pipeline.api.keras.topology import Model, merge
from analytics_zoo_tpu.tfpark.text.keras.text_model import TextKerasModel


def char_word_features(seq_len, word_length, char_vocab_size, char_emb_dim):
    """char ids (B, L, W) -> per-word char feature (B, L, char_emb_dim)."""
    chars = Input(shape=(seq_len, word_length), name="char_input")
    flat = Reshape((seq_len * word_length,))(chars)
    ce = Embedding(char_vocab_size, char_emb_dim)(flat)
    ce = Reshape((seq_len, word_length, char_emb_dim))(ce)
    pooled = AutoGrad.mean(ce, axis=2)
    return chars, pooled


class NER(TextKerasModel):
    def __init__(self, num_entities, word_vocab_size, char_vocab_size,
                 word_length=12, seq_len=64, word_emb_dim=100,
                 char_emb_dim=30, tagger_lstm_dim=100, dropout=0.5,
                 crf_mode="reg", optimizer=None):
        self.num_entities = int(num_entities)
        words = Input(shape=(seq_len,), name="word_input")
        we = Embedding(word_vocab_size, word_emb_dim)(words)
        chars, cf = char_word_features(seq_len, word_length, char_vocab_size,
                                       char_emb_dim)
        h = merge([we, cf], mode="concat", concat_axis=-1)
        h = Bidirectional(LSTM(tagger_lstm_dim, return_sequences=True))(h)
        h = Dropout(dropout)(h)
        h = Bidirectional(LSTM(tagger_lstm_dim, return_sequences=True))(h)
        out = Dense(num_entities, activation="softmax")(h)
        super().__init__(Model([words, chars], out), optimizer,
                         losses="sparse_categorical_crossentropy")
