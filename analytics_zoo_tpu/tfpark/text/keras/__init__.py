from .intent_extraction import IntentEntity
from .ner import NER
from .pos_tagging import SequenceTagger
from .text_model import TextKerasModel

__all__ = ["TextKerasModel", "NER", "SequenceTagger", "IntentEntity"]
