"""Joint intent + entity model (reference pyzoo/zoo/tfpark/text/keras/
intent_extraction.py:21-70, wrapping nlp-architect's MultiTaskIntentModel).

Inputs: word indices (B, L), char indices (B, L, word_length).
Outputs: intent distribution (B, num_intents) and entity tags
(B, L, num_entities) — the reference's two-headed contract.
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras.engine import Input
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Bidirectional,
    Dense,
    Dropout,
    Embedding,
    LSTM,
)
from analytics_zoo_tpu.pipeline.api.keras.topology import Model, merge
from analytics_zoo_tpu.tfpark.text.keras.ner import char_word_features
from analytics_zoo_tpu.tfpark.text.keras.text_model import TextKerasModel


class IntentEntity(TextKerasModel):
    def __init__(self, num_intents, num_entities, word_vocab_size,
                 char_vocab_size, word_length=12, seq_len=64,
                 word_emb_dim=100, char_emb_dim=30, char_lstm_dim=30,
                 tagger_lstm_dim=100, dropout=0.2, optimizer=None):
        words = Input(shape=(seq_len,), name="word_input")
        we = Embedding(word_vocab_size, word_emb_dim)(words)
        chars, cf = char_word_features(seq_len, word_length, char_vocab_size,
                                       char_emb_dim)
        h = merge([we, cf], mode="concat", concat_axis=-1)
        shared = Bidirectional(LSTM(tagger_lstm_dim,
                                    return_sequences=True))(h)
        shared = Dropout(dropout)(shared)
        # intent head: final-state summary of the shared encoding
        intent_enc = Bidirectional(LSTM(tagger_lstm_dim))(shared)
        intent = Dense(num_intents, activation="softmax",
                       name="intent_out")(intent_enc)
        # entity head: per-token tagger
        tagged = Bidirectional(LSTM(tagger_lstm_dim,
                                    return_sequences=True))(shared)
        entities = Dense(num_entities, activation="softmax",
                         name="entity_out")(tagged)
        super().__init__(Model([words, chars], [intent, entities]),
                         optimizer,
                         losses=["sparse_categorical_crossentropy"] * 2)
