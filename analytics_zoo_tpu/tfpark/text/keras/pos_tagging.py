"""POS/chunk sequence tagger (reference pyzoo/zoo/tfpark/text/keras/
pos_tagging.py:21-60, wrapping nlp-architect's SequenceTagger).

Two outputs: pos tags (B, L, num_pos_labels) and chunk tags
(B, L, num_chunk_labels); optional char input when ``char_vocab_size`` is
given (pos_tagging.py docstring contract).
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras.engine import Input
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Bidirectional,
    Dense,
    Dropout,
    Embedding,
    LSTM,
)
from analytics_zoo_tpu.pipeline.api.keras.topology import Model, merge
from analytics_zoo_tpu.tfpark.text.keras.ner import char_word_features
from analytics_zoo_tpu.tfpark.text.keras.text_model import TextKerasModel


class SequenceTagger(TextKerasModel):
    def __init__(self, num_pos_labels, num_chunk_labels, word_vocab_size,
                 char_vocab_size=None, word_length=12, seq_len=64,
                 feature_size=100, dropout=0.2, classifier="softmax",
                 optimizer=None):
        classifier = classifier.lower()
        assert classifier in ("softmax", "crf"), \
            "classifier should be either softmax or crf"
        words = Input(shape=(seq_len,), name="word_input")
        h = Embedding(word_vocab_size, feature_size)(words)
        inputs = [words]
        if char_vocab_size is not None:
            chars, cf = char_word_features(seq_len, word_length,
                                           char_vocab_size, feature_size)
            inputs.append(chars)
            h = merge([h, cf], mode="concat", concat_axis=-1)
        h = Bidirectional(LSTM(feature_size, return_sequences=True))(h)
        h = Dropout(dropout)(h)
        pos = Dense(num_pos_labels, activation="softmax", name="pos_out")(h)
        chunk = Dense(num_chunk_labels, activation="softmax",
                      name="chunk_out")(h)
        super().__init__(
            Model(inputs if len(inputs) > 1 else inputs[0], [pos, chunk]),
            optimizer,
            losses=["sparse_categorical_crossentropy"] * 2)
