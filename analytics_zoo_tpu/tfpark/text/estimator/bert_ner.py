"""BERTNER (reference pyzoo/zoo/tfpark/text/estimator/bert_ner.py):
sequence output -> dropout -> per-token dense softmax tagger."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Dropout
from analytics_zoo_tpu.tfpark.estimator import TFEstimatorSpec
from analytics_zoo_tpu.tfpark.text.estimator.bert_base import (
    BERTBaseEstimator,
)
from analytics_zoo_tpu.tfpark.text.estimator.bert_classifier import sparse_ce


class BERTNER(BERTBaseEstimator):
    def __init__(self, num_entities, bert_config_file=None,
                 init_checkpoint=None, optimizer=None, model_dir=None,
                 dropout=0.1, **bert_overrides):
        def head_fn(seq, pooled, labels, mode, params):
            h = Dropout(dropout)(seq)
            probs = Dense(num_entities, activation="softmax",
                          name="ner_out")(h)
            if mode == "predict" or labels is None:
                return TFEstimatorSpec(mode, predictions=probs)
            return TFEstimatorSpec(mode, predictions=probs,
                                   loss=sparse_ce(probs, labels))

        super().__init__(head_fn, bert_config_file=bert_config_file,
                         init_checkpoint=init_checkpoint,
                         optimizer=optimizer, model_dir=model_dir,
                         **bert_overrides)
