from .bert_base import BERTBaseEstimator, bert_input_fn
from .bert_classifier import BERTClassifier
from .bert_ner import BERTNER
from .bert_squad import BERTSquad

__all__ = ["BERTBaseEstimator", "bert_input_fn", "BERTClassifier",
           "BERTNER", "BERTSquad"]
