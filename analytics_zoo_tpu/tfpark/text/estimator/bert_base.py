"""BERT estimator base (reference pyzoo/zoo/tfpark/text/estimator/
bert_base.py:21-130).

The reference's ``bert_model`` builds google-research BERT from a config
file and checkpoints; here the encoder is the framework's own
:class:`~analytics_zoo_tpu.pipeline.api.keras.layers.BERT` layer (one fused
XLA program, bf16-friendly), and each estimator supplies a head over the
``[sequence_output, pooled_output]`` pair.
"""

from __future__ import annotations

import json

import numpy as np

from analytics_zoo_tpu.feature.dataset import FeatureSet
from analytics_zoo_tpu.pipeline.api.keras.layers import BERT
from analytics_zoo_tpu.tfpark.estimator import TFEstimator


def read_bert_config(bert_config_file: str | None) -> dict:
    """google-research bert_config.json -> BERT layer kwargs."""
    if bert_config_file is None:
        return {}
    with open(bert_config_file) as f:
        cfg = json.load(f)
    return dict(
        vocab=cfg.get("vocab_size", 30522),
        hidden_size=cfg.get("hidden_size", 768),
        n_block=cfg.get("num_hidden_layers", 12),
        n_head=cfg.get("num_attention_heads", 12),
        seq_len=cfg.get("max_position_embeddings", 512),
        intermediate_size=cfg.get("intermediate_size", 3072),
        hidden_p_drop=cfg.get("hidden_dropout_prob", 0.1),
        attn_p_drop=cfg.get("attention_probs_dropout_prob", 0.1),
        type_vocab=cfg.get("type_vocab_size", 2),
    )


def bert_input_fn(data, max_seq_length: int, batch_size: int = 32,
                  labels=None):
    """Build an input_fn from token arrays (reference bert_base.py:51-106
    takes an RDD of feature dicts).

    ``data``: dict with ``input_ids``, optional ``token_type_ids``,
    ``position_ids``, ``input_mask`` arrays of shape (N, max_seq_length),
    or just the input_ids array.
    """
    if not isinstance(data, dict):
        data = {"input_ids": np.asarray(data)}
    ids = np.asarray(data["input_ids"], np.int32)
    n, l = ids.shape
    assert l == max_seq_length, f"input_ids length {l} != {max_seq_length}"
    types = np.asarray(data.get("token_type_ids",
                                np.zeros_like(ids)), np.int32)
    positions = np.asarray(data.get(
        "position_ids", np.broadcast_to(np.arange(l, dtype=np.int32),
                                        (n, l))), np.int32)
    mask = np.asarray(data.get("input_mask", np.ones_like(ids)), np.int32)
    xs = [ids, types, positions, mask]
    y = data.get("labels", labels)

    def input_fn():
        return FeatureSet.of(xs, None if y is None else np.asarray(y))

    return input_fn


class BERTBaseEstimator(TFEstimator):
    """Reference bert_base.py:108-130: TFEstimator whose model_fn runs the
    BERT encoder then a task head.

    ``head_fn(seq_output, pooled_output, labels, mode, params)`` returns a
    TFEstimatorSpec.
    """

    def __init__(self, head_fn, bert_config_file=None, init_checkpoint=None,
                 optimizer=None, model_dir=None, **bert_overrides):
        bert_kwargs = read_bert_config(bert_config_file)
        bert_kwargs.update(bert_overrides)
        self._bert_kwargs = bert_kwargs
        self._init_checkpoint = init_checkpoint
        self._head_fn = head_fn
        self.bert = None

        def model_fn(features, labels, mode, params):
            self.bert = BERT(**bert_kwargs)
            seq, pooled = self.bert(list(features))
            return head_fn(seq, pooled, labels, mode, params)

        super().__init__(model_fn, optimizer=optimizer, model_dir=model_dir)

    def _ensure_built(self, fs, mode):
        first = self._spec is None
        super()._ensure_built(fs, mode)
        if first and self._init_checkpoint:
            self._load_init_checkpoint()

    def _load_init_checkpoint(self):
        """Warm-start the encoder from saved weights (reference
        init_checkpoint: tf checkpoint restore)."""
        net = self._train_net or self._pred_net
        params, _ = net.build_params()
        # plain-array archive (save_checkpoint writes np.asarray only);
        # allow_pickle stays False so a tampered file cannot smuggle a
        # pickle payload through an object array
        with np.load(self._init_checkpoint, allow_pickle=False) as data:
            saved = {k: data[k] for k in data.files}
        name = self.bert.name
        bert_params = params.get(name)
        if bert_params is None:
            raise ValueError(
                f"no parameter group {name!r} in the built net; cannot "
                "warm-start")
        import jax

        flat, treedef = jax.tree_util.tree_flatten(bert_params)
        restored, misses = [], 0
        for i, leaf in enumerate(flat):
            hit = saved.get(f"{name}/{i}")
            if hit is None or hit.shape != np.asarray(leaf).shape:
                misses += 1
                restored.append(leaf)
            else:
                restored.append(hit)
        if misses == len(flat):
            raise ValueError(
                f"init_checkpoint {self._init_checkpoint!r} matches none of "
                f"the {len(flat)} encoder leaves (saved under a different "
                "layer name or architecture)")
        if misses:
            import logging

            logging.getLogger("analytics_zoo_tpu").warning(
                "warm-start restored %d/%d encoder leaves; %d kept their "
                "fresh initialization (shape/name mismatch)",
                len(flat) - misses, len(flat), misses)
        params[name] = jax.tree_util.tree_unflatten(treedef, restored)
        net.params = params

    def save_init_checkpoint(self, path: str):
        """Save the trained encoder for later warm-starts."""
        import jax

        net = self._train_net or self._pred_net
        name = self.bert.name
        flat, _ = jax.tree_util.tree_flatten(net.params[name])
        np.savez(path, **{f"{name}/{i}": np.asarray(a)
                          for i, a in enumerate(flat)})
