"""BERTSquad (reference pyzoo/zoo/tfpark/text/estimator/bert_squad.py):
SQuAD-style extractive QA — per-token start/end logit heads."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.tfpark.estimator import TFEstimatorSpec
from analytics_zoo_tpu.tfpark.text.estimator.bert_base import (
    BERTBaseEstimator,
)


def _squad_loss(start_probs, end_probs, labels):
    """labels: (B, 2) int start/end positions; mean of the two NLLs
    (the reference averages start_loss and end_loss)."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.pipeline.api.autograd import _apply_op

    def fn(sp, ep, y):
        y = y.astype(jnp.int32)
        nll = 0.0
        for probs, pos in ((sp, y[:, 0]), (ep, y[:, 1])):
            logp = jnp.log(jnp.clip(probs, 1e-7, 1.0))
            nll = nll - jnp.take_along_axis(
                logp, pos[:, None], axis=-1)[..., 0]
        return nll / 2.0

    return _apply_op(fn, lambda shapes: (shapes[0][0],), "squad_loss",
                     start_probs, end_probs, labels)


class BERTSquad(BERTBaseEstimator):
    def __init__(self, bert_config_file=None, init_checkpoint=None,
                 optimizer=None, model_dir=None, **bert_overrides):
        def head_fn(seq, pooled, labels, mode, params):
            start = Dense(1, name="squad_start")(seq)
            end = Dense(1, name="squad_end")(seq)
            start_p = _token_softmax(start)
            end_p = _token_softmax(end)
            if mode == "predict" or labels is None:
                return TFEstimatorSpec(mode, predictions=[start_p, end_p])
            return TFEstimatorSpec(
                mode, predictions=[start_p, end_p],
                loss=_squad_loss(start_p, end_p, labels))

        super().__init__(head_fn, bert_config_file=bert_config_file,
                         init_checkpoint=init_checkpoint,
                         optimizer=optimizer, model_dir=model_dir,
                         **bert_overrides)


def _token_softmax(logits_3d):
    """(B, L, 1) logits -> (B, L) softmax over tokens."""
    import jax

    from analytics_zoo_tpu.pipeline.api.autograd import _apply_op

    return _apply_op(
        lambda x: jax.nn.softmax(x[..., 0], axis=-1),
        lambda s: tuple(s[:-1]), "token_softmax", logits_3d)
