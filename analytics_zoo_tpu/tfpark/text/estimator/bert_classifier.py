"""BERTClassifier (reference pyzoo/zoo/tfpark/text/estimator/
bert_classifier.py:20-90): pooled output -> dropout -> dense softmax."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Dropout
from analytics_zoo_tpu.tfpark.estimator import TFEstimatorSpec
from analytics_zoo_tpu.tfpark.text.estimator.bert_base import (
    BERTBaseEstimator,
)


# sparse_ce lives with the generic estimator machinery now; re-exported
# here for backwards compatibility with existing imports.
from analytics_zoo_tpu.tfpark.estimator import sparse_ce  # noqa: F401,E402


class BERTClassifier(BERTBaseEstimator):
    def __init__(self, num_classes, bert_config_file=None,
                 init_checkpoint=None, optimizer=None, model_dir=None,
                 dropout=0.1, **bert_overrides):
        def head_fn(seq, pooled, labels, mode, params):
            h = Dropout(dropout)(pooled)
            probs = Dense(num_classes, activation="softmax",
                          name="classifier_out")(h)
            if mode == "predict" or labels is None:
                return TFEstimatorSpec(mode, predictions=probs)
            loss = sparse_ce(probs, labels)
            return TFEstimatorSpec(mode, predictions=probs, loss=loss)

        super().__init__(head_fn, bert_config_file=bert_config_file,
                         init_checkpoint=init_checkpoint,
                         optimizer=optimizer, model_dir=model_dir,
                         **bert_overrides)
