"""BERTClassifier (reference pyzoo/zoo/tfpark/text/estimator/
bert_classifier.py:20-90): pooled output -> dropout -> dense softmax."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Dropout
from analytics_zoo_tpu.tfpark.estimator import TFEstimatorSpec
from analytics_zoo_tpu.tfpark.text.estimator.bert_base import (
    BERTBaseEstimator,
)


def sparse_ce(probs, labels):
    """Per-sample sparse CE as a graph op over (probs, int labels)
    Variables; used by the BERT heads to express loss inside the model_fn
    graph (the reference uses tf.nn.sparse_softmax_cross_entropy)."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.pipeline.api.autograd import _apply_op

    def fn(p, y):
        logp = jnp.log(jnp.clip(p, 1e-7, 1.0))
        y = y.astype(jnp.int32).reshape(y.shape[0], -1)
        if y.shape[-1:] != (1,):  # sequence labels: mean over positions
            picked = jnp.take_along_axis(
                logp.reshape(y.shape + (logp.shape[-1],)), y[..., None],
                axis=-1)[..., 0]
            return -jnp.mean(picked, axis=-1)
        picked = jnp.take_along_axis(logp, y, axis=-1)[..., 0]
        return -picked

    return _apply_op(fn, lambda shapes: (shapes[0][0],), "sparse_ce",
                     probs, labels)


class BERTClassifier(BERTBaseEstimator):
    def __init__(self, num_classes, bert_config_file=None,
                 init_checkpoint=None, optimizer=None, model_dir=None,
                 dropout=0.1, **bert_overrides):
        def head_fn(seq, pooled, labels, mode, params):
            h = Dropout(dropout)(pooled)
            probs = Dense(num_classes, activation="softmax",
                          name="classifier_out")(h)
            if mode == "predict" or labels is None:
                return TFEstimatorSpec(mode, predictions=probs)
            loss = sparse_ce(probs, labels)
            return TFEstimatorSpec(mode, predictions=probs, loss=loss)

        super().__init__(head_fn, bert_config_file=bert_config_file,
                         init_checkpoint=init_checkpoint,
                         optimizer=optimizer, model_dir=model_dir,
                         **bert_overrides)
