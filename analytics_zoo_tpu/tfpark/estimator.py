"""TFEstimator-equivalent: spec-driven, bring-your-own-model-function
training (reference pyzoo/zoo/tfpark/estimator.py:84-357).

The reference's ``model_fn(features, labels, mode, params)`` builds a TF
graph; variables are collected from the session and trained through the
push-weights/run-graph/pull-grads sandwich (SURVEY.md §3.3).  Here
``model_fn`` receives *symbolic Variables* (the framework's autograd/keras
graph tensors), composes layers and AutoGrad math, and returns a
:class:`TFEstimatorSpec` with ``loss``/``predictions`` graph outputs.  The
estimator lowers that graph to the standard jitted SPMD train step — no
session, no weight shuttling.
"""

from __future__ import annotations

import numpy as np

from analytics_zoo_tpu.feature.dataset import FeatureSet
from analytics_zoo_tpu.pipeline.api.keras.engine import Input, Variable
from analytics_zoo_tpu.pipeline.api.keras.metrics import get_metric
from analytics_zoo_tpu.pipeline.api.keras.objectives import LossFunction
from analytics_zoo_tpu.pipeline.api.keras.optimizers import get_optimizer
from analytics_zoo_tpu.pipeline.api.keras.topology import Model

TRAIN, EVAL, PREDICT = "train", "eval", "predict"


def ZooOptimizer(optimizer):
    """Reference tfpark.ZooOptimizer wraps a tf.train.Optimizer for the
    distributed engine; here any framework optimizer/name passes through."""
    return get_optimizer(optimizer)


def sparse_ce(probs, labels):
    """Per-sample sparse CE as a graph op over (probs, int labels)
    Variables; used by the BERT heads to express loss inside the model_fn
    graph (the reference uses tf.nn.sparse_softmax_cross_entropy)."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.pipeline.api.autograd import _apply_op

    def fn(p, y):
        logp = jnp.log(jnp.clip(p, 1e-7, 1.0))
        y = y.astype(jnp.int32).reshape(y.shape[0], -1)
        if y.shape[-1:] != (1,):  # sequence labels: mean over positions
            picked = jnp.take_along_axis(
                logp.reshape(y.shape + (logp.shape[-1],)), y[..., None],
                axis=-1)[..., 0]
            return -jnp.mean(picked, axis=-1)
        picked = jnp.take_along_axis(logp, y, axis=-1)[..., 0]
        return -picked

    return _apply_op(fn, lambda shapes: (shapes[0][0],), "sparse_ce",
                     probs, labels)


class TFEstimatorSpec:
    """Ops returned by a model_fn (reference estimator.py:76-82)."""

    def __init__(self, mode, predictions=None, loss=None):
        if mode in (TRAIN, EVAL) and loss is None:
            raise ValueError(f"mode {mode!r} requires a loss")
        if mode in (EVAL, PREDICT) and predictions is None:
            raise ValueError(f"mode {mode!r} requires predictions")
        for v, what in ((predictions, "predictions"), (loss, "loss")):
            if v is not None and not _all_variables(v):
                raise TypeError(f"{what} must be symbolic Variable(s) built "
                                "from the features/labels arguments")
        self.mode = mode
        self.predictions = predictions
        self.loss = loss


def _all_variables(v) -> bool:
    vs = v if isinstance(v, (list, tuple)) else [v]
    return all(isinstance(x, Variable) for x in vs)


def _peek_shapes(fs: FeatureSet):
    """(feature_shapes, label_shapes) without the batch dim, plus dtypes."""
    batch = next(fs.batches(1, shuffle=False, drop_last=False))
    xs = batch["x"] if isinstance(batch["x"], list) else [batch["x"]]
    ys = batch.get("y")
    ys = [] if ys is None else (ys if isinstance(ys, list) else [ys])
    return ([tuple(a.shape[1:]) for a in xs],
            [tuple(a.shape[1:]) for a in ys])


class TFEstimator:
    """Reference TFEstimator (estimator.py:84): train/evaluate/predict from
    input_fns, spec-driven model building, gradient-clipping setters."""

    def __init__(self, model_fn, optimizer=None, model_dir: str | None = None,
                 config=None, params=None, warm_start_from=None):
        self.model_fn = model_fn
        self.optimizer = get_optimizer(optimizer) if optimizer is not None \
            else None
        self.model_dir = model_dir
        self.config = config
        self.params = params or {}
        self._grad_clip = None
        # built lazily from the first dataset seen
        self._spec = None
        self._train_net = None
        self._pred_net = None
        self._label_count = 0

    # -- gradient clipping (reference estimator.py:168-189) --------------
    def clear_gradient_clipping(self):
        self._grad_clip = None

    def set_constant_gradient_clipping(self, min, max):  # noqa: A002
        self._grad_clip = ("const", float(min), float(max))

    def set_gradient_clipping_by_l2_norm(self, clip_norm):
        self._grad_clip = ("l2norm", float(clip_norm))

    def set_optimizer(self, optimizer):
        self.optimizer = get_optimizer(optimizer)

    # -- graph building ---------------------------------------------------
    def _ensure_built(self, fs: FeatureSet, mode: str):
        """Call model_fn once on symbolic inputs; derive train + predict
        nets from the same graph so they share layers (the role of the
        reference's TF variable reuse)."""
        if self._spec is not None:
            return
        f_shapes, l_shapes = _peek_shapes(fs)
        features = [Input(shape=s, name=f"feature_{i}")
                    for i, s in enumerate(f_shapes)]
        labels = [Input(shape=s, name=f"label_{i}")
                  for i, s in enumerate(l_shapes)]
        f_arg = features[0] if len(features) == 1 else features
        l_arg = (labels[0] if len(labels) == 1 else labels) if labels \
            else None
        build_mode = mode if (mode == PREDICT or labels) else PREDICT
        spec = self.model_fn(f_arg, l_arg, build_mode, self.params)
        if not isinstance(spec, TFEstimatorSpec):
            raise TypeError("model_fn must return a TFEstimatorSpec")
        self._spec = spec
        self._label_count = len(labels)
        # train net FIRST so canonical layer names are fixed by the full
        # graph; the predict net reuses the already-named layers
        if spec.loss is not None:
            self._train_net = Model(features + labels, spec.loss)
        if spec.predictions is not None:
            self._pred_net = Model(features, spec.predictions)

    def _training_estimator(self):
        from analytics_zoo_tpu.pipeline.estimator import Estimator

        if self.optimizer is None:
            raise ValueError("no optimizer set; pass optimizer= or call "
                             "set_optimizer")
        # the graph already computes the loss; the training loss fn just
        # averages the graph output
        passthrough = LossFunction(lambda y_true, y_pred: y_pred,
                                   "model_fn_loss")
        return Estimator(self._train_net, optimizer=self.optimizer,
                         loss=passthrough, grad_clip=self._grad_clip,
                         model_dir=self.model_dir)

    @staticmethod
    def _to_feature_set(data) -> FeatureSet:
        if isinstance(data, FeatureSet):
            return data
        if isinstance(data, tuple):
            return FeatureSet.of(*data)
        return FeatureSet.of(data)

    # -- the tf.estimator-style entry points ------------------------------
    def train(self, input_fn, steps: int | None = None,
              batch_size: int = 32) -> "TFEstimator":
        """Reference estimator.py:194-251: train until ``steps`` iterations
        (or one epoch if None)."""
        from analytics_zoo_tpu.common.triggers import MaxEpoch, MaxIteration

        fs = self._to_feature_set(input_fn())
        self._ensure_built(fs, TRAIN)
        if self._train_net is None:
            raise ValueError("model_fn returned no loss; cannot train")
        # re-wrap: features+labels all become model *inputs* of the loss net
        merged = _MergedFeatureSet(fs)
        est = self._training_estimator()
        end = MaxIteration(steps) if steps is not None else MaxEpoch(1)
        est.train(merged, batch_size=batch_size, end_trigger=end)
        self._sync_params_to_pred()
        return self

    @property
    def _trained(self) -> bool:
        return self._train_net is not None and \
            self._train_net.params is not None

    def _sync_params_to_pred(self):
        if self._pred_net is not None and self._trained:
            self._pred_net.build_params()
            # overwrite the shared layers' params with the trained values;
            # keep params of layers only on the predictions path
            self._pred_net.params = {
                **self._pred_net.params,
                **{k: v for k, v in self._train_net.params.items()
                   if k in self._pred_net.params},
            }
            self._pred_net.state = {
                **self._pred_net.state,
                **{k: v for k, v in self._train_net.state.items()
                   if k in self._pred_net.state},
            }

    def evaluate(self, input_fn, eval_methods, steps=None,
                 checkpoint_path=None) -> dict:
        """Reference estimator.py:253-313: dict of metric -> value."""
        fs = self._to_feature_set(input_fn())
        self._ensure_built(fs, EVAL)
        if self._pred_net is None:
            raise ValueError("model_fn returned no predictions")
        preds, labels = self._forward_all(fs)
        out = {}
        for name in eval_methods:
            metric = get_metric(name)
            out[name] = metric.finalize(metric.batch_stats(labels, preds))
        if self._trained:
            losses = self._loss_all(fs)
            out["loss"] = float(np.mean(losses))
        return out

    def predict(self, input_fn, checkpoint_path=None,
                batch_size: int = 32) -> np.ndarray:
        """Reference estimator.py:315+."""
        fs = self._to_feature_set(input_fn())
        self._ensure_built(fs, PREDICT)
        if self._pred_net is None:
            raise ValueError("model_fn returned no predictions")
        self._sync_params_to_pred()
        xs = _stack_all(fs, labels=False)
        return self._pred_net.predict(
            xs[0] if len(xs) == 1 else xs, batch_size=batch_size)

    # -- helpers -----------------------------------------------------------
    def _forward_all(self, fs: FeatureSet):
        self._sync_params_to_pred()
        xs = _stack_all(fs, labels=False)
        ys = _stack_all(fs, labels=True)
        preds = self._pred_net.predict(xs[0] if len(xs) == 1 else xs)
        return preds, (ys[0] if len(ys) == 1 else ys)

    def _loss_all(self, fs: FeatureSet):
        # the loss output may be scalar per batch; run batch-wise forwards
        net = self._train_net
        net.build_params()
        losses = []
        for batch in _MergedFeatureSet(fs).batches(256, shuffle=False,
                                                   drop_last=False):
            out, _ = net.forward(net.params, batch["x"], state=net.state,
                                 training=False)
            losses.append(float(np.mean(np.asarray(out))))
        return losses


def _stack_all(fs: FeatureSet, labels: bool) -> list:
    """Materialize a FeatureSet side as full arrays (eval/predict path)."""
    chunks = []
    for batch in fs.batches(1024, shuffle=False, drop_last=False):
        part = batch.get("y") if labels else batch["x"]
        if part is None:
            return []
        chunks.append(part if isinstance(part, list) else [part])
    return [np.concatenate([c[i] for c in chunks])
            for i in range(len(chunks[0]))]


class _MergedFeatureSet(FeatureSet):
    """View of a FeatureSet where labels are appended to the features (the
    loss net takes features+labels as inputs and outputs the loss)."""

    def __init__(self, base: FeatureSet):
        self.base = base

    @property
    def num_samples(self):
        return self.base.num_samples

    def batches(self, *args, **kwargs):
        for batch in self.base.batches(*args, **kwargs):
            xs = batch["x"] if isinstance(batch["x"], list) else [batch["x"]]
            ys = batch.get("y")
            ys = [] if ys is None else (
                ys if isinstance(ys, list) else [ys])
            merged = {"x": list(xs) + list(ys)}
            if "w" in batch:
                merged["w"] = batch["w"]
            if "n_valid" in batch:
                merged["n_valid"] = batch["n_valid"]
            yield merged
