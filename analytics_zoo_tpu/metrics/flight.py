"""Crash flight recorder — the last N structured events, dumped on death.

A crashed run's registry dies with the process; what a postmortem needs
is the *sequence of final steps* — which step was in flight, whether it
was a straggler, which component went stale, what exception fired.  The
:class:`FlightRecorder` is a bounded ring of structured events (dicts)
fed by the estimator fit loop, ``ClusterServing.step()`` and the health
rollup; on ``atexit``, ``SIGTERM`` or an unhandled exception the ring is
dumped as JSON into ``ZOO_FLIGHT_DIR`` (one file per pid, atomic
rename), and a live process serves the same ring at ``/flightz``
(:mod:`analytics_zoo_tpu.metrics.http`).

The black-box-recorder shape (bounded, newest-window, always-on) follows
the Tracer ring (tracing.py): a multi-day job's recorder is O(capacity)
forever, and the window an operator reads after a day-2 crash contains
day 2.  Disable with ``ZOO_FLIGHT=0`` (then ``record`` is a cheap early
return); cap with ``ZOO_FLIGHT_EVENTS`` (default 4096).

:class:`StragglerDetector` is the per-step anomaly flagger: a step
slower than ``k`` x the rolling p50 of recent steps is a straggler (the
multi-host stall signature — one slow host drags every SPMD step), and
the fit loop records it as a ``straggler`` event.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

__all__ = ["FlightRecorder", "StragglerDetector", "StragglerBoard",
           "get_flight_recorder", "set_flight_recorder",
           "register_predump_hook"]


# ---------------------------------------------------------------------------
# Pre-dump hooks: subsystems with in-flight background work (the async
# checkpoint writer) register a flush here so a SIGTERM/exit/crash dump
# contains their FINAL event (e.g. the ``ckpt`` complete/error record)
# instead of racing the writer thread to process death.  Hooks must be
# bounded (join with timeout) and exception-safe; a dying process never
# dies harder over a hook.
# ---------------------------------------------------------------------------

_predump_lock = threading.Lock()
_predump_hooks: list = []  # guarded-by: _predump_lock


def register_predump_hook(fn) -> None:
    """Run ``fn()`` before any flight dump is written (idempotent per
    function object).  Used by the async checkpointer so the shutdown
    ordering is: flush pending snapshot -> record its ``ckpt`` event ->
    write the flight dump -> exit."""
    with _predump_lock:
        if fn not in _predump_hooks:
            _predump_hooks.append(fn)


def _run_predump_hooks() -> None:
    with _predump_lock:
        hooks = list(_predump_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception:  # noqa: BLE001 - dump path must never raise
            pass


class FlightRecorder:
    """Bounded ring of structured events + crash/exit dump hooks."""

    def __init__(self, capacity: int = 4096, dump_dir: str | None = None,
                 enabled: bool = True):
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.dropped = 0  # guarded-by: _lock
        self._events: collections.deque = collections.deque(  # guarded-by: _lock
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self._installed = False
        self._dumped_reasons: set[str] = set()  # guarded-by: _lock

    # -- recording ------------------------------------------------------
    def record(self, kind: str, **fields) -> dict | None:
        """Append one event; returns it (None when disabled).

        Events carry a ``(mono, ts)`` clock pair (ISSUE 17):
        CLOCK_MONOTONIC is shared across processes of one boot, so
        ``tools/flight_merge.py`` can align dumps from many processes
        on one timeline without trusting each process's wall clock."""
        if not self.enabled:
            return None
        ev = {"ts": time.time(), "mono": time.monotonic(), "kind": kind}
        ev.update(fields)
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1  # deque evicts the oldest on append
            self._events.append(ev)
        return ev

    def record_exception(self, exc: BaseException, where: str = ""):
        """One ``exception`` event carrying type/message/traceback tail
        (last frames only — the ring holds many events, not one core
        dump)."""
        import traceback

        tb = traceback.format_exception(type(exc), exc, exc.__traceback__)
        self.record("exception", where=where,
                    exc_type=type(exc).__name__, message=str(exc),
                    traceback="".join(tb[-6:]))

    def events(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e.get("kind") == kind]
        return evs

    def clear(self):
        with self._lock:
            self._events.clear()
            self.dropped = 0

    # -- dumping --------------------------------------------------------
    def to_doc(self, reason: str = "live") -> dict:
        # (monotonic, epoch) captured back-to-back: the merge tool's
        # per-process offset estimate even for docs whose events
        # predate the per-event `mono` field
        return {
            "reason": reason,
            "pid": os.getpid(),
            "dumped_unix": time.time(),
            "clock_anchor": {"epoch": time.time(),
                             "monotonic": time.monotonic()},
            "dropped_events": self.dropped,
            "events": self.events(),
        }

    def dump(self, reason: str) -> str | None:
        """Write the ring to ``dump_dir`` (atomic rename); one file per
        (pid, reason) so the atexit pass after a SIGTERM dump doesn't
        overwrite the more interesting earlier snapshot.  Returns the
        path, or None when no dir is configured / already dumped."""
        if not self.dump_dir or not self.enabled:
            return None
        with self._lock:
            if reason in self._dumped_reasons:
                return None
            self._dumped_reasons.add(reason)
        # Shutdown ordering fix: flush registered background writers
        # BEFORE snapshotting the ring, so their final events (the async
        # checkpointer's ``ckpt`` complete) are IN this dump.  Runs
        # outside ``_lock`` — hooks record events themselves.
        _run_predump_hooks()
        path = os.path.join(self.dump_dir,
                            f"flight-{os.getpid()}-{reason}.json")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.to_doc(reason), f)
            os.replace(tmp, path)
        except OSError:
            return None  # a dying process must not die harder over this
        return path

    # -- death hooks ----------------------------------------------------
    def install(self) -> "FlightRecorder":
        """Arm atexit + SIGTERM + unhandled-exception dumps (idempotent).

        Existing handlers are CHAINED, not replaced: the prior excepthook
        still prints the traceback, a prior SIGTERM handler still runs.
        Signal installation is skipped off the main thread (signal.signal
        raises there) and when a non-default SIGTERM handler belongs to
        an embedding app we chain to it.
        """
        if self._installed:
            return self
        self._installed = True
        import atexit
        import signal
        import sys

        atexit.register(lambda: self.dump("exit"))

        prev_hook = sys.excepthook

        def hook(exc_type, exc, tb):
            try:
                e = exc if exc is not None else exc_type()
                e.__traceback__ = tb
                self.record_exception(e, where="unhandled")
                self.dump("crash")
            finally:
                prev_hook(exc_type, exc, tb)

        sys.excepthook = hook

        try:
            prev_sig = signal.getsignal(signal.SIGTERM)
            if prev_sig is None:
                # a C-level handler we cannot call or restore from
                # Python: leave it alone entirely (atexit still dumps)
                return self

            def on_term(signum, frame):
                self.record("signal", signal="SIGTERM")
                self.dump("sigterm")
                if prev_sig == signal.SIG_IGN:
                    return  # the app IGNORES SIGTERM: keep it alive
                if callable(prev_sig):
                    prev_sig(signum, frame)
                else:  # SIG_DFL: re-deliver with default disposition
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, on_term)
        except (ValueError, OSError):
            pass  # not the main thread (embedded run): atexit still fires
        return self


class StragglerDetector:
    """Flag steps exceeding ``k`` x the rolling p50 of recent steps.

    The p50 baseline (not the mean) makes the detector robust to the
    stragglers themselves: ten 30s stalls in a 128-step window barely
    move the median, so the threshold stays anchored to the *typical*
    step.  ``min_steps`` suppresses verdicts until the window has enough
    history to mean something (compile steps would otherwise flag the
    whole warmup).
    """

    def __init__(self, k: float = 3.0, window: int = 128,
                 min_steps: int = 20):
        if k <= 1.0:
            raise ValueError(f"straggler factor k={k} must be > 1")
        self.k = float(k)
        self.min_steps = int(min_steps)
        self._window: collections.deque = collections.deque(  # guarded-by: _lock
            maxlen=int(window))
        self._lock = threading.Lock()

    @staticmethod
    def _median(vals: list) -> float:
        if not vals:
            return 0.0
        mid = len(vals) // 2
        return vals[mid] if len(vals) % 2 \
            else 0.5 * (vals[mid - 1] + vals[mid])

    def rolling_p50(self) -> float:
        with self._lock:
            vals = sorted(self._window)
        return self._median(vals)

    def observe(self, seconds: float) -> bool:
        """Record one step duration; True iff it is a straggler against
        the *prior* window (the step never dilutes its own baseline)."""
        with self._lock:
            vals = sorted(self._window)
            self._window.append(seconds)
        if len(vals) < self.min_steps:
            return False
        return seconds > self.k * self._median(vals)


class StragglerBoard:
    """Per-WORKER rolling-p50 slowdown factors (the elastic-training
    rebalance signal; ISSUE 16).

    :class:`StragglerDetector` answers "was THIS step a straggler";
    the board answers "which worker is persistently slow, and by how
    much" — ``observe(worker, step_s)`` feeds one worker's step time
    and returns that worker's slowdown factor: its rolling p50 over the
    median of every worker's rolling p50 (the fleet baseline).  1.0
    means on-pace; ``k`` means k x slower than the typical worker.  The
    supervisor's micro-batch rebalancer consumes :meth:`factors`
    instead of reaching into flight internals.

    Per-worker p50s (not pooled samples) keep the baseline robust to
    uneven reporting rates: a chatty fast worker cannot drown out a
    silent slow one.  ``min_steps`` suppresses factors until a worker
    has enough history (warmup steps would otherwise flag everyone).
    """

    def __init__(self, window: int = 128, min_steps: int = 5):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self.min_steps = int(min_steps)
        self._windows: dict = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def _p50s_locked(self) -> dict:
        # zoolint: disable=guarded-by -- _locked suffix: callers hold _lock across this call
        return {w: StragglerDetector._median(sorted(win))
                for w, win in self._windows.items()
                if len(win) >= self.min_steps}

    def observe(self, worker: str, step_s: float) -> float:
        """Record one step duration for ``worker``; returns the
        worker's current slowdown factor (1.0 while history is thin)."""
        with self._lock:
            win = self._windows.get(worker)
            if win is None:
                win = self._windows[worker] = collections.deque(
                    maxlen=self.window)
            win.append(float(step_s))
        return self.slowdown(worker)

    def fleet_p50(self) -> float:
        """Median of the per-worker rolling p50s (0.0 with no data)."""
        with self._lock:
            p50s = self._p50s_locked()
        return StragglerDetector._median(sorted(p50s.values()))

    def slowdown(self, worker: str) -> float:
        with self._lock:
            p50s = self._p50s_locked()
        base = StragglerDetector._median(sorted(p50s.values()))
        mine = p50s.get(worker)
        if mine is None or base <= 0.0:
            return 1.0
        return mine / base

    def factors(self) -> dict:
        """``{worker: slowdown_factor}`` for every worker with enough
        history — the rebalancer's one input."""
        with self._lock:
            p50s = self._p50s_locked()
        base = StragglerDetector._median(sorted(p50s.values()))
        if base <= 0.0:
            return {w: 1.0 for w in p50s}
        return {w: p / base for w, p in p50s.items()}

    def forget(self, worker: str) -> None:
        """Drop a departed worker's window so its history cannot skew
        the fleet baseline after it left the membership."""
        with self._lock:
            self._windows.pop(worker, None)


# ---------------------------------------------------------------------------
# Process-global default.  ZOO_FLIGHT=0 disables recording; ZOO_FLIGHT_DIR
# arms the crash dump; ZOO_FLIGHT_EVENTS overrides the ring capacity.
# ---------------------------------------------------------------------------

_default: FlightRecorder | None = None  # guarded-by: _default_lock
_default_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                env = os.environ
                _default = FlightRecorder(
                    capacity=int(env.get("ZOO_FLIGHT_EVENTS", "4096")),
                    dump_dir=env.get("ZOO_FLIGHT_DIR") or None,
                    enabled=env.get("ZOO_FLIGHT", "1") != "0",
                )
    return _default


def set_flight_recorder(recorder: FlightRecorder) -> FlightRecorder:
    """Swap the process-global recorder (tests); returns the previous."""
    global _default
    with _default_lock:
        prev, _default = _default, recorder
    return prev
