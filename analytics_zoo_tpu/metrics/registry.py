"""Metrics registry — labeled Counter / Gauge / Histogram families.

The framework-wide measurement substrate (ISSUE 1): every hot path
(estimator fit loop, serving step, inference predict) records into a
process-global :class:`MetricsRegistry`, and exporters
(:mod:`analytics_zoo_tpu.metrics.exporters`) render one snapshot in
Prometheus text, JSONL, or TensorBoard scalars.  The data model is the
Prometheus one — a *family* (name, kind, help, label names) owning one
*child* per label-value combination — because that is what every
downstream consumer (scrapers, dashboards, ``tools/metrics_dump.py``)
already knows how to read.

Design constraints, in order:

1. **Zero cost when disabled.**  A disabled registry hands back one
   shared :data:`NULL` singleton from every ``counter()/gauge()/
   histogram()/labels()`` call — no dict insert, no child allocation, no
   per-step garbage on the hot path (asserted by identity in
   ``tests/test_metrics.py``).
2. **Thread-safe.**  The serving loop, the infeed thread and predict
   callers all record concurrently; family creation holds the registry
   lock, child updates hold a per-family lock (Python float ``+=`` is
   three bytecodes, not atomic).
3. **Bounded memory.**  Histograms are fixed-bucket (counts + sum), so a
   multi-day job's telemetry is O(buckets), never O(observations);
   p50/p95/p99 come from linear interpolation inside the bucket bounds.
"""

from __future__ import annotations

import bisect
import math
import os
import threading
from typing import Sequence

__all__ = [
    "NULL", "NullMetric", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "get_registry", "set_registry",
    "DEFAULT_BUCKETS",
]

# Latency-shaped default buckets (seconds), Prometheus-style: the serving
# path spans ~100us jit dispatch to multi-second cold compiles.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class _NullTimer:
    """Reusable no-op context manager (``nullcontext`` allocates per use
    on some versions; this one is a shared singleton)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_TIMER = _NullTimer()


class NullMetric:
    """The disabled-mode no-op: one shared instance answers every metric
    call on a disabled registry.  ``labels()`` returns itself, so chains
    like ``reg.counter(...).labels(x="1").inc()`` allocate nothing."""

    __slots__ = ()

    def labels(self, **kwargs):
        return self

    def inc(self, amount: float = 1.0):
        pass

    def dec(self, amount: float = 1.0):
        pass

    def set(self, value: float):
        pass

    def observe(self, value: float):
        pass

    def time(self):
        return _NULL_TIMER

    def get(self) -> float:
        return 0.0

    def summary(self) -> dict:
        return {}

    def percentile(self, q: float) -> float:
        return 0.0

    def snapshot_state(self):
        return None

    def delta_since(self, prev) -> dict:
        return {}


NULL = NullMetric()


class _Timer:
    """``with child.time():`` — observe the block's wall seconds."""

    __slots__ = ("_child", "_t0")

    def __init__(self, child):
        self._child = child

    def __enter__(self):
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time

        self._child.observe(time.perf_counter() - self._t0)
        return False


class _Family:
    """Base: a named metric family owning labeled children."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}  # guarded-by: _lock

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **kwargs):
        """Child for one label-value combination (created on demand)."""
        if set(kwargs) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kwargs)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kwargs[k]) for k in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._new_child()
                    self._children[key] = child
        return child

    def _default(self):
        """The unlabeled child — families with no labelnames proxy their
        value methods straight to it."""
        if self.labelnames:
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}; "
                "call .labels(...) first")
        return self.labels()

    def samples(self) -> list[tuple[dict, object]]:
        """[(labels_dict, child)] snapshot for exporters."""
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, key)), child)
                for key, child in items]


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0  # guarded-by: _lock
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    def get(self) -> float:
        return self._value


class Counter(_Family):
    """Monotonically increasing count (records served, steps run)."""

    kind = "counter"
    _new_child = _CounterChild

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def get(self) -> float:
        return self._default().get()


class _GaugeChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0  # guarded-by: _lock
        self._lock = threading.Lock()

    def set(self, value: float):
        # single STORE of an immutable float: atomic under the GIL, and
        # last-writer-wins is exactly gauge semantics — taking the lock
        # here would serialize every hot-path set() against inc()
        # zoolint: disable=guarded-by -- atomic replace; gauge is last-writer-wins
        self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    def get(self) -> float:
        return self._value


class Gauge(_Family):
    """Point-in-time value (queue depth, memory ratio, throughput)."""

    kind = "gauge"
    _new_child = _GaugeChild

    def set(self, value: float):
        self._default().set(value)

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def dec(self, amount: float = 1.0):
        self._default().dec(amount)

    def get(self) -> float:
        return self._default().get()


class _HistogramChild:
    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_inf_sum",
                 "_lock")

    def __init__(self, bounds: tuple):
        self._bounds = bounds  # ascending finite upper bounds; immutable
        self._counts = [0] * (len(bounds) + 1)  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._inf_sum = 0.0  # guarded-by: _lock (sum past the last bound)
        self._lock = threading.Lock()

    def observe(self, value: float):
        i = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if i == len(self._bounds):
                self._inf_sum += value

    def time(self):
        return _Timer(self)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _snapshot(self) -> tuple[list[int], float, int, float]:
        """One locked copy of (counts, sum, count, inf_sum) — every
        multi-value read (summary, percentile) derives from a SINGLE
        snapshot so one exported row can never mix states (e.g. show
        p99 < p50 because a burst landed between two reads)."""
        with self._lock:
            return (list(self._counts), self._sum, self._count,
                    self._inf_sum)

    def export_state(self) -> tuple[list[tuple[float, int]], float, int]:
        """(cumulative buckets, sum, count) from ONE snapshot, so the
        Prometheus invariant ``_bucket{le="+Inf"} == _count`` holds even
        while another thread observes mid-export."""
        counts, total_sum, count, _ = self._snapshot()
        out, cum = [], 0
        for b, c in zip(self._bounds, counts):
            cum += c
            out.append((b, cum))
        out.append((math.inf, cum + counts[-1]))
        return out, total_sum, count

    def buckets(self) -> list[tuple[float, int]]:
        """[(upper_bound, CUMULATIVE count)], Prometheus `le` semantics,
        ending with (+Inf, total)."""
        return self.export_state()[0]

    def _percentile_from(self, snap, q: float) -> float:
        """Quantile estimate by linear interpolation within the bucket
        containing rank q*count (the standard fixed-bucket estimator —
        exact to within one bucket width)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} not in [0, 1]")
        counts, _, total, inf_sum = snap
        if total == 0:
            return 0.0
        rank = q * total
        prev_bound, prev_cum = 0.0, 0
        cum = 0
        for bound, c in zip(self._bounds + (math.inf,), counts):
            cum += c
            if cum >= rank:
                if math.isinf(bound):
                    # open-ended tail: the point estimate is the mean of
                    # the observations that actually landed PAST the
                    # last bound (tracked separately in _inf_sum, so a
                    # 120s stall is reported as ~120s, not clamped to
                    # the last bucket bound)
                    n_inf = cum - prev_cum
                    if n_inf == 0:
                        return prev_bound
                    return max(inf_sum / n_inf, prev_bound)
                if cum == prev_cum:
                    return bound
                frac = (rank - prev_cum) / (cum - prev_cum)
                return prev_bound + frac * (bound - prev_bound)
            prev_bound, prev_cum = bound, cum
        return prev_bound

    def percentile(self, q: float) -> float:
        return self._percentile_from(self._snapshot(), q)

    def summary(self) -> dict:
        """{count, sum, mean, p50, p95, p99} — the exporter/report
        shape, all derived from ONE consistent snapshot."""
        return self._summary_of(self._snapshot())

    def _summary_of(self, snap) -> dict:
        _, total_sum, c, _ = snap
        return {
            "count": c,
            "sum": total_sum,
            "mean": (total_sum / c) if c else 0.0,
            "p50": self._percentile_from(snap, 0.50),
            "p95": self._percentile_from(snap, 0.95),
            "p99": self._percentile_from(snap, 0.99),
        }

    def snapshot_state(self) -> tuple:
        """Opaque cumulative state for :meth:`delta_since` — take one
        before a window, hand it back after to summarize only what
        landed in between."""
        return self._snapshot()

    def delta_since(self, prev: tuple | None) -> dict:
        """Summary of the observations since ``prev`` (a value from
        :meth:`snapshot_state`; ``None`` means since child creation).

        Histograms are cumulative, which is the right export shape but
        the WRONG controller input: a decision loop (feature/autotune.py)
        must react to *recent* behavior, not a lifetime blur where the
        first hour of a run outvotes the last minute.  Bucket counts and
        sums are monotone, so the window is an exact bucket-wise
        subtraction; p50/p95/p99/mean are then computed on the window's
        own distribution.  An empty window returns ``count == 0`` and
        zeros.  ``prev`` from a child with different bucket bounds
        raises; a ``prev`` AHEAD of the current state (the child was
        replaced/reset under the caller) degrades to the full current
        summary instead of reporting negative counts.
        """
        cur = self._snapshot()
        if prev is None:
            return self._summary_of(cur)
        p_counts, p_sum, p_count, p_inf = prev
        c_counts, c_sum, c_count, c_inf = cur
        if len(p_counts) != len(c_counts):
            raise ValueError(
                f"snapshot has {len(p_counts)} buckets but histogram has "
                f"{len(c_counts)} — delta_since needs a snapshot of THIS "
                "child")
        d_counts = [c - p for c, p in zip(c_counts, p_counts)]
        if any(d < 0 for d in d_counts) or c_count < p_count:
            return self._summary_of(cur)  # reset under us: full window
        return self._summary_of(
            (d_counts, c_sum - p_sum, c_count - p_count, c_inf - p_inf))


class Histogram(_Family):
    """Fixed-bucket distribution (latencies, batch sizes)."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if any(math.isinf(b) for b in bounds):
            raise ValueError("+Inf bucket is implicit; pass finite bounds")
        self.bucket_bounds = bounds

    def _new_child(self):
        return _HistogramChild(self.bucket_bounds)

    def observe(self, value: float):
        self._default().observe(value)

    def time(self):
        return self._default().time()

    def percentile(self, q: float) -> float:
        return self._default().percentile(q)

    def summary(self) -> dict:
        return self._default().summary()

    def snapshot_state(self) -> tuple:
        return self._default().snapshot_state()

    def delta_since(self, prev: tuple | None) -> dict:
        return self._default().delta_since(prev)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Thread-safe family registry.

    ``enabled=False`` turns every factory method into a return of the
    shared :data:`NULL` no-op (the zero-cost-when-disabled contract);
    flipping :meth:`set_enabled` later affects only *subsequent* factory
    calls — code that cached a real child keeps recording into it.
    """

    def __init__(self, enabled: bool = True):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}  # guarded-by: _lock
        self.enabled = bool(enabled)

    def set_enabled(self, enabled: bool):
        self.enabled = bool(enabled)

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        if not self.enabled:
            return NULL
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = cls(name, help, labelnames, **kwargs)
                    self._families[name] = fam
        if not isinstance(fam, cls) or \
                fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} re-registered as {cls.kind} with labels "
                f"{tuple(labelnames)} but exists as {fam.kind} with "
                f"labels {fam.labelnames}")
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] | None = None) -> Histogram:
        """``buckets=None`` means DEFAULT_BUCKETS at creation and
        no-check on access (callers reading an existing family need not
        know its bounds); EXPLICIT buckets that conflict with the
        existing family raise — silently landing observations in the
        wrong bounds would corrupt every percentile."""
        fam = self._get_or_create(
            Histogram, name, help, labelnames,
            buckets=DEFAULT_BUCKETS if buckets is None else buckets)
        if buckets is not None and isinstance(fam, Histogram):
            expected = tuple(sorted(float(b) for b in buckets))
            if fam.bucket_bounds != expected:
                raise ValueError(
                    f"histogram {name!r} re-registered with buckets "
                    f"{expected} but exists with {fam.bucket_bounds}")
        return fam

    def collect(self) -> list[_Family]:
        """Families sorted by name (exporter input)."""
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def clear(self):
        with self._lock:
            self._families.clear()


# ---------------------------------------------------------------------------
# Process-global default registry.  ZOO_METRICS=0 disables it at creation —
# the env tier matching ZooConfig's other knobs (common/engine.py).
# ---------------------------------------------------------------------------

_default: MetricsRegistry | None = None  # guarded-by: _default_lock
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-global registry every built-in instrumentation site
    records into (estimator fit loop, serving step, inference predict)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = MetricsRegistry(
                    enabled=os.environ.get("ZOO_METRICS", "1") != "0")
    return _default


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests, embedding apps); returns
    the previous one."""
    global _default
    with _default_lock:
        prev, _default = _default, registry
    return prev
