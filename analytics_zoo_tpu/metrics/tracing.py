"""Step tracing — nested ``span()`` blocks exported as Chrome-trace JSON.

The role of the reference's ``Utils.timeIt`` logging, upgraded to a
structured timeline: every ``with span("zoo.train.step")`` records one
complete event (``ph: "X"``) into the process-global :class:`Tracer`;
``Tracer.to_chrome_trace()`` renders the ``chrome://tracing`` /
Perfetto-loadable document, the same format ``jax.profiler`` traces use
so the two timelines can be eyeballed side by side.

Spans nest through a :mod:`contextvars` variable, so nesting is correct
across threads (the serving loop thread and the infeed thread each get
their own span stack) and each event records its parent span's name.

Two optional device hooks, both gated on jax being importable so the
module stays dependency-free:

- ``span(..., sync=tree)`` calls ``jax.block_until_ready`` on the tree
  before closing the span — an explicit device-sync point, because an
  async-dispatched step's host-side duration is otherwise just the
  dispatch cost (the same reason ``Estimator.measure_pure_step``
  fetch-forces its loss).
- ``Tracer(jax_bridge=True)`` (default) additionally wraps each span in
  ``jax.profiler.TraceAnnotation`` when jax is initialized, so zoo spans
  show up inside ``jax.profiler`` captures (ZOO_PROFILE_DIR).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time

__all__ = ["Tracer", "span", "get_tracer", "set_tracer"]

# Innermost open span's name (per execution context / thread).
_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "zoo_current_span", default=None)


def _block_until_ready(tree):
    """Device-sync a pytree if jax is importable; no-op otherwise."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax is always in this image
        return
    jax.block_until_ready(tree)


class Tracer:
    """Bounded in-memory event sink.

    ``max_events`` caps memory on multi-day jobs as a RING buffer: past
    the cap the OLDEST events are evicted and counted (``dropped``),
    never silently — the export carries the eviction count as metadata.
    Keeping the newest window is the debugging-shaped choice: the trace
    an operator saves after a day-2 anomaly must contain day 2, not the
    first hour of startup spans.
    """

    def __init__(self, enabled: bool = True, max_events: int = 50_000,
                 jax_bridge: bool = True):
        import collections

        self.enabled = bool(enabled)
        self.max_events = int(max_events)
        self.jax_bridge = bool(jax_bridge)
        self.dropped = 0  # guarded-by: _lock
        self._events: collections.deque = collections.deque(  # guarded-by: _lock
            maxlen=self.max_events)
        self._lock = threading.Lock()
        # registry counter mirroring `dropped`, resolved lazily on the
        # first eviction (constructing a Tracer must not force the
        # process-global registry into existence)
        self._drop_counter = None
        # perf_counter origin so ts fields are small positive
        # microseconds — plus a (monotonic, epoch) anchor captured at
        # the SAME instant, so tools/flight_merge.py can place this
        # process's µs timeline on the cluster-wide wall clock (each
        # process's trace clock alone is only self-consistent)
        self._t0 = time.perf_counter()
        self._t0_monotonic = time.monotonic()
        self._t0_epoch = time.time()

    # -- recording ------------------------------------------------------
    def now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def add_event(self, name: str, ts_us: float, dur_us: float,
                  args: dict | None = None):
        if not self.enabled:
            return
        ev = {
            "name": name,
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "cat": "zoo",
        }
        if args:
            ev["args"] = args
        evicting = False
        with self._lock:
            if len(self._events) == self.max_events:
                self.dropped += 1  # deque evicts the oldest on append
                evicting = True
            self._events.append(ev)
        if evicting:
            # ring evictions were silent before (ISSUE 2 satellite): a
            # scraper watching zoo_trace_spans_dropped_total now sees a
            # trace outgrowing its window without pulling /trace
            if self._drop_counter is None:
                from analytics_zoo_tpu.metrics.registry import get_registry

                self._drop_counter = get_registry().counter(
                    "zoo_trace_spans_dropped_total",
                    "span events evicted from the tracer ring buffer")
            self._drop_counter.inc()

    # -- export ---------------------------------------------------------
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clock_anchor(self) -> dict:
        """The trace-origin instant on three clocks: ``ts=0`` µs of
        this trace corresponds to ``epoch`` wall time and ``monotonic``
        (CLOCK_MONOTONIC — shared by all processes of one boot, so
        same-host merges can sidestep wall-clock skew entirely)."""
        return {"epoch": self._t0_epoch,
                "monotonic": self._t0_monotonic,
                "pid": os.getpid()}

    def to_chrome_trace(self) -> dict:
        """The ``chrome://tracing`` JSON object format."""
        doc = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "metadata": {"producer": "analytics_zoo_tpu.metrics.tracing",
                         "dropped_events": self.dropped,
                         "clock_anchor": self.clock_anchor()},
        }
        return doc

    def save(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def clear(self):
        with self._lock:
            self._events.clear()
            self.dropped = 0


@contextlib.contextmanager
def span(name: str, sync=None, args: dict | None = None,
         tracer: Tracer | None = None):
    """Time a block as one trace event; nests via contextvars.

    Args:
      name: event name (dotted convention: ``zoo.train.step``).
      sync: optional pytree passed to ``jax.block_until_ready`` before the
        span closes — makes the span cover device execution, not just the
        async dispatch.
      args: extra key/values attached to the event.
      tracer: override the process-global tracer (tests).
    """
    t = tracer if tracer is not None else get_tracer()
    if not t.enabled:
        # cheap disabled path: no contextvar churn, no event dict
        yield
        if sync is not None:
            _block_until_ready(sync)
        return
    parent = _current_span.get()
    token = _current_span.set(name)
    annot = None
    if t.jax_bridge:
        try:
            import jax

            annot = jax.profiler.TraceAnnotation(name)
            annot.__enter__()
        except Exception:
            annot = None
    t0 = t.now_us()
    try:
        yield
        if sync is not None:
            _block_until_ready(sync)
    finally:
        dur = t.now_us() - t0
        if annot is not None:
            try:
                annot.__exit__(None, None, None)
            except Exception:
                pass
        _current_span.reset(token)
        ev_args = dict(args) if args else {}
        if parent is not None:
            ev_args["parent"] = parent
        t.add_event(name, t0, dur, ev_args or None)


# ---------------------------------------------------------------------------
# Process-global default tracer.  ZOO_TRACE=0 disables span recording;
# ZOO_TRACE_EVENTS overrides the event cap.
# ---------------------------------------------------------------------------

_default: Tracer | None = None  # guarded-by: _default_lock
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                env = os.environ
                _default = Tracer(
                    enabled=env.get("ZOO_TRACE", "1") != "0",
                    max_events=int(env.get("ZOO_TRACE_EVENTS", "50000")),
                )
    return _default


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-global tracer; returns the previous one."""
    global _default
    with _default_lock:
        prev, _default = _default, tracer
    return prev
