"""Runtime collectors: per-step breakdown and device-memory gauges.

The tf.data lesson (PAPERS.md: "tf.data: A Machine Learning Data
Processing Framework"): the data-wait vs. compute split must be measured
*inside* the framework, per step, not reconstructed per-benchmark.
:class:`StepMetrics` is that split for the estimator fit loop:

- ``data_wait``   — blocking on the infeed queue (host batch assembly +
  H2D dispatch the double-buffered feeder failed to hide);
- ``dispatch``    — handing the sharded batch to the jitted step
  (host-side async dispatch cost);
- ``step``        — one full loop iteration wall time (data_wait +
  dispatch + callback/trigger work; device compute overlaps it).

All three are histograms, so the exporters carry p50/p95/p99 — tail
behavior (a stalling input pipeline shows up as a fat data_wait p99 long
before it moves the mean).

:func:`record_device_memory` snapshots ``device.memory_stats()`` into
gauges when the backend provides it (TPU does; CPU returns None — the
collector is a silent no-op there).
"""

from __future__ import annotations

from analytics_zoo_tpu.metrics.registry import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    get_registry,
)

__all__ = ["StepMetrics", "ServingMetrics", "DataPipelineMetrics",
           "AutotuneMetrics", "FleetMetrics", "OracleMetrics",
           "ElasticMetrics", "ScrapeMetrics", "SloMetrics",
           "RouterMetrics", "AdmissionMetrics",
           "record_device_memory"]

# Step-time shaped buckets (seconds): the shared latency bounds minus
# the 30s tail — a 30s TRAIN step is not a resolution we need, and
# deriving (not copying) keeps the two tables in sync.
STEP_BUCKETS = DEFAULT_BUCKETS[:-1]

# Batch sizes are small integers; bound buckets cover 1..4096.
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


class StepMetrics:
    """Fit-loop breakdown recorder.

    Children are resolved ONCE at construction, so the per-step cost is
    three ``observe`` + two ``inc`` calls — and on a disabled registry
    every one of those is the shared no-op singleton (no allocation)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.data_wait = reg.histogram(
            "zoo_train_data_wait_seconds",
            "time blocked on the infeed queue per step",
            buckets=STEP_BUCKETS)
        self.dispatch = reg.histogram(
            "zoo_train_step_dispatch_seconds",
            "host-side jitted-step dispatch time per step",
            buckets=STEP_BUCKETS)
        self.step = reg.histogram(
            "zoo_train_step_seconds",
            "full loop-iteration wall time per step",
            buckets=STEP_BUCKETS)
        self.steps = reg.counter(
            "zoo_train_steps_total", "train steps dispatched")
        self.stragglers = reg.counter(
            "zoo_train_stragglers_total",
            "steps flagged by the flight recorder's straggler detector "
            "(> k x rolling p50)")
        self.records = reg.counter(
            "zoo_train_records_total", "training records consumed")
        self.throughput = reg.gauge(
            "zoo_train_throughput_records_per_sec",
            "end-to-end fit throughput, updated per epoch")
        self.epoch = reg.gauge("zoo_train_epoch", "current epoch")

    def record_step(self, data_wait_s: float, dispatch_s: float,
                    step_s: float, batch_size: int, steps: int = 1):
        """One loop iteration = one DISPATCH.  Under the fused multi-step
        path (``ZOO_STEPS_PER_DISPATCH=K``) a dispatch advances ``steps``
        optimizer steps and consumes ``batch_size`` records total, so the
        steps/records counters keep their K=1 meaning while the three
        histograms measure per-dispatch host cost (the quantity fusion
        amortizes)."""
        self.data_wait.observe(data_wait_s)
        self.dispatch.observe(dispatch_s)
        self.step.observe(step_s)
        self.steps.inc(steps)
        self.records.inc(batch_size)

    def record_epoch(self, epoch: int, throughput: float):
        self.epoch.set(epoch)
        self.throughput.set(throughput)


class ServingMetrics:
    """Cluster Serving telemetry (one instance per :class:`ClusterServing`).

    Gauges/histograms follow the queueing-system canon: offered depth,
    service batch size, end-to-end service latency, broker pressure."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        # callers gate work done ONLY to feed a metric (e.g. the extra
        # broker xlen round-trip for queue_depth) on this flag — the
        # NULL children silently discard values, but the side channel
        # that produced them is not free
        self.enabled = reg.enabled
        self.queue_depth = reg.gauge(
            "zoo_serving_queue_depth",
            "input-stream backlog after the poll")
        self.batch_size = reg.histogram(
            "zoo_serving_batch_size",
            "records per served micro-batch", buckets=BATCH_BUCKETS)
        self.latency = reg.histogram(
            "zoo_serving_step_latency_seconds",
            "decode -> predict -> write-back latency per non-empty step "
            "(poll/block wait excluded)")
        self.predict_latency = reg.histogram(
            "zoo_serving_predict_seconds",
            "model predict time per micro-batch group")
        self.records = reg.counter(
            "zoo_serving_records_total", "records served")
        self.trims = reg.counter(
            "zoo_serving_backpressure_trims_total",
            "backpressure stream cuts (ClusterServing.scala:128-134 role)")
        self.stragglers = reg.counter(
            "zoo_serving_stragglers_total",
            "serving cycles flagged > k x rolling p50 by the flight "
            "recorder's straggler detector")
        self.memory_ratio = reg.gauge(
            "zoo_serving_broker_memory_ratio",
            "broker used/max memory in [0,1]")


class DataPipelineMetrics:
    """Host data-plane telemetry (``zoo_data_prefetch_*``) for the
    parallel prefetch pipeline (feature/prefetch.py).

    The two histograms are the pipeline's diagnosis pair: a fat
    ``consumer_wait`` p99 means the pipeline is the bottleneck (raise
    ``workers``/``depth``); a fat ``producer_stall`` p99 means the
    CONSUMER (device step) is — the pipeline is keeping up and further
    workers buy nothing.  Queue occupancy sits between them: pinned at
    the depth limit is healthy, pinned at zero is starving."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.enabled = reg.enabled
        self.queue_depth = reg.gauge(
            "zoo_data_prefetch_queue_depth",
            "prefetch queue occupancy (batches ready or in flight)")
        self.depth_limit = reg.gauge(
            "zoo_data_prefetch_depth",
            "configured prefetch queue capacity")
        self.workers = reg.gauge(
            "zoo_data_prefetch_workers",
            "configured prefetch worker threads")
        self.producer_stall = reg.histogram(
            "zoo_data_prefetch_producer_stall_seconds",
            "time the producer blocked on a full prefetch queue per batch",
            buckets=STEP_BUCKETS)
        self.consumer_wait = reg.histogram(
            "zoo_data_prefetch_consumer_wait_seconds",
            "time the consumer blocked waiting for the next prefetched "
            "batch", buckets=STEP_BUCKETS)
        self.batches = reg.counter(
            "zoo_data_prefetch_batches_total",
            "batches delivered through the prefetch pipeline")
        self.errors = reg.counter(
            "zoo_data_prefetch_errors_total",
            "exceptions propagated through the prefetch pipeline")
        self.batch_bytes = reg.gauge(
            "zoo_data_prefetch_batch_bytes",
            "host bytes of the last delivered batch (the autotune "
            "RAM-budget estimator input: resident ≈ bytes x depth)")


class AutotuneMetrics:
    """Closed-loop autotuner telemetry (``zoo_autotune_*``,
    feature/autotune.py).

    Gauges mirror the controller's CURRENT knob values so a scrape shows
    what the pipeline is running with right now; the decision counter
    (labeled by knob and reason) is the tuning activity rate — a counter
    that keeps climbing long after warmup means the policy is
    oscillating, not converging.  The full structured decision log
    (time, knob, old→new, reason) is bounded in the controller and
    served at ``/varz`` under ``autotune``."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.enabled = reg.enabled
        self.workers = reg.gauge(
            "zoo_autotune_workers",
            "current autotuned prefetch worker-pool size")
        self.depth = reg.gauge(
            "zoo_autotune_depth",
            "current autotuned prefetch queue depth")
        self.read_ahead = reg.gauge(
            "zoo_autotune_read_ahead",
            "current autotuned shard read-ahead count")
        self.k = reg.gauge(
            "zoo_autotune_k",
            "current autotuned steps_per_dispatch (fused scan-K)")
        self.ram_budget = reg.gauge(
            "zoo_autotune_ram_budget_bytes",
            "configured host-RAM budget for the prefetch window")
        self.ram_estimate = reg.gauge(
            "zoo_autotune_ram_estimate_bytes",
            "estimated resident bytes of the prefetch window "
            "(batch bytes x (depth + workers) + read-ahead shards)")
        self.decisions = reg.counter(
            "zoo_autotune_decisions_total",
            "autotune knob changes, by knob and reason",
            labelnames=("knob", "reason"))


class OracleMetrics:
    """Predictive compile-plane telemetry (``zoo_oracle_*``,
    analysis/oracle.py).

    The family's job is the data-loop audit: every prediction the
    oracle hands a consumer (the autotuner's K prior, the estimator's
    ``plan="auto"``) is counted, and once the consumer measures the
    outcome the predicted/measured pair lands in per-config gauges with
    the relative error alongside — a scrape answers "is the model
    earning its priors" without replaying the run.  ``fit_samples`` is
    the residual model's training-set size (0 = pure analytic
    roofline, the <N-samples fallback)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.enabled = reg.enabled
        self.predictions = reg.counter(
            "zoo_oracle_predictions_total",
            "config predictions served, by consumer "
            "(autotune_k / plan_auto / rank)",
            labelnames=("consumer",))
        self.predicted_sps = reg.gauge(
            "zoo_oracle_predicted_steps_per_sec",
            "oracle-predicted steps/sec for the chosen config",
            labelnames=("config",))
        self.measured_sps = reg.gauge(
            "zoo_oracle_measured_steps_per_sec",
            "measured steps/sec reported back for a predicted config",
            labelnames=("config",))
        self.rel_error = reg.gauge(
            "zoo_oracle_prediction_rel_error",
            "|predicted - measured| / measured for the last "
            "prediction->outcome pair per config",
            labelnames=("config",))
        self.fit_samples = reg.gauge(
            "zoo_oracle_fit_samples",
            "training rows behind the residual model "
            "(0 = analytic-only fallback)")
        # predictive serving plane (ISSUE 20): the choose_serving
        # verdict per model — what the fleet was PRIMED with before the
        # first request, scored against measured predict latency the
        # same way every oracle pick is
        self.serving_predicted_seconds = reg.gauge(
            "zoo_serving_predicted_seconds",
            "oracle-predicted predict-step wall seconds per pad bucket",
            labelnames=("model", "bucket"))
        self.serving_predicted_replicas = reg.gauge(
            "zoo_serving_predicted_replicas",
            "oracle-predicted replica target for the offered rate",
            labelnames=("model",))
        self.serving_predicted_budget_ms = reg.gauge(
            "zoo_serving_predicted_batch_budget_ms",
            "oracle-picked continuous-batching budget per model",
            labelnames=("model",))


class FleetMetrics:
    """Serving-fleet control plane telemetry (``zoo_fleet_*``,
    serving/fleet.py + the claim-mode server loop).

    The replica-count pair (live vs target) is the autoscaler's visible
    state; the decision counter (labeled action/reason) is its activity
    rate — like ``zoo_autotune_decisions_total``, a counter still
    climbing long after a load change means the policy is oscillating.
    ``lease_takeovers`` is the fleet's fault-tolerance signal: nonzero
    means a replica died mid-batch and a survivor reclaimed its
    records (exactly-once via lease expiry).  ``est_p99_seconds`` is
    the scaler's own SLO estimate (predict p99 + Little's-law queue
    delay) so a scrape shows WHAT the scale decision saw."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.enabled = reg.enabled
        self.replicas = reg.gauge(
            "zoo_fleet_replicas", "live serving replicas")
        self.replicas_target = reg.gauge(
            "zoo_fleet_replicas_target",
            "autoscaler's current target replica count")
        self.decisions = reg.counter(
            "zoo_fleet_decisions_total",
            "autoscaler scale decisions, by action and reason",
            labelnames=("action", "reason"))
        self.lease_takeovers = reg.counter(
            "zoo_fleet_lease_takeovers_total",
            "expired-lease records reclaimed from dead replicas")
        self.replica_deaths = reg.counter(
            "zoo_fleet_replica_deaths_total",
            "replicas found dead by the controller's supervision pass")
        self.est_p99 = reg.gauge(
            "zoo_fleet_est_p99_seconds",
            "scaler's estimated request p99 over the last window "
            "(predict p99 + queue_depth / service_rate)")
        self.queue_depth = reg.gauge(
            "zoo_fleet_unclaimed_backlog",
            "unclaimed input-stream backlog at the last scaler tick "
            "(claimed in-flight work excluded)")
        self.slo_violations = reg.counter(
            "zoo_fleet_slo_violation_windows_total",
            "scaler windows whose estimated p99 violated the SLO")
        self.batch_flushes = reg.counter(
            "zoo_fleet_batch_flushes_total",
            "continuous-batching bucket flushes, by reason "
            "(full / budget / drain)", labelnames=("reason",))
        # federation tier (ISSUE 17): host dimension alongside replicas
        self.hosts = reg.gauge(
            "zoo_fleet_hosts",
            "live scrape-fresh hosts contributing federated signals")
        self.hosts_target = reg.gauge(
            "zoo_fleet_hosts_target",
            "scaler's host target from replicas-per-host packing "
            "(advisory — an external provisioner acts on it)")


class RouterMetrics:
    """Multi-tenant router telemetry (``zoo_router_*`` +
    per-model ``zoo_fleet_*{model=}``, serving/router.py).

    One ``ModelRouter`` supervises a heterogeneous set of per-model
    fleets; the model-labeled fleet trio (replicas / backlog /
    est p99) is the per-tenant view the unlabeled ``zoo_fleet_*``
    families cannot carry (two controllers on one registry would
    collide), and a merged scrape across hosts keeps the label — the
    zoowatch federation plane sees each tenant separately."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.enabled = reg.enabled
        self.models = reg.gauge(
            "zoo_router_models", "models currently routed")
        self.decisions = reg.counter(
            "zoo_router_decisions_total",
            "router control actions (prime / scale / stop), "
            "by model and action", labelnames=("model", "action"))
        self.replicas = reg.gauge(
            "zoo_fleet_model_replicas",
            "live serving replicas, by model", labelnames=("model",))
        self.backlog = reg.gauge(
            "zoo_fleet_model_backlog",
            "unclaimed per-model stream backlog at the last tick",
            labelnames=("model",))
        self.est_p99 = reg.gauge(
            "zoo_fleet_model_est_p99_seconds",
            "scaler's estimated request p99, by model",
            labelnames=("model",))


class AdmissionMetrics:
    """Front-door admission telemetry (``zoo_admission_*``,
    serving/admission.py).

    The accept/shed counter pair is the shedding audit: every enqueue
    verdict is counted by model, so `accepted == served` (the
    exactly-once audit) and the shed fraction under overload are both
    one scrape away.  ``state`` is the current verdict gauge (0 =
    accepting, 1 = shedding) and ``retry_after_seconds`` the hint the
    last shed carried — what a client backoff loop actually obeys."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.enabled = reg.enabled
        self.requests = reg.counter(
            "zoo_admission_requests_total",
            "front-door verdicts, by model and verdict (accept/shed)",
            labelnames=("model", "verdict"))
        self.state = reg.gauge(
            "zoo_admission_state",
            "current admission state (0 accepting, 1 shedding), "
            "by model", labelnames=("model",))
        self.retry_after = reg.gauge(
            "zoo_admission_retry_after_seconds",
            "retry-after hint carried by the latest shed verdict",
            labelnames=("model",))
        self.evaluations = reg.counter(
            "zoo_admission_evaluations_total",
            "admission re-evaluation ticks across all models")


class ElasticMetrics:
    """Elastic training-runtime telemetry (``zoo_elastic_*``,
    elastic/supervisor.py + membership.py).

    The generation/world pair is the membership ledger's visible state:
    generation increments on ANY join/leave, world size is the live
    member count the next training cohort runs at.  ``rejoins_total``
    (labeled by reason — worker_death / worker_join / below_min) is the
    supervisor's activity rate, the elastic analogue of
    ``zoo_fleet_decisions_total``.  ``steps_lost_total`` is the
    fault-tolerance cost signal: steps replayed from the last durable
    snapshot after an uncheckpointed death — zero while faults land on
    checkpoint boundaries.  ``rejoin_seconds`` is the gap from a
    generation change to the new cohort's first training step; it is
    the number the lease (``ZOO_ELASTIC_LEASE_MS``) trades against
    false-positive deaths."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.enabled = reg.enabled
        self.generation = reg.gauge(
            "zoo_elastic_generation",
            "membership generation (increments on any join/leave)")
        self.world_size = reg.gauge(
            "zoo_elastic_world_size",
            "live training-worker count of the current generation")
        self.rejoins = reg.counter(
            "zoo_elastic_rejoins_total",
            "generation changes orchestrated by the supervisor, "
            "by reason", labelnames=("reason",))
        self.worker_deaths = reg.counter(
            "zoo_elastic_worker_deaths_total",
            "workers found dead (expired lease or dead process) by the "
            "supervisor's scan")
        self.respawns = reg.counter(
            "zoo_elastic_respawns_total",
            "worker processes respawned by the supervisor")
        self.steps_lost = reg.counter(
            "zoo_elastic_steps_lost_total",
            "training steps replayed from the latest snapshot after an "
            "uncheckpointed fault")
        self.rebalances = reg.counter(
            "zoo_elastic_rebalances_total",
            "straggler-driven micro-batch share rebalances")
        self.rejoin_seconds = reg.histogram(
            "zoo_elastic_rejoin_seconds",
            "wall time from generation change to the new cohort's "
            "first step")


class ScrapeMetrics:
    """Federation-scraper telemetry (``zoo_scrape_*``,
    metrics/scrape.py).

    ``staleness_seconds`` is the load-bearing gauge: seconds since the
    last successful pull from each target.  A dead host's counters stop
    moving but its LAST values persist in the aggregator (flagged
    ``stale`` — merge.py), so staleness is the only signal that
    distinguishes "quiet host" from "vanished host"; the default
    heartbeat SLO watches exactly this family."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.enabled = reg.enabled
        self.targets = reg.gauge(
            "zoo_scrape_targets",
            "targets currently in the scrape set (static + discovered)")
        self.fetches = reg.counter(
            "zoo_scrape_fetches_total",
            "successful telemetry pulls, by target",
            labelnames=("target",))
        self.errors = reg.counter(
            "zoo_scrape_errors_total",
            "failed telemetry pulls (connect/timeout/decode), by target",
            labelnames=("target",))
        self.staleness = reg.gauge(
            "zoo_scrape_staleness_seconds",
            "seconds since the last successful pull, by target",
            labelnames=("target",))
        self.fetch_seconds = reg.histogram(
            "zoo_scrape_fetch_seconds",
            "wall time of one target pull (GET + decode + ingest)")


class SloMetrics:
    """Burn-rate engine telemetry (``zoo_slo_*``, metrics/slo.py).

    ``burn_rate`` is windowed (label ``window`` = short/long): 1.0
    means the error budget burns exactly at the sustainable rate; an
    alert needs BOTH windows above the spec's threshold, so a brief
    spike (short high, long low) and old news (long high, short low)
    both stay quiet.  ``alert_active`` is the current verdict per SLO;
    ``alerts_total`` counts firing transitions."""

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.enabled = reg.enabled
        self.burn_rate = reg.gauge(
            "zoo_slo_burn_rate",
            "error-budget burn rate per SLO and window "
            "(1.0 = burning exactly at budget)",
            labelnames=("slo", "window"))
        self.alert_active = reg.gauge(
            "zoo_slo_alert_active",
            "1 while the multi-window burn alert fires, by SLO",
            labelnames=("slo",))
        self.alerts = reg.counter(
            "zoo_slo_alerts_total",
            "alert firing transitions (quiet -> firing), by SLO",
            labelnames=("slo",))
        self.evaluations = reg.counter(
            "zoo_slo_evaluations_total",
            "engine evaluation ticks across all specs")


def record_device_memory(registry: MetricsRegistry | None = None) -> int:
    """Snapshot per-device memory stats into gauges.

    Returns the number of devices that reported stats (0 on backends
    without ``memory_stats``, e.g. CPU — then no gauges are touched)."""
    reg = registry if registry is not None else get_registry()
    if not reg.enabled:
        return 0
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return 0
    reported = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        reported += 1
        dev = str(d.id)
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                reg.gauge(
                    f"zoo_device_{key}",
                    "per-device HBM usage (jax memory_stats)",
                    labelnames=("device",),
                ).labels(device=dev).set(stats[key])
    return reported
