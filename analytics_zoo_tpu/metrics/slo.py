"""Declarative SLOs + multi-window burn-rate alerting over a
:class:`~analytics_zoo_tpu.metrics.timeseries.TimeSeriesStore`.

An :class:`SloSpec` names a metric family, a per-observation threshold
and an objective ("99% of predict calls complete within 500 ms").  The
:class:`SloEngine` evaluates every spec each tick using the SRE
multi-window burn-rate rule: the alert fires only when BOTH a short
window (is it happening NOW?) and a long window (has it been happening
long enough to matter?) burn the error budget above the spec's
``burn_threshold``.  A burn rate of 1.0 means errors arrive exactly as
fast as the budget allows; 14.4 means a 30-day budget dies in 2 days.
The short window makes the alert fast to RESOLVE once the cause is
fixed; the long window keeps one bad scrape from paging.

Verdicts land the three standard ways every zoo control plane uses
(autotune / fleet / elastic convention): the ``zoo_slo_*`` metric
family, ``slo_alert`` flight events, and a bounded decision log
surfaced at /varz under ``slo`` — plus the dedicated ``/alertz``
endpoint (metrics/http.py) that serves every live engine's alert state
for dashboards and the bench harness.

Spec kinds:

- ``latency`` — family is a histogram; an observation is bad when it
  lands above ``threshold`` (bucket-interpolated over the window).
- ``ceiling`` — family is a gauge; a sampled point is bad when its
  value exceeds ``threshold`` (heartbeat age, memory ratio, stall
  seconds).

Consumers: the federated ``SloScaler`` path reads the same store; the
elastic ``TrainSupervisor`` runs a private engine over per-worker
heartbeat-age series and converts firing alerts into
straggler/dead-worker decisions.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import weakref

from analytics_zoo_tpu.metrics.flight import get_flight_recorder
from analytics_zoo_tpu.metrics.runtime import SloMetrics
from analytics_zoo_tpu.metrics.timeseries import TimeSeriesStore

__all__ = ["SloSpec", "SloEngine", "default_slos", "varz_doc",
           "alertz_doc"]

# Live engines for the /varz `slo` panel and /alertz — weak so a
# dropped engine disappears from the rollup instead of leaking.
_active: "weakref.WeakSet[SloEngine]" = weakref.WeakSet()
_active_lock = threading.Lock()


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One service-level objective over a stored metric family.

    ``objective`` is the good-fraction target (0.99 = 1% error
    budget); ``threshold`` is the per-observation ceiling in the
    family's native unit (seconds for latency histograms)."""

    name: str
    family: str
    threshold: float
    objective: float = 0.99
    kind: str = "latency"  # "latency" (histogram) | "ceiling" (gauge)
    short_window: float = 30.0
    long_window: float = 300.0
    burn_threshold: float = 1.0
    labels: tuple = ()  # ((key, value), ...) — exact series match
    description: str = ""

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SloSpec {self.name!r}: objective must be in (0, 1), "
                f"got {self.objective}")
        if self.threshold <= 0:
            raise ValueError(
                f"SloSpec {self.name!r}: threshold must be > 0, "
                f"got {self.threshold}")
        if not 0 < self.short_window < self.long_window:
            raise ValueError(
                f"SloSpec {self.name!r}: need 0 < short_window "
                f"({self.short_window}) < long_window "
                f"({self.long_window})")
        if self.kind not in ("latency", "ceiling"):
            raise ValueError(
                f"SloSpec {self.name!r}: kind must be 'latency' or "
                f"'ceiling', got {self.kind!r}")
        if self.burn_threshold <= 0:
            raise ValueError(
                f"SloSpec {self.name!r}: burn_threshold must be > 0, "
                f"got {self.burn_threshold}")

    def label_dict(self) -> dict | None:
        return dict(self.labels) if self.labels else None

    def to_doc(self) -> dict:
        return {
            "name": self.name, "family": self.family,
            "threshold": self.threshold, "objective": self.objective,
            "kind": self.kind, "short_window": self.short_window,
            "long_window": self.long_window,
            "burn_threshold": self.burn_threshold,
            "labels": dict(self.labels),
            "description": self.description,
        }


def default_slos(slo_p99_ms: float = 500.0,
                 step_budget_s: float = 1.0,
                 ckpt_stall_s: float = 1.0,
                 heartbeat_stale_s: float = 10.0,
                 short_window: float = 30.0,
                 long_window: float = 300.0,
                 burn_threshold: float = 1.0) -> list[SloSpec]:
    """The four stock SLOs the zoowatch plane watches out of the box.

    The heartbeat SLO rides on the scraper's own staleness gauge, so a
    host that stops answering /varz burns budget even though none of
    ITS metrics move — the federation-tier liveness check."""
    common = dict(short_window=short_window, long_window=long_window,
                  burn_threshold=burn_threshold)
    return [
        SloSpec("predict_latency", "zoo_serving_predict_seconds",
                threshold=slo_p99_ms / 1e3, objective=0.99,
                description="serving predict p99 budget", **common),
        SloSpec("step_time", "zoo_train_step_seconds",
                threshold=step_budget_s, objective=0.95,
                description="training step-time budget", **common),
        SloSpec("checkpoint_stall", "zoo_ckpt_stall_seconds",
                threshold=ckpt_stall_s, objective=0.99,
                description="async checkpoint stall budget", **common),
        SloSpec("worker_heartbeat", "zoo_scrape_staleness_seconds",
                threshold=heartbeat_stale_s, objective=0.90,
                kind="ceiling",
                description="scrape-target freshness (host liveness)",
                **common),
    ]


class SloEngine:
    """Evaluates SLO specs against a store; holds alert state.

    ``evaluate()`` is the tick — call it from whatever loop already
    owns the store's cadence (the scraper's poll loop passes itself as
    ``on_scrape`` hook, the supervisor ticks its private engine).  The
    engine never starts threads of its own."""

    def __init__(self, store: TimeSeriesStore,
                 specs: list | tuple = (),
                 registry=None, flight=None,
                 log_capacity: int = 256, clock=time.time):
        self.store = store
        self.metrics = SloMetrics(registry)
        self._flight = flight if flight is not None \
            else get_flight_recorder()
        self._clock = clock
        self._lock = threading.Lock()
        self._specs: dict[str, SloSpec] = {}  # guarded-by: _lock
        self._alerts: dict[str, dict] = {}  # guarded-by: _lock
        # bounded decision log (firing/resolved transitions), /varz slo
        self._decisions = collections.deque(  # guarded-by: _lock
            maxlen=int(log_capacity))
        for spec in specs:
            self.add_spec(spec)
        with _active_lock:
            _active.add(self)

    def add_spec(self, spec: SloSpec):
        if not isinstance(spec, SloSpec):
            raise TypeError(f"expected SloSpec, got {type(spec)!r}")
        with self._lock:
            self._specs[spec.name] = spec

    def specs(self) -> list[SloSpec]:
        with self._lock:
            return list(self._specs.values())

    # -- evaluation -----------------------------------------------------
    def _burns(self, spec: SloSpec, now: float) -> tuple[float, float]:
        labels = spec.label_dict()
        short = self.store.burn_rate(
            spec.family, spec.threshold, spec.objective,
            spec.short_window, labels=labels, now=now)
        long_ = self.store.burn_rate(
            spec.family, spec.threshold, spec.objective,
            spec.long_window, labels=labels, now=now)
        return short, long_

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One tick over every spec; returns the CURRENTLY FIRING
        alerts.  Transitions (quiet->firing, firing->resolved) land in
        the flight recorder and the decision log; burn gauges update
        every tick."""
        t = now if now is not None else self._clock()
        with self._lock:
            specs = list(self._specs.values())
        firing_now = []
        for spec in specs:
            # store queries take the STORE's lock; never ours
            short, long_ = self._burns(spec, t)
            firing = (short >= spec.burn_threshold
                      and long_ >= spec.burn_threshold)
            if self.metrics.enabled:
                self.metrics.burn_rate.labels(
                    slo=spec.name, window="short").set(short)
                self.metrics.burn_rate.labels(
                    slo=spec.name, window="long").set(long_)
                self.metrics.alert_active.labels(
                    slo=spec.name).set(1.0 if firing else 0.0)
            with self._lock:
                prev = self._alerts.get(spec.name)
                was_firing = bool(prev and prev.get("firing"))
                alert = {
                    "slo": spec.name, "firing": firing,
                    "short_burn": round(short, 4),
                    "long_burn": round(long_, 4),
                    "burn_threshold": spec.burn_threshold,
                    "threshold": spec.threshold,
                    "objective": spec.objective,
                    "since": (prev.get("since") if was_firing and firing
                              else (t if firing else None)),
                    "ts": t,
                }
                self._alerts[spec.name] = alert
                transition = None
                if firing and not was_firing:
                    transition = "firing"
                elif was_firing and not firing:
                    transition = "resolved"
                if transition:
                    self._decisions.append({
                        "ts": t, "slo": spec.name, "state": transition,
                        "short_burn": round(short, 4),
                        "long_burn": round(long_, 4),
                    })
            if transition:
                if self.metrics.enabled and transition == "firing":
                    self.metrics.alerts.labels(slo=spec.name).inc()
                self._flight.record(
                    "slo_alert", slo=spec.name, state=transition,
                    short_burn=round(short, 4),
                    long_burn=round(long_, 4),
                    threshold=spec.threshold)
            if firing:
                firing_now.append(alert)
        if self.metrics.enabled:
            self.metrics.evaluations.inc()
        return firing_now

    # -- introspection --------------------------------------------------
    def alerts(self) -> list[dict]:
        """Latest verdict per spec (firing and quiet both listed)."""
        with self._lock:
            return [dict(a) for a in self._alerts.values()]

    def firing(self) -> list[dict]:
        return [a for a in self.alerts() if a.get("firing")]

    def decision_log(self) -> list[dict]:
        with self._lock:
            return list(self._decisions)

    def to_doc(self) -> dict:
        with self._lock:
            specs = [s.to_doc() for s in self._specs.values()]
            alerts = [dict(a) for a in self._alerts.values()]
            decisions = list(self._decisions)
        return {"specs": specs, "alerts": alerts,
                "decisions": decisions}


def varz_doc() -> list[dict]:
    """Docs for every live engine — the /varz ``slo`` panel (same
    sys.modules-gated pattern as autotune/fleet/elastic)."""
    with _active_lock:
        engines = list(_active)
    return [e.to_doc() for e in engines]


def alertz_doc() -> dict:
    """The /alertz body: one merged alert view across live engines."""
    with _active_lock:
        engines = list(_active)
    alerts = []
    for e in engines:
        alerts.extend(e.alerts())
    return {
        "ts": time.time(),
        "engines": len(engines),
        "firing": [a for a in alerts if a.get("firing")],
        "alerts": alerts,
    }
