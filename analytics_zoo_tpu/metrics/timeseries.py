"""Time-series windows over telemetry snapshots — the history /varz never had.

Every exporter in the stack serves the registry's *current* cumulative
state; every controller that needs "recent behavior" (autotune, the
fleet scaler) keeps its own private ``snapshot_state()`` baseline.  The
:class:`TimeSeriesStore` makes that pattern a shared primitive: a
bounded per-series ring of timestamped points in the MERGEABLE sample
format (:func:`analytics_zoo_tpu.metrics.merge.registry_samples` — the
shape the federation scraper pulls off the wire), answering the three
window queries the zoowatch control planes need:

- :meth:`rate` — counter increase per second over a trailing window
  (monotone-reset tolerant, the Prometheus ``rate()`` contract);
- :meth:`percentile_over` / :meth:`window_summary` — a histogram's
  distribution over ONLY the window, by bucket-wise subtraction of the
  cumulative state at the window's edges.  The subtraction and
  interpolation are the registry's own: points store
  ``Histogram.delta_since``-compatible state tuples and the summary is
  computed by ``_HistogramChild.delta_since`` itself, so window
  percentiles here and in the autotuner can never drift apart;
- :meth:`burn_rate` — the SRE error-budget burn over a window: the
  fraction of observations that violated an SLO threshold, divided by
  the budget ``(1 - objective)``.  ``1.0`` = burning exactly at budget;
  the multi-window alert rule lives in :mod:`analytics_zoo_tpu.metrics.
  slo`.

Per-host series: ingest labels samples with their source (the scraper
passes ``source={"host": target}``), and every query takes ``labels``
— ``None`` AGGREGATES across all series of the family (counters sum,
histograms merge bucket-wise when bounds agree), which is exactly the
fleet-wide view the federated scaler reads.

Thread-safety: one lock around the ring dict; ingestion comes from the
scraper thread while queries come from scaler/engine ticks.  Nothing
blocking is called under the lock.
"""

from __future__ import annotations

import threading
import time

from analytics_zoo_tpu.metrics.registry import _HistogramChild

__all__ = ["TimeSeriesStore", "fraction_le"]

# A store that outlives its scrape targets must not grow without bound:
# past this many distinct series, new ones are counted and dropped.
DEFAULT_MAX_SERIES = 4096


def _series_key(name: str, labels: dict | None) -> tuple:
    return (name, tuple(sorted((labels or {}).items())))


def _hist_state_from_sample(sample: dict):
    """Mergeable histogram sample -> ``(bounds, state)`` where ``state``
    is the registry's ``snapshot_state()`` tuple ``(per-bucket counts,
    sum, count, inf_sum)``.

    The wire format carries cumulative counts and no ``inf_sum`` (the
    mean of the open tail); the tail therefore interpolates to the last
    finite bound — the conservative estimate a remote series can
    support."""
    bkts = sample.get("buckets") or []
    if not bkts:
        return None
    bounds = tuple(float(b) for b, _ in bkts[:-1])
    cums = [int(c) for _, c in bkts]
    counts = [cums[0]] + [cums[i] - cums[i - 1]
                          for i in range(1, len(cums))]
    return bounds, (counts, float(sample.get("sum", 0.0)),
                    int(sample.get("count", 0)), 0.0)


def _window_summary(bounds: tuple, new_state: tuple,
                    prev_state: tuple | None) -> dict:
    """``Histogram.delta_since`` over two stored state tuples.

    Routed through a detached ``_HistogramChild`` so the bucket-wise
    subtraction, reset degradation and percentile interpolation are the
    registry's OWN code path, not a reimplementation that could drift."""
    child = _HistogramChild(bounds)
    counts, h_sum, h_count, inf_sum = new_state
    with child._lock:
        child._counts = list(counts)
        child._sum = float(h_sum)
        child._count = int(h_count)
        child._inf_sum = float(inf_sum)
    return child.delta_since(prev_state)


def _merge_hist_states(states: list) -> tuple | None:
    """Element-wise sum of same-bounds ``(bounds, state)`` pairs — the
    cross-host aggregate; ``None`` on bound conflict (the merge.py
    rule: silently adding mismatched buckets corrupts percentiles)."""
    if not states:
        return None
    bounds = states[0][0]
    if any(b != bounds for b, _ in states[1:]):
        return None
    counts = [0] * len(states[0][1][0])
    h_sum = h_count = inf_sum = 0.0
    for _, (c, s, n, inf) in states:
        if len(c) != len(counts):
            return None
        counts = [a + b for a, b in zip(counts, c)]
        h_sum += s
        h_count += n
        inf_sum += inf
    return bounds, (counts, h_sum, int(h_count), inf_sum)


def fraction_le(bounds: tuple, counts: list, threshold: float) -> float:
    """Estimated fraction of observations ``<= threshold`` from a
    per-bucket count vector (linear interpolation inside the bucket the
    threshold falls in — the same fixed-bucket estimator the registry's
    percentiles use, inverted).  1.0 on an empty window (no
    observations violated anything)."""
    total = sum(counts)
    if total <= 0:
        return 1.0
    good = 0.0
    prev_bound = 0.0
    for i, c in enumerate(counts):
        bound = bounds[i] if i < len(bounds) else float("inf")
        if threshold >= bound:
            good += c
        elif threshold > prev_bound:
            width = bound - prev_bound
            frac = ((threshold - prev_bound) / width) if width > 0 else 0.0
            good += c * frac
            break
        else:
            break
        prev_bound = bound
    return min(1.0, good / total)


class _Series:
    __slots__ = ("kind", "points")

    def __init__(self, kind: str, capacity: int):
        import collections

        self.kind = kind
        # (ts, value) for counter/gauge; (ts, (bounds, state)) histogram
        self.points = collections.deque(maxlen=capacity)


class TimeSeriesStore:
    """Bounded per-series ring of timestamped snapshot points."""

    def __init__(self, capacity: int = 512,
                 max_series: int = DEFAULT_MAX_SERIES,
                 clock=time.time):
        if capacity < 2:
            raise ValueError(
                f"capacity must be >= 2 (a window needs two edges), "
                f"got {capacity}")
        self.capacity = int(capacity)
        self.max_series = int(max_series)
        self._clock = clock
        self._lock = threading.Lock()
        self._series: dict[tuple, _Series] = {}  # guarded-by: _lock
        self.dropped_series = 0  # guarded-by: _lock

    # -- ingestion ------------------------------------------------------
    def ingest(self, samples: list, ts: float | None = None,
               source: dict | None = None) -> int:
        """Append one timestamped point per mergeable-format sample
        (``merge.registry_samples`` shape).  ``source`` labels are
        merged into every sample's labels — per-host series identity.
        Returns the number of points stored."""
        when = float(ts) if ts is not None else self._clock()
        stored = 0
        prepared = []
        for s in samples:
            labels = dict(s.get("labels") or {})
            if source:
                labels.update(source)
            kind = s.get("kind")
            if kind == "histogram":
                st = _hist_state_from_sample(s)
                if st is None:
                    continue
                prepared.append((_series_key(s["name"], labels),
                                 "histogram", st))
            elif kind in ("counter", "gauge"):
                prepared.append((_series_key(s["name"], labels), kind,
                                 float(s.get("value", 0.0))))
        with self._lock:
            for key, kind, point in prepared:
                ser = self._series.get(key)
                if ser is None:
                    if len(self._series) >= self.max_series:
                        self.dropped_series += 1
                        continue
                    ser = self._series[key] = _Series(kind, self.capacity)
                ser.points.append((when, point))
                stored += 1
        return stored

    def ingest_registry(self, registry=None, ts: float | None = None,
                        source: dict | None = None) -> int:
        """Convenience: snapshot a LIVE registry into the store (the
        local, non-federated feed)."""
        from analytics_zoo_tpu.metrics.merge import registry_samples

        return self.ingest(registry_samples(registry), ts=ts,
                           source=source)

    def observe(self, name: str, value: float, kind: str = "gauge",
                labels: dict | None = None, ts: float | None = None):
        """Append one scalar point directly (gauge/counter) — the
        supervisor's heartbeat-age feed, which has no registry sample
        behind it."""
        self.ingest([{"name": name, "kind": kind, "value": float(value),
                      **({"labels": labels} if labels else {})}], ts=ts)

    # -- introspection --------------------------------------------------
    def series(self) -> dict:
        """``{rendered_key: {"kind", "points", "newest_ts"}}``."""
        with self._lock:
            items = list(self._series.items())
        out = {}
        for (name, labels), ser in items:
            key = name if not labels else "%s{%s}" % (
                name, ",".join(f"{k}={v}" for k, v in labels))
            newest = ser.points[-1][0] if ser.points else None
            out[key] = {"kind": ser.kind, "points": len(ser.points),
                        "newest_ts": newest}
        return out

    def label_sets(self, name: str) -> list[dict]:
        with self._lock:
            keys = [k for k in self._series if k[0] == name]
        return [dict(labels) for _, labels in keys]

    def _select(self, name: str, labels: dict | None) -> list[_Series]:
        """Matching series under the lock-free read contract: exact
        label match when given, every series of the family when None."""
        with self._lock:
            if labels is not None:
                ser = self._series.get(_series_key(name, labels))
                return [ser] if ser is not None else []
            return [ser for (n, _), ser in self._series.items()
                    if n == name]

    @staticmethod
    def _window_points(ser: _Series, start: float) -> list:
        # deques are append-only here; a snapshot list is race-free
        return [p for p in list(ser.points) if p[0] >= start]

    # -- queries --------------------------------------------------------
    def rate(self, name: str, window: float,
             labels: dict | None = None, now: float | None = None) -> float:
        """Counter increase per second over the trailing ``window``
        (summed across series when ``labels`` is None).  A counter
        reset mid-window degrades to the newest value over the elapsed
        time — increase can never be negative."""
        t = now if now is not None else self._clock()
        total = 0.0
        for ser in self._select(name, labels):
            pts = self._window_points(ser, t - window)
            if len(pts) < 2:
                continue
            (t0, v0), (t1, v1) = pts[0], pts[-1]
            if t1 <= t0:
                continue
            inc = (v1 - v0) if v1 >= v0 else v1
            total += max(0.0, inc) / (t1 - t0)
        return total

    def window_summary(self, name: str, window: float,
                       labels: dict | None = None,
                       now: float | None = None) -> dict:
        """Histogram distribution over ONLY the window:
        ``{count, sum, mean, p50, p95, p99}`` via the registry's
        ``delta_since`` between the window's edge states.  Aggregates
        across series when ``labels`` is None (bound conflicts keep
        per-series windows out of the merge, the merge.py rule).
        Returns a zero summary when the window has no two edges."""
        t = now if now is not None else self._clock()
        edges = []
        for ser in self._select(name, labels):
            if ser.kind != "histogram":
                continue
            pts = self._window_points(ser, t - window)
            if not pts:
                continue
            # window edges: oldest in-window state is the baseline; a
            # series younger than the window uses its first point ever
            # recorded (count since birth — no pre-history to subtract)
            edges.append((pts[0][1], pts[-1][1]))
        if not edges:
            return {"count": 0, "sum": 0.0, "mean": 0.0,
                    "p50": 0.0, "p95": 0.0, "p99": 0.0}
        merged_new = _merge_hist_states([new for _, new in edges])
        merged_old = _merge_hist_states([old for old, _ in edges])
        if merged_new is None or merged_old is None:
            # bound conflict across hosts: fall back to the largest
            # single series rather than corrupting the percentiles
            old, new = max(edges, key=lambda e: e[1][1][2])
            return _window_summary(new[0], new[1], old[1])
        return _window_summary(merged_new[0], merged_new[1],
                               merged_old[1])

    def percentile_over(self, name: str, q: float, window: float,
                        labels: dict | None = None,
                        now: float | None = None) -> float:
        """One window-local quantile (0.99 for a p99-over-30s)."""
        key = "p%d" % round(q * 100)
        summ = self.window_summary(name, window, labels=labels, now=now)
        if key in summ:
            return summ[key]
        # delta_since summaries carry exactly p50/p95/p99 — the set the
        # registry computes; anything else would be a silent estimate
        raise ValueError(
            f"percentile_over supports q in {{0.5, 0.95, 0.99}}, "
            f"got {q}")

    def bad_fraction(self, name: str, threshold: float, window: float,
                     labels: dict | None = None,
                     now: float | None = None) -> tuple[float, int]:
        """``(violating_fraction, samples)`` over the window.

        Histogram series: fraction of window observations above the
        threshold (bucket interpolation).  Gauge series: fraction of
        window POINTS above the threshold — the freshness/ceiling SLO
        shape (heartbeat age, memory ratio).  Counters have no
        threshold semantics and contribute nothing."""
        t = now if now is not None else self._clock()
        good = 0.0
        total = 0
        for ser in self._select(name, labels):
            pts = self._window_points(ser, t - window)
            if not pts:
                continue
            if ser.kind == "histogram":
                bounds, new = pts[-1][1]
                old = pts[0][1][1]
                d = [c - p for c, p in zip(new[0], old[0])]
                if any(x < 0 for x in d):
                    d = list(new[0])  # reset mid-window: full state
                n = sum(d)
                if n <= 0:
                    continue
                good += fraction_le(bounds, d, threshold) * n
                total += n
            elif ser.kind == "gauge":
                vals = [v for _, v in pts]
                good += sum(1 for v in vals if v <= threshold)
                total += len(vals)
        if total <= 0:
            return 0.0, 0
        return max(0.0, 1.0 - good / total), total

    def burn_rate(self, name: str, threshold: float, objective: float,
                  window: float, labels: dict | None = None,
                  now: float | None = None) -> float:
        """Error-budget burn over the window: ``bad_fraction / (1 -
        objective)``.  1.0 = violating exactly as often as the SLO
        allows; an alert rule fires on a multiple of it (slo.py).
        0.0 when the window holds no samples — no data is not a
        violation (the scrape-staleness SLO covers silent hosts)."""
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {objective}")
        bad, n = self.bad_fraction(name, threshold, window,
                                   labels=labels, now=now)
        if n == 0:
            return 0.0
        return bad / (1.0 - objective)
