"""Health model — component heartbeats with stale-threshold rollup.

The ``/healthz`` contract a load balancer or kubelet needs (ISSUE 2): a
process is *healthy* iff every registered component has heartbeat
recently.  Components are the long-lived loops whose silence means the
process is wedged even though it still accepts TCP connections — the
serving loop, the infeed feeder, actor connections.  Each registers with
a ``stale_after`` budget; :meth:`HealthRegistry.status` rolls the ages
up into one verdict, and :class:`~analytics_zoo_tpu.metrics.http.
MetricsServer` maps that verdict onto 200/503.

Transitions (healthy -> stale and back) are recorded into the flight
recorder (:mod:`analytics_zoo_tpu.metrics.flight`) when one is
installed, so a postmortem dump shows *when* a component went quiet,
not just that it was quiet at the end.

Thread-safety: heartbeats come from the serving loop, the feeder thread
and actor pumps concurrently; a heartbeat is one locked dict write.
"""

from __future__ import annotations

import threading
import time

__all__ = ["HealthRegistry", "get_health", "set_health"]

# A component that never declared its own budget is considered wedged
# after this many seconds of silence.
DEFAULT_STALE_AFTER = 15.0


class HealthRegistry:
    """Named component heartbeats + stale rollup.

    ``register`` is idempotent (safe in constructors / loop preambles);
    ``heartbeat`` auto-registers unknown components with the default
    budget so instrumentation sites need no setup ceremony.
    """

    def __init__(self, clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        # name -> [stale_after, last_beat, last_verdict_healthy, forced]
        # forced is None (age-driven) or an explicit bool verdict for
        # components that are idle-OK but break-FAIL (actor connections:
        # no traffic is fine, a broken pipe is not)
        self._components: dict[str, list] = {}  # guarded-by: _lock

    def register(self, component: str,
                 stale_after: float = DEFAULT_STALE_AFTER):
        """Declare a component (and its silence budget); beats it once."""
        with self._lock:
            entry = self._components.get(component)
            if entry is None:
                self._components[component] = [float(stale_after),
                                               self._clock(), True, None]
            else:
                entry[0] = float(stale_after)
                entry[1] = self._clock()

    def heartbeat(self, component: str):
        with self._lock:
            entry = self._components.get(component)
            if entry is None:
                self._components[component] = [DEFAULT_STALE_AFTER,
                                               self._clock(), True, None]
            else:
                entry[1] = self._clock()
                entry[3] = None  # fresh beat overrides a forced verdict

    def set_status(self, component: str, healthy: bool):
        """Explicit verdict for components with no natural cadence: the
        rollup uses it instead of the age check until the next
        heartbeat.  An actor connection is marked healthy at spawn and
        unhealthy when its pipe/socket breaks — silence in between is
        not staleness."""
        with self._lock:
            entry = self._components.get(component)
            if entry is None:
                entry = [DEFAULT_STALE_AFTER, self._clock(), True, None]
                self._components[component] = entry
            entry[1] = self._clock()
            entry[3] = bool(healthy)

    def unregister(self, component: str):
        """Drop a component (a loop that finished *on purpose* must not
        read as wedged forever after)."""
        with self._lock:
            self._components.pop(component, None)

    def status(self) -> dict:
        """Rollup: ``{"healthy": bool, "components": {name: {...}}}``.

        Healthy iff every registered component's age is within its
        budget (an empty registry is healthy: nothing claimed to be
        alive, so nothing is provably wedged).  Observing a component
        cross its threshold (either direction) records one ``health``
        transition event into the flight recorder.
        """
        now = self._clock()
        transitions = []
        components = {}
        healthy = True
        with self._lock:
            for name, entry in self._components.items():
                stale_after, last_beat, was_healthy, forced = entry
                age = now - last_beat
                ok = forced if forced is not None else age <= stale_after
                if ok != was_healthy:
                    entry[2] = ok
                    transitions.append((name, ok, age))
                healthy = healthy and ok
                components[name] = {
                    "healthy": ok,
                    "age_seconds": round(age, 3),
                    "stale_after_seconds": stale_after,
                }
                if forced is not None:
                    components[name]["forced"] = forced
        for name, ok, age in transitions:
            _record_transition(name, ok, age)
        return {"healthy": healthy, "components": components}


def _record_transition(component: str, healthy: bool, age: float):
    """Flight-recorder hook (lazy import: flight.py never imports us)."""
    try:
        from analytics_zoo_tpu.metrics.flight import get_flight_recorder

        get_flight_recorder().record(
            "health", component=component,
            state="healthy" if healthy else "stale",
            age_seconds=round(age, 3))
    except Exception:  # health must never take the caller down
        pass


# ---------------------------------------------------------------------------
# Process-global default, matching get_registry()/get_tracer().
# ---------------------------------------------------------------------------

_default: HealthRegistry | None = None  # guarded-by: _default_lock
_default_lock = threading.Lock()


def get_health() -> HealthRegistry:
    """The process-global health registry every built-in loop beats."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = HealthRegistry()
    return _default


def set_health(health: HealthRegistry) -> HealthRegistry:
    """Swap the process-global health registry (tests); returns the
    previous one."""
    global _default
    with _default_lock:
        prev, _default = _default, health
    return prev
