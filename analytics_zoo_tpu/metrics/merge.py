"""Mergeable telemetry snapshots + driver-side cross-process aggregation.

The distributed half of the registry (ISSUE 2): every actor-worker and
spawned-actor process accumulates its own :class:`MetricsRegistry`; this
module defines the *snapshot* format those processes ship over the
``__zoo_telemetry__`` control frame (parallel/actors.py) and the
driver-side :class:`TelemetryAggregator` that folds snapshots from many
sources into one pod-level view.

Snapshot format (:func:`telemetry_snapshot`) — a plain JSON-able dict,
built on the registry's existing export primitives (``child.get()`` for
counters/gauges, ``_HistogramChild.export_state()`` for the cumulative
bucket vector), so a snapshot carries FULL mergeable state, not lossy
p50/p95 summaries::

    {"ts": ..., "pid": ..., "host": ..., "health": {...},
     "samples": [
       {"name", "kind", "help", "labels"?, "value"},            # ctr/gauge
       {"name", "kind", "help", "labels"?,                      # histogram
        "buckets": [[le, cum], ..., [None, total]],             # None = +Inf
        "sum": ..., "count": ...},
     ]}

Merge semantics (the Prometheus aggregation rules):

- **counters sum** across sources — 3 actors that each served 100
  records are a pod that served 300;
- **gauges keep per-source labeled series** — queue depths and memory
  ratios from different hosts must not be added;
- **histograms merge bucket-wise** (element-wise cumulative-count sum,
  sums and counts added) when bucket bounds agree; sources with
  conflicting bounds stay per-source only (silently adding mismatched
  buckets would corrupt every percentile — same rule as the registry's
  explicit-bucket conflict check).

The aggregator keeps ingested snapshots *alongside* the driver registry
(not folded into it): re-ingesting a fresh pull from the same source
REPLACES its series, which a fold-into-counters design cannot express.
``merged()`` returns both the per-source labeled series and the
cluster totals; ``prometheus_text()`` renders the per-source series in
exposition format for ``/metrics`` (scrapers sum; humans read totals
from ``/varz``).

Staleness (ISSUE 17): a source that stops re-ingesting is never
evicted — its last snapshot stays in the rollup so a dead host remains
VISIBLE — but once its last ingest is older than ``stale_after``
seconds it is flagged: ``sources()`` reports ``stale: true`` +
``age_seconds``, and ``labeled_samples()`` adds a ``stale="true"``
label to its series so dashboards and the federation scraper can
filter it without losing it.  Totals keep stale contributions (a dead
host's counters are its true last-known work; dropping them would make
pod totals dip on every death) — the per-source flags carry the
verdict.
"""

from __future__ import annotations

import math
import os
import socket
import threading
import time

from analytics_zoo_tpu.metrics.registry import MetricsRegistry, get_registry

__all__ = ["telemetry_snapshot", "registry_samples", "merge_samples",
           "samples_to_prometheus", "TelemetryAggregator"]


def registry_samples(registry: MetricsRegistry | None = None) -> list[dict]:
    """One registry's families in the mergeable sample format (the
    ``samples`` list of :func:`telemetry_snapshot`)."""
    reg = registry if registry is not None else get_registry()
    samples = []
    for fam in reg.collect():
        for labels, child in fam.samples():
            s = {"name": fam.name, "kind": fam.kind, "help": fam.help}
            if labels:
                s["labels"] = labels
            if fam.kind == "histogram":
                bkts, h_sum, h_count = child.export_state()
                # +Inf encoded as None: the snapshot crosses JSON
                # boundaries (/varz consumers), where Infinity is not
                # valid strict JSON
                s["buckets"] = [
                    [None if math.isinf(b) else b, cum]
                    for b, cum in bkts]
                s["sum"] = h_sum
                s["count"] = h_count
            else:
                s["value"] = child.get()
            samples.append(s)
    return samples


def telemetry_snapshot(registry: MetricsRegistry | None = None,
                       health=None) -> dict:
    """Full mergeable state of one process: registry + health rollup."""
    if health is None:
        from analytics_zoo_tpu.metrics.health import get_health

        health = get_health()
    return {
        "ts": time.time(),
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "health": health.status(),
        "samples": registry_samples(registry),
    }


def _series_key(sample: dict) -> tuple:
    """(name, sorted orig labels) — the cross-source merge identity."""
    return (sample["name"],
            tuple(sorted((sample.get("labels") or {}).items())))


def _merge_group(samples: list[dict]) -> dict | None:
    """Merge same-series samples from different sources into one total.

    Counters sum; histograms merge bucket-wise (None on bound
    conflict); gauges return None (no meaningful cross-source total).
    """
    kind = samples[0]["kind"]
    out = {k: v for k, v in samples[0].items() if k in
           ("name", "kind", "help", "labels")}
    if kind == "counter":
        out["value"] = sum(s.get("value", 0.0) for s in samples)
        return out
    if kind == "histogram":
        bounds = [tuple(b for b, _ in s["buckets"]) for s in samples]
        if any(b != bounds[0] for b in bounds[1:]):
            return None  # conflicting bounds: per-source series only
        out["buckets"] = [
            [bound, sum(s["buckets"][i][1] for s in samples)]
            for i, (bound, _) in enumerate(samples[0]["buckets"])]
        out["sum"] = sum(s.get("sum", 0.0) for s in samples)
        out["count"] = sum(s.get("count", 0) for s in samples)
        return out
    return None  # gauge


def merge_samples(sample_lists: list[list[dict]]) -> list[dict]:
    """Cluster totals across N sources' sample lists (see module doc)."""
    groups: dict[tuple, list[dict]] = {}
    for samples in sample_lists:
        for s in samples:
            groups.setdefault(_series_key(s), []).append(s)
    out = []
    for key in sorted(groups):
        merged = _merge_group(groups[key])
        if merged is not None:
            out.append(merged)
    return out


class TelemetryAggregator:
    """Driver-side pod view: latest snapshot per source, merged on read.

    ``ingest(snap, host=..., actor=...)`` labels every series from that
    snapshot with the given source labels; the (sorted) label set IS the
    source identity, so a fresh pull from the same actor replaces its
    previous snapshot instead of double-counting it.
    """

    #: default seconds-without-ingest before a source is flagged stale
    DEFAULT_STALE_AFTER = 15.0

    def __init__(self, registry: MetricsRegistry | None = None,
                 stale_after: float | None = None):
        self._registry = registry
        self.stale_after = (float(stale_after) if stale_after is not None
                            else self.DEFAULT_STALE_AFTER)
        self._lock = threading.Lock()
        # key -> (source_labels, snapshot, ingest_time)
        self._sources: dict[tuple, tuple[dict, dict, float]] = {}  # guarded-by: _lock

    def ingest(self, snap: dict, **source_labels) -> tuple:
        if not source_labels:
            raise ValueError(
                "ingest() needs at least one source label (host=/actor=) "
                "— unlabeled snapshots from two sources would collide")
        key = tuple(sorted(
            (k, str(v)) for k, v in source_labels.items()))
        with self._lock:
            self._sources[key] = (dict(key), snap, time.time())
        return key

    def sources(self) -> dict:
        now = time.time()
        with self._lock:
            items = list(self._sources.items())
        return {
            ",".join(f"{k}={v}" for k, v in key): {
                "labels": labels,
                "ts": snap.get("ts"),
                "host": snap.get("host"),
                "pid": snap.get("pid"),
                "healthy": (snap.get("health") or {}).get("healthy"),
                "ingested": ingested,
                "age_seconds": round(now - ingested, 3),
                "stale": (now - ingested) > self.stale_after,
            }
            for key, (labels, snap, ingested) in items
        }

    def stale_sources(self) -> list[str]:
        """Rendered keys of sources past the stale threshold."""
        return [k for k, v in self.sources().items() if v["stale"]]

    def labeled_samples(self) -> list[dict]:
        """Every source's samples with its source labels merged in; a
        source past ``stale_after`` additionally gets ``stale="true"``
        (visible-but-flagged — never evicted)."""
        now = time.time()
        with self._lock:
            items = list(self._sources.values())
        out = []
        for labels, snap, ingested in items:
            stale = (now - ingested) > self.stale_after
            for s in snap.get("samples", []):
                ls = dict(s.get("labels") or {})
                ls.update(labels)
                if stale:
                    ls["stale"] = "true"
                out.append({**s, "labels": ls})
        return out

    def merged(self, include_driver: bool = True) -> dict:
        """The pod-level doc served at ``/varz`` on an aggregating
        driver: per-source labeled series, cluster totals, source and
        health inventory — plus the driver's own registry alongside."""
        with self._lock:
            items = list(self._sources.values())
        doc = {
            "ts": time.time(),
            "sources": self.sources(),
            "samples": self.labeled_samples(),
            "totals": merge_samples(
                [snap.get("samples", []) for _, snap, _ in items]),
        }
        if include_driver:
            reg = (self._registry if self._registry is not None
                   else get_registry())
            doc["driver"] = telemetry_snapshot(reg)
        return doc

    def prometheus_text(self) -> str:
        """Per-source series in exposition format.  NOTE: the
        aggregating driver's ``/metrics`` does NOT concatenate this with
        ``exporters.prometheus_text`` — two renders of a shared family
        name would emit duplicate ``# TYPE`` blocks, which a Prometheus
        parser rejects wholesale; it feeds driver + source samples
        through ONE :func:`samples_to_prometheus` pass instead."""
        return samples_to_prometheus(self.labeled_samples())


def samples_to_prometheus(samples: list[dict]) -> str:
    """Render snapshot-format samples as Prometheus exposition text
    (same sanitization/escaping/collision rules as
    ``exporters.prometheus_text``, which renders live registries).
    Samples sharing a name render as ONE family group with one ``TYPE``
    line, regardless of which source they came from."""
    from analytics_zoo_tpu.metrics.exporters import (
        _fmt,
        _label_str,
        unique_exposition_names,
    )

    by_name: dict[str, list[dict]] = {}
    for s in samples:
        by_name.setdefault(s["name"], []).append(s)
    names = unique_exposition_names(sorted(by_name))
    lines: list[str] = []
    for name in sorted(by_name):
        group = by_name[name]
        pname = names[name]
        if group[0].get("help"):
            lines.append(f"# HELP {pname} {group[0]['help']}")
        lines.append(f"# TYPE {pname} {group[0]['kind']}")
        for s in group:
            labels = s.get("labels") or {}
            if s["kind"] == "histogram":
                for bound, cum in s.get("buckets", []):
                    le = "+Inf" if bound is None else _fmt(float(bound))
                    lines.append(
                        f"{pname}_bucket"
                        f"{_label_str(labels, {'le': le})} {cum}")
                lines.append(
                    f"{pname}_sum{_label_str(labels)}"
                    f" {_fmt(s.get('sum', 0.0))}")
                lines.append(
                    f"{pname}_count{_label_str(labels)}"
                    f" {int(s.get('count', 0))}")
            else:
                lines.append(
                    f"{pname}{_label_str(labels)}"
                    f" {_fmt(s.get('value', 0.0))}")
    return "\n".join(lines) + ("\n" if lines else "")
