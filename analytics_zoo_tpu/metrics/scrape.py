"""VarzScraper — the federation tier's pull loop.

A daemon thread polls a target set (static list, ``ZOO_SCRAPE_TARGETS``,
or a discovery callable over fleet/elastic broker records) and pulls
each host's ``/telemetryz`` (the MERGEABLE snapshot: histogram samples
keep bucket vectors), falling back to ``/varz`` when the target predates
the route — the fallback keeps counters/gauges and drops histogram
summaries, which cannot be merged.  Every successful pull feeds:

- the :class:`~analytics_zoo_tpu.metrics.merge.TelemetryAggregator`
  (current merged values, ``/metrics`` + ``/varz aggregate`` on the
  driver), and
- an optional :class:`~analytics_zoo_tpu.metrics.timeseries.
  TimeSeriesStore` (windowed history — what the federated scaler and
  the SLO engine query), labeled per target.

Failure visibility is the point: per-target staleness gauges and
fetch-error counters (``zoo_scrape_*``), a ``scrape:<target>``
component heartbeat in the local :class:`HealthRegistry` (so the
driver's /healthz goes 503 when any target goes dark past its stale
threshold — the merged verdict), and the aggregator's ``stale``
flagging keep a dead host visible in every rollup instead of silently
vanishing from it.

An attached :class:`~analytics_zoo_tpu.metrics.slo.SloEngine` is
evaluated once per poll cycle — the scraper is the natural tick source
for federation-level SLOs (its own staleness gauge feeds the stock
``worker_heartbeat`` spec).

Locking: ``_lock`` guards only target bookkeeping; it is NEVER held
across an HTTP fetch, a broker call, or an aggregator/store/engine
ingest.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
import weakref

from analytics_zoo_tpu.metrics.health import get_health
from analytics_zoo_tpu.metrics.runtime import ScrapeMetrics

__all__ = ["VarzScraper", "normalize_target", "targets_from_env",
           "fleet_varz_targets", "elastic_varz_targets", "varz_doc",
           "VARZ_KEY_PREFIX"]

# Broker hash key prefix under which processes publish their metrics
# URL for discovery (fleet replicas with --metrics-port, elastic
# workers with ZOO_METRICS_PORT): key = prefix + owner/worker id,
# fields {"url": ..., "ts": ...}.  One key PER process — a shared hash
# would reintroduce the FileBroker read-modify-write race the roster
# redesign removed.
VARZ_KEY_PREFIX = "__zoo-varz-"

_active: "weakref.WeakSet[VarzScraper]" = weakref.WeakSet()
_active_lock = threading.Lock()


def normalize_target(target) -> tuple[str, str]:
    """``(name, base_url)`` from ``host:port``, a full URL, or a
    ``(name, url)`` pair.  Trailing path components (``/varz``) are
    stripped — the scraper owns route selection."""
    if isinstance(target, (tuple, list)) and len(target) == 2:
        name, url = str(target[0]), str(target[1])
    else:
        name = url = str(target).strip()
    if not url.startswith(("http://", "https://")):
        url = "http://" + url
    scheme, rest = url.split("://", 1)
    hostport = rest.split("/", 1)[0]
    base = f"{scheme}://{hostport}"
    if name == url or not name:
        name = hostport
    return name, base


def targets_from_env(env: dict | None = None) -> list[tuple[str, str]]:
    """Parse ``ZOO_SCRAPE_TARGETS`` (comma/space separated
    ``host:port`` or URLs) into normalized pairs."""
    import os

    raw = (env if env is not None else os.environ).get(
        "ZOO_SCRAPE_TARGETS", "")
    out = []
    for part in raw.replace(",", " ").split():
        out.append(normalize_target(part))
    return out


def fleet_varz_targets(broker, prefix: str = VARZ_KEY_PREFIX):
    """Discovery callable over broker-published metrics URLs: every
    process that started a metrics server and registered it under
    ``prefix + <owner>`` (fleet replicas via ``--metrics-port``).
    Returns ``{owner: url}``; tolerant of redis byte values."""
    from analytics_zoo_tpu.elastic.membership import fget

    def discover() -> dict:
        out = {}
        try:
            keys = broker.keys(prefix)
        except Exception:
            return out
        for key in keys:
            k = key.decode() if isinstance(key, bytes) else str(key)
            url = fget(broker.hgetall(k), "url")
            if url:
                out[k[len(prefix):]] = url
        return out

    return discover


def elastic_varz_targets(broker, prefix: str):
    """Discovery callable over elastic membership heartbeats: workers
    that publish a ``varz`` field in their ``hb`` hash (set when
    ``ZOO_METRICS_PORT`` started a server in the worker).  ``prefix``
    is the ledger prefix (``MembershipLedger.prefix``)."""
    from analytics_zoo_tpu.elastic.membership import (
        MembershipLedger,
        fget,
    )

    ledger = MembershipLedger(broker, prefix=prefix)

    def discover() -> dict:
        out = {}
        try:
            members = ledger.members()
        except Exception:
            return out
        for wid in members:
            url = fget(broker.hgetall(ledger.hb_key(wid)), "varz")
            if url:
                out[wid] = url
        return out

    return discover


class _Target:
    __slots__ = ("name", "url", "static", "last_ok", "last_err",
                 "errors", "fetches", "remote_healthy")

    def __init__(self, name: str, url: str, static: bool):
        self.name = name
        self.url = url
        self.static = static
        self.last_ok: float | None = None
        self.last_err: str | None = None
        self.errors = 0
        self.fetches = 0
        self.remote_healthy: bool | None = None


class VarzScraper:
    """Cross-host telemetry poller feeding aggregator + store + SLOs."""

    def __init__(self, targets=(), aggregator=None, store=None,
                 engine=None, interval: float = 1.0,
                 stale_after: float | None = None, timeout: float = 2.0,
                 registry=None, health=None, discover=None,
                 source_label: str = "host", clock=time.time):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self.aggregator = aggregator
        self.store = store
        self.engine = engine
        self.interval = float(interval)
        # default: a target is stale after missing ~3 polls
        self.stale_after = (float(stale_after) if stale_after is not None
                            else 3.0 * self.interval)
        self.timeout = float(timeout)
        self.source_label = source_label
        self.metrics = ScrapeMetrics(registry)
        self._health = health if health is not None else get_health()
        self._discover = discover
        self._clock = clock
        self._lock = threading.Lock()
        self._targets: dict[str, _Target] = {}  # guarded-by: _lock
        self._thread: threading.Thread | None = None  # guarded-by: _lock
        self._stop = threading.Event()  # guarded-by: _lock
        for t in targets:
            self.add_target(t)
        for t in targets_from_env():
            self.add_target(t)
        with _active_lock:
            _active.add(self)

    # -- target set -----------------------------------------------------
    def add_target(self, target, static: bool = True):
        name, url = normalize_target(target)
        with self._lock:
            if name not in self._targets:
                self._targets[name] = _Target(name, url, static)
        # component registration outside our lock (health has its own)
        self._health.register(f"scrape:{name}",
                              stale_after=self.stale_after)

    def remove_target(self, name: str):
        with self._lock:
            self._targets.pop(name, None)
        self._health.unregister(f"scrape:{name}")

    def targets(self) -> list[str]:
        with self._lock:
            return sorted(self._targets)

    def _merge_discovered(self):
        if self._discover is None:
            return
        try:
            found = self._discover()
        except Exception:
            return
        pairs = (found.items() if isinstance(found, dict)
                 else [(None, t) for t in found])
        for name, url in pairs:
            self.add_target((name, url) if name else url, static=False)

    # -- one pull -------------------------------------------------------
    def _fetch(self, base: str) -> dict:
        """GET the mergeable snapshot; fall back to /varz (counters and
        gauges only — summary-format histograms cannot be merged) for
        targets predating the /telemetryz route."""
        try:
            with urllib.request.urlopen(base + "/telemetryz",
                                        timeout=self.timeout) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
        with urllib.request.urlopen(base + "/varz",
                                    timeout=self.timeout) as r:
            doc = json.loads(r.read().decode())
        samples = [s for s in doc.get("samples", ())
                   if s.get("kind") in ("counter", "gauge")]
        return {"ts": doc.get("ts"), "health": doc.get("health"),
                "samples": samples}

    def poll_once(self) -> int:
        """One full cycle: discovery, every target pulled, staleness
        gauges refreshed, attached SLO engine ticked.  Returns the
        number of successful pulls.  Public so tests and synchronous
        callers can drive the scraper without the thread."""
        self._merge_discovered()
        with self._lock:
            targets = list(self._targets.values())
        now = self._clock()
        ok = 0
        for tgt in targets:
            t0 = time.perf_counter()
            try:
                snap = self._fetch(tgt.url)
            except Exception as e:
                tgt.errors += 1
                tgt.last_err = repr(e)
                if self.metrics.enabled:
                    self.metrics.errors.labels(target=tgt.name).inc()
            else:
                ok += 1
                tgt.fetches += 1
                tgt.last_ok = now
                tgt.last_err = None
                health = snap.get("health") or {}
                tgt.remote_healthy = bool(health.get("healthy", True))
                self._ingest(tgt, snap, now)
                if self.metrics.enabled:
                    self.metrics.fetches.labels(target=tgt.name).inc()
                    self.metrics.fetch_seconds.observe(
                        time.perf_counter() - t0)
            self._update_verdict(tgt, now)
        if self.metrics.enabled:
            self.metrics.targets.set(len(targets))
        if self.engine is not None:
            self.engine.evaluate(now=now)
        return ok

    def _ingest(self, tgt: _Target, snap: dict, now: float):
        source = {self.source_label: tgt.name}
        if self.aggregator is not None:
            self.aggregator.ingest(snap, **source)
        if self.store is not None:
            self.store.ingest(snap.get("samples", ()), ts=now,
                              source=source)

    def _update_verdict(self, tgt: _Target, now: float):
        age = (now - tgt.last_ok) if tgt.last_ok is not None \
            else float("inf")
        if self.metrics.enabled:
            self.metrics.staleness.labels(target=tgt.name).set(
                min(age, 1e9))
        # staleness series for the stock worker_heartbeat SLO: the
        # gauge above is per-process registry; the STORE feeds windows
        if self.store is not None:
            self.store.observe(
                "zoo_scrape_staleness_seconds", min(age, 1e9),
                labels={"target": tgt.name}, ts=now)
        comp = f"scrape:{tgt.name}"
        fresh = age <= self.stale_after
        if fresh and tgt.remote_healthy is not False:
            self._health.heartbeat(comp)
        elif fresh and tgt.remote_healthy is False:
            # target answers but reports itself unhealthy: propagate
            self._health.set_status(comp, False)

    # -- merged verdict -------------------------------------------------
    def healthz(self) -> dict:
        """The federation-level health rollup: healthy iff every target
        is fresh AND reports itself healthy."""
        now = self._clock()
        with self._lock:
            targets = list(self._targets.values())
        out = {}
        healthy = True
        for tgt in targets:
            age = (now - tgt.last_ok) if tgt.last_ok is not None \
                else None
            t_ok = (age is not None and age <= self.stale_after
                    and tgt.remote_healthy is not False)
            healthy = healthy and t_ok
            out[tgt.name] = {
                "url": tgt.url, "healthy": t_ok,
                "age_seconds": age, "fetches": tgt.fetches,
                "errors": tgt.errors, "last_error": tgt.last_err,
                "remote_healthy": tgt.remote_healthy,
                "static": tgt.static,
            }
        return {"healthy": healthy and bool(targets), "targets": out}

    def to_doc(self) -> dict:
        doc = self.healthz()
        doc["interval"] = self.interval
        doc["stale_after"] = self.stale_after
        return doc

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "VarzScraper":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="zoo-scrape")
            self._thread.start()
        return self

    def stop(self):
        with self._lock:
            thread = self._thread
            self._thread = None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5)

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                # the poll loop must survive anything a target throws
                pass
            self._stop.wait(self.interval)


def varz_doc() -> list[dict]:
    """Docs for every live scraper — the /varz ``scrape`` panel."""
    with _active_lock:
        scrapers = list(_active)
    return [s.to_doc() for s in scrapers]
