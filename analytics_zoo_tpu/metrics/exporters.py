"""Exporters: Prometheus text exposition, JSONL append, TensorBoard bridge.

One registry snapshot, three render targets:

- :func:`prometheus_text` — the `text exposition format
  <https://prometheus.io/docs/instrumenting/exposition_formats/>`_ a
  scraper (or a human with curl) reads; histograms expose cumulative
  ``_bucket{le=...}`` series plus ``_sum``/``_count``.
- :class:`JsonlExporter` / :func:`write_jsonl` — append one JSON object
  per snapshot to a file; ``tools/metrics_dump.py`` renders these into a
  latency/throughput table.
- :class:`TensorBoardExporter` — bridge into the existing event-file
  writers (:mod:`analytics_zoo_tpu.tensorboard.writer`): every sample
  becomes an ``add_scalar`` so serving/estimator telemetry lands next to
  the Loss/Throughput curves already written there.
"""

from __future__ import annotations

import functools
import json
import math
import os
import re
import time

from analytics_zoo_tpu.metrics.registry import MetricsRegistry, get_registry

__all__ = [
    "prometheus_text", "JsonlExporter", "write_jsonl",
    "TensorBoardExporter", "sample_key",
    "sanitize_metric_name", "sanitize_label_name",
    "unique_exposition_names",
]

# Prometheus charsets: metric names allow colons, label names do not.
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


@functools.lru_cache(maxsize=1024)
def sanitize_metric_name(name: str) -> str:
    """Map an arbitrary registry name onto the Prometheus metric-name
    charset (``[a-zA-Z_:][a-zA-Z0-9_:]*``): dots and other invalid
    characters become underscores; a leading digit gets a ``_`` prefix.
    Valid names pass through unchanged (the common case — cached so the
    exposition hot path pays one dict lookup, not a regex pass)."""
    if _METRIC_NAME_RE.match(name):
        return name
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not out or not re.match(r"[a-zA-Z_:]", out[0]):
        out = "_" + out
    return out


@functools.lru_cache(maxsize=1024)
def sanitize_label_name(name: str) -> str:
    """Label-name variant (``[a-zA-Z_][a-zA-Z0-9_]*`` — no colons)."""
    if _LABEL_NAME_RE.match(name):
        return name
    out = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    if not out or not re.match(r"[a-zA-Z_]", out[0]):
        out = "_" + out
    return out


def unique_exposition_names(names) -> dict:
    """raw family name -> COLLISION-FREE sanitized exposition name.

    Two distinct registry names can sanitize to the same string
    (``zoo.lat.seconds`` vs ``zoo_lat_seconds``); emitting both under
    one name would produce duplicate ``# TYPE`` blocks and make a
    Prometheus parser reject the whole scrape body.  The later name (in
    iteration order) gets a deterministic crc32 suffix instead — stable
    across processes and scrapes, unlike ``hash()``."""
    import zlib

    out: dict = {}
    owner: dict = {}
    for raw in names:
        s = sanitize_metric_name(raw)
        if owner.get(s, raw) != raw:
            s = f"{s}_x{zlib.crc32(raw.encode()) & 0xFFFFFFFF:08x}"
        owner[s] = raw
        out[raw] = s
    return out


def sample_key(sample: dict) -> str:
    """Canonical flat key for one :func:`snapshot` sample —
    ``name`` or ``name{label=value,...}`` — shared by every consumer
    that needs a dict key per labeled series (``tools/metrics_dump.py``,
    ``tools/serving_bench.py``), so the two JSON outputs agree."""
    labels = sample.get("labels")
    if not labels:
        return sample["name"]
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{sample['name']}{{{inner}}}"


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n").replace(
        '"', r'\"')


def _label_str(labels: dict, extra: dict | None = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    # collision-free label names: two raw keys sanitizing to one name
    # ("a.b" and "a_b") would render a duplicate label inside one
    # sample, which the Prometheus parser rejects wholesale — same
    # crc32-suffix rule as unique_exposition_names
    import zlib

    parts = []
    owner: dict = {}
    for k, v in sorted(items.items()):
        name = sanitize_label_name(k)
        if owner.get(name, k) != k:
            name = f"{name}_x{zlib.crc32(k.encode()) & 0xFFFFFFFF:08x}"
        owner[name] = k
        parts.append(f'{name}="{_escape_label(v)}"')
    return "{" + ",".join(parts) + "}"


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def prometheus_text(registry: MetricsRegistry | None = None) -> str:
    """Render a registry snapshot in Prometheus text exposition format."""
    reg = registry if registry is not None else get_registry()
    lines: list[str] = []
    families = reg.collect()
    # registry names are unconstrained (dots are natural for spans); the
    # EXPOSITION must stay inside the Prometheus charset — and stay
    # collision-free after mapping — or the scraper rejects the whole body
    names = unique_exposition_names(f.name for f in families)
    for fam in families:
        name = names[fam.name]
        if fam.help:
            lines.append(f"# HELP {name} {fam.help}")
        lines.append(f"# TYPE {name} {fam.kind}")
        for labels, child in fam.samples():
            if fam.kind == "histogram":
                # one snapshot for buckets AND sum/count: the exposition
                # must satisfy _bucket{le="+Inf"} == _count even with
                # concurrent observes mid-scrape
                bkts, h_sum, h_count = child.export_state()
                for bound, cum in bkts:
                    lines.append(
                        f"{name}_bucket"
                        f"{_label_str(labels, {'le': _fmt(bound)})}"
                        f" {cum}")
                lines.append(
                    f"{name}_sum{_label_str(labels)}"
                    f" {_fmt(h_sum)}")
                lines.append(
                    f"{name}_count{_label_str(labels)} {h_count}")
            else:
                lines.append(
                    f"{name}{_label_str(labels)} {_fmt(child.get())}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot(registry: MetricsRegistry | None = None,
             step: int | None = None) -> dict:
    """One registry snapshot as a plain JSON-able dict — the JSONL line
    shape (also what ``bench.py`` embeds in its result line)."""
    reg = registry if registry is not None else get_registry()
    samples = []
    for fam in reg.collect():
        for labels, child in fam.samples():
            s = {"name": fam.name, "kind": fam.kind}
            if labels:
                s["labels"] = labels
            if fam.kind == "histogram":
                s.update(child.summary())
            else:
                s["value"] = child.get()
            samples.append(s)
    doc = {"ts": time.time(), "samples": samples}
    if step is not None:
        doc["step"] = int(step)
    return doc


class JsonlExporter:
    """Append registry snapshots to a JSONL file (one object per line)."""

    def __init__(self, path: str,
                 registry: MetricsRegistry | None = None):
        self.path = path
        self._registry = registry
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)

    def write(self, step: int | None = None) -> dict:
        doc = snapshot(self._registry, step=step)
        with open(self.path, "a") as f:
            f.write(json.dumps(doc) + "\n")
        return doc


def write_jsonl(path: str, registry: MetricsRegistry | None = None,
                step: int | None = None) -> dict:
    """One-shot :class:`JsonlExporter` append."""
    return JsonlExporter(path, registry).write(step=step)


class TensorBoardExporter:
    """Bridge a registry snapshot into an event-file writer.

    ``writer`` is anything with ``add_scalar(tag, value, step)`` — a
    :class:`~analytics_zoo_tpu.tensorboard.writer.FileWriter` or any of
    the TrainSummary/ValidationSummary/InferenceSummary wrappers.
    Histograms export their summary as ``<name>/p50`` etc. (event files
    carry scalars; the full bucket vector stays in Prometheus/JSONL).
    """

    def __init__(self, writer, registry: MetricsRegistry | None = None):
        self._writer = writer
        self._registry = registry

    def export(self, step: int) -> int:
        """Write every sample at ``step``; returns #scalars written."""
        reg = (self._registry if self._registry is not None
               else get_registry())
        n = 0
        for fam in reg.collect():
            for labels, child in fam.samples():
                tag = fam.name + _label_str(labels)
                if fam.kind == "histogram":
                    for k, v in child.summary().items():
                        self._writer.add_scalar(f"{tag}/{k}", v, step)
                        n += 1
                else:
                    self._writer.add_scalar(tag, child.get(), step)
                    n += 1
        return n
