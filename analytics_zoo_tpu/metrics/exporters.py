"""Exporters: Prometheus text exposition, JSONL append, TensorBoard bridge.

One registry snapshot, three render targets:

- :func:`prometheus_text` — the `text exposition format
  <https://prometheus.io/docs/instrumenting/exposition_formats/>`_ a
  scraper (or a human with curl) reads; histograms expose cumulative
  ``_bucket{le=...}`` series plus ``_sum``/``_count``.
- :class:`JsonlExporter` / :func:`write_jsonl` — append one JSON object
  per snapshot to a file; ``tools/metrics_dump.py`` renders these into a
  latency/throughput table.
- :class:`TensorBoardExporter` — bridge into the existing event-file
  writers (:mod:`analytics_zoo_tpu.tensorboard.writer`): every sample
  becomes an ``add_scalar`` so serving/estimator telemetry lands next to
  the Loss/Throughput curves already written there.
"""

from __future__ import annotations

import json
import math
import os
import time

from analytics_zoo_tpu.metrics.registry import MetricsRegistry, get_registry

__all__ = [
    "prometheus_text", "JsonlExporter", "write_jsonl",
    "TensorBoardExporter", "sample_key",
]


def sample_key(sample: dict) -> str:
    """Canonical flat key for one :func:`snapshot` sample —
    ``name`` or ``name{label=value,...}`` — shared by every consumer
    that needs a dict key per labeled series (``tools/metrics_dump.py``,
    ``tools/serving_bench.py``), so the two JSON outputs agree."""
    labels = sample.get("labels")
    if not labels:
        return sample["name"]
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{sample['name']}{{{inner}}}"


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n").replace(
        '"', r'\"')


def _label_str(labels: dict, extra: dict | None = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(items.items()))
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def prometheus_text(registry: MetricsRegistry | None = None) -> str:
    """Render a registry snapshot in Prometheus text exposition format."""
    reg = registry if registry is not None else get_registry()
    lines: list[str] = []
    for fam in reg.collect():
        if fam.help:
            lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for labels, child in fam.samples():
            if fam.kind == "histogram":
                # one snapshot for buckets AND sum/count: the exposition
                # must satisfy _bucket{le="+Inf"} == _count even with
                # concurrent observes mid-scrape
                bkts, h_sum, h_count = child.export_state()
                for bound, cum in bkts:
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_label_str(labels, {'le': _fmt(bound)})}"
                        f" {cum}")
                lines.append(
                    f"{fam.name}_sum{_label_str(labels)}"
                    f" {_fmt(h_sum)}")
                lines.append(
                    f"{fam.name}_count{_label_str(labels)} {h_count}")
            else:
                lines.append(
                    f"{fam.name}{_label_str(labels)} {_fmt(child.get())}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot(registry: MetricsRegistry | None = None,
             step: int | None = None) -> dict:
    """One registry snapshot as a plain JSON-able dict — the JSONL line
    shape (also what ``bench.py`` embeds in its result line)."""
    reg = registry if registry is not None else get_registry()
    samples = []
    for fam in reg.collect():
        for labels, child in fam.samples():
            s = {"name": fam.name, "kind": fam.kind}
            if labels:
                s["labels"] = labels
            if fam.kind == "histogram":
                s.update(child.summary())
            else:
                s["value"] = child.get()
            samples.append(s)
    doc = {"ts": time.time(), "samples": samples}
    if step is not None:
        doc["step"] = int(step)
    return doc


class JsonlExporter:
    """Append registry snapshots to a JSONL file (one object per line)."""

    def __init__(self, path: str,
                 registry: MetricsRegistry | None = None):
        self.path = path
        self._registry = registry
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)

    def write(self, step: int | None = None) -> dict:
        doc = snapshot(self._registry, step=step)
        with open(self.path, "a") as f:
            f.write(json.dumps(doc) + "\n")
        return doc


def write_jsonl(path: str, registry: MetricsRegistry | None = None,
                step: int | None = None) -> dict:
    """One-shot :class:`JsonlExporter` append."""
    return JsonlExporter(path, registry).write(step=step)


class TensorBoardExporter:
    """Bridge a registry snapshot into an event-file writer.

    ``writer`` is anything with ``add_scalar(tag, value, step)`` — a
    :class:`~analytics_zoo_tpu.tensorboard.writer.FileWriter` or any of
    the TrainSummary/ValidationSummary/InferenceSummary wrappers.
    Histograms export their summary as ``<name>/p50`` etc. (event files
    carry scalars; the full bucket vector stays in Prometheus/JSONL).
    """

    def __init__(self, writer, registry: MetricsRegistry | None = None):
        self._writer = writer
        self._registry = registry

    def export(self, step: int) -> int:
        """Write every sample at ``step``; returns #scalars written."""
        reg = (self._registry if self._registry is not None
               else get_registry())
        n = 0
        for fam in reg.collect():
            for labels, child in fam.samples():
                tag = fam.name + _label_str(labels)
                if fam.kind == "histogram":
                    for k, v in child.summary().items():
                        self._writer.add_scalar(f"{tag}/{k}", v, step)
                        n += 1
                else:
                    self._writer.add_scalar(tag, child.get(), step)
                    n += 1
        return n
