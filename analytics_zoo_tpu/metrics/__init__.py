"""``analytics_zoo_tpu.metrics`` — unified observability subsystem.

One measurement substrate for the whole stack (ISSUE 1): a process-global
:class:`MetricsRegistry` of labeled Counter/Gauge/Histogram families, a
contextvar-nested :func:`span` tracer exporting Chrome-trace JSON, and
exporters for Prometheus text, JSONL, and the in-repo TensorBoard
writers.  Instrumented by default in the estimator fit loop
(`zoo_train_*`), Cluster Serving (`zoo_serving_*`), pooled inference
(`zoo_inference_*`) and the pipeline-parallel schedules
(`zoo_pipeline_*`); disable with ``ZOO_METRICS=0`` / ``ZOO_TRACE=0``
(then every recording call is a shared no-op — zero per-step cost).

See ``docs/observability.md`` for the API tour and metric catalogue.
"""

from analytics_zoo_tpu.metrics.exporters import (
    JsonlExporter,
    TensorBoardExporter,
    prometheus_text,
    sample_key,
    snapshot,
    write_jsonl,
)
from analytics_zoo_tpu.metrics.registry import (
    DEFAULT_BUCKETS,
    NULL,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullMetric,
    get_registry,
    set_registry,
)
from analytics_zoo_tpu.metrics.runtime import (
    ServingMetrics,
    StepMetrics,
    record_device_memory,
)
from analytics_zoo_tpu.metrics.tracing import (
    Tracer,
    get_tracer,
    set_tracer,
    span,
)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "NullMetric",
    "NULL", "DEFAULT_BUCKETS", "get_registry", "set_registry",
    "Tracer", "span", "get_tracer", "set_tracer",
    "prometheus_text", "snapshot", "sample_key", "JsonlExporter",
    "write_jsonl", "TensorBoardExporter",
    "StepMetrics", "ServingMetrics", "record_device_memory",
]
