"""``analytics_zoo_tpu.metrics`` — unified observability subsystem.

One measurement substrate for the whole stack (ISSUE 1): a process-global
:class:`MetricsRegistry` of labeled Counter/Gauge/Histogram families, a
contextvar-nested :func:`span` tracer exporting Chrome-trace JSON, and
exporters for Prometheus text, JSONL, and the in-repo TensorBoard
writers.  Instrumented by default in the estimator fit loop
(`zoo_train_*`), Cluster Serving (`zoo_serving_*`), pooled inference
(`zoo_inference_*`) and the pipeline-parallel schedules
(`zoo_pipeline_*`); disable with ``ZOO_METRICS=0`` / ``ZOO_TRACE=0``
(then every recording call is a shared no-op — zero per-step cost).

The distributed plane (ISSUE 2): :class:`MetricsServer` serves
``/metrics`` ``/varz`` ``/trace`` ``/healthz`` ``/flightz`` over HTTP
(opt-in via ``ZOO_METRICS_PORT``); :mod:`merge` defines the mergeable
cross-process snapshot format and the driver-side
:class:`TelemetryAggregator`; :mod:`health` is the component-heartbeat
registry behind ``/healthz``; :mod:`flight` is the bounded crash flight
recorder dumped to ``ZOO_FLIGHT_DIR`` on exit/SIGTERM/crash.  Remote
actor and worker processes ship snapshots to the driver over the
``__zoo_telemetry__`` control frame (``ActorContext.metrics()``).

The federation plane (ISSUE 17): :class:`VarzScraper` pulls every
host's ``/telemetryz`` into a :class:`TelemetryAggregator` + a
:class:`TimeSeriesStore` of windowed history, and an :class:`SloEngine`
evaluates declarative :class:`SloSpec` objectives into multi-window
burn-rate alerts served at ``/alertz`` — the layer the federated
``SloScaler`` and the elastic supervisor's heartbeat verdicts read.

See ``docs/observability.md`` for the API tour and metric catalogue.
"""

from analytics_zoo_tpu.metrics.exporters import (
    JsonlExporter,
    TensorBoardExporter,
    prometheus_text,
    sample_key,
    sanitize_label_name,
    sanitize_metric_name,
    snapshot,
    write_jsonl,
)
from analytics_zoo_tpu.metrics.flight import (
    FlightRecorder,
    StragglerBoard,
    StragglerDetector,
    get_flight_recorder,
    register_predump_hook,
    set_flight_recorder,
)
from analytics_zoo_tpu.metrics.health import (
    HealthRegistry,
    get_health,
    set_health,
)
from analytics_zoo_tpu.metrics.http import (
    MetricsServer,
    maybe_start_from_env,
)
from analytics_zoo_tpu.metrics.merge import (
    TelemetryAggregator,
    merge_samples,
    telemetry_snapshot,
)
from analytics_zoo_tpu.metrics.registry import (
    DEFAULT_BUCKETS,
    NULL,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullMetric,
    get_registry,
    set_registry,
)
from analytics_zoo_tpu.metrics.runtime import (
    AdmissionMetrics,
    AutotuneMetrics,
    DataPipelineMetrics,
    ElasticMetrics,
    FleetMetrics,
    OracleMetrics,
    RouterMetrics,
    ScrapeMetrics,
    ServingMetrics,
    SloMetrics,
    StepMetrics,
    record_device_memory,
)
from analytics_zoo_tpu.metrics.scrape import (
    VarzScraper,
    fleet_varz_targets,
)
from analytics_zoo_tpu.metrics.slo import (
    SloEngine,
    SloSpec,
    default_slos,
)
from analytics_zoo_tpu.metrics.timeseries import (
    TimeSeriesStore,
)
from analytics_zoo_tpu.metrics.tracing import (
    Tracer,
    get_tracer,
    set_tracer,
    span,
)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "NullMetric",
    "NULL", "DEFAULT_BUCKETS", "get_registry", "set_registry",
    "Tracer", "span", "get_tracer", "set_tracer",
    "prometheus_text", "snapshot", "sample_key", "JsonlExporter",
    "write_jsonl", "TensorBoardExporter",
    "sanitize_metric_name", "sanitize_label_name",
    "StepMetrics", "ServingMetrics", "DataPipelineMetrics",
    "AutotuneMetrics", "FleetMetrics", "OracleMetrics",
    "ElasticMetrics", "ScrapeMetrics", "SloMetrics",
    "RouterMetrics", "AdmissionMetrics",
    "record_device_memory",
    "TimeSeriesStore", "SloSpec", "SloEngine", "default_slos",
    "VarzScraper", "fleet_varz_targets",
    "MetricsServer", "maybe_start_from_env",
    "TelemetryAggregator", "telemetry_snapshot", "merge_samples",
    "HealthRegistry", "get_health", "set_health",
    "FlightRecorder", "StragglerDetector", "StragglerBoard",
    "get_flight_recorder", "set_flight_recorder",
    "register_predump_hook",
]
