"""HTTP scrape endpoints — the process's telemetry served over stdlib HTTP.

:class:`MetricsServer` is a ``ThreadingHTTPServer`` on a daemon thread
exposing the observability subsystem to scrapers, load balancers and
humans with curl:

====================  ====================================================
``/metrics``          Prometheus text exposition (``exporters.
                      prometheus_text``); on an aggregating driver the
                      pod's per-source series are appended after the
                      local registry's.
``/varz``             one JSON registry snapshot (the JSONL line shape),
                      plus health, tracer-drop and flight counters —
                      ``tools/metrics_dump.py --url`` renders it.
``/trace``            Chrome-trace JSON from the Tracer ring (load in
                      ``chrome://tracing`` / Perfetto).
``/healthz``          200 when every registered component heartbeat is
                      fresh, 503 with the stale components otherwise
                      (health.py rollup) — the readiness-probe contract.
``/flightz``          the flight recorder ring as JSON (flight.py).
``/telemetryz``       the MERGEABLE snapshot (``merge.telemetry_
                      snapshot``): histogram samples keep their bucket
                      vectors, so a federation scraper (metrics/
                      scrape.py) can ingest them into a
                      ``TelemetryAggregator``/``TimeSeriesStore`` —
                      /varz histograms are lossy summaries.
``/alertz``           SLO burn-rate alert state across every live
                      ``SloEngine`` (metrics/slo.py): firing + latest
                      verdict per spec.  Empty doc when no engine runs.
====================  ====================================================

``port=0`` binds an ephemeral port (tests read :attr:`MetricsServer.port`
after :meth:`start`); :meth:`stop` shuts the listener down cleanly.
Opt-in from production entry points is one env var::

    ZOO_METRICS_PORT=9090 python serve.py      # ClusterServing.run()
    ZOO_METRICS_PORT=9090 python train.py      # estimator fit loop

both call :func:`maybe_start_from_env`, which starts ONE server per
process (idempotent) and leaves the process untouched when the var is
unset.  The bind address defaults to **127.0.0.1** — the same
loopback-first posture as the actor-worker transport: the body is
read-only telemetry (no pickle, no RCE), but ``/flightz`` carries
exception messages and traceback tails, so exposing it off-host is an
explicit ``ZOO_METRICS_HOST=0.0.0.0`` decision (node-exporter-style
scraping across a pod), not a silent default.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from analytics_zoo_tpu.metrics.exporters import prometheus_text, snapshot
from analytics_zoo_tpu.metrics.registry import MetricsRegistry

__all__ = ["MetricsServer", "maybe_start_from_env"]


class _Handler(BaseHTTPRequestHandler):
    # set per-server via type(); BaseHTTPRequestHandler instantiates one
    # handler per request
    server_ref: "MetricsServer" = None  # type: ignore[assignment]

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler contract)
        try:
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            route = self.server_ref._routes.get(path)
            if route is None:
                self._reply(404, "application/json", json.dumps(
                    {"error": "not found",
                     "endpoints": sorted(self.server_ref._routes)}))
                return
            status, ctype, body = route()
            self._reply(status, ctype, body)
        except BrokenPipeError:
            pass
        except Exception as e:  # a scrape must never kill the process
            try:
                self._reply(500, "application/json",
                            json.dumps({"error": repr(e)}))
            except Exception:
                pass

    def _reply(self, status: int, ctype: str, body: str):
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):
        pass  # scrapes every few seconds must not spam stderr


class MetricsServer:
    """Serve this process's registry/tracer/health/flight over HTTP."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: MetricsRegistry | None = None, tracer=None,
                 health=None, flight=None, aggregator=None):
        self._want_port = int(port)
        self._host = host
        self._registry = registry
        self._tracer = tracer
        self._health = health
        self._flight = flight
        self.aggregator = aggregator
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._routes = {
            "/metrics": self._metrics,
            "/varz": self._varz,
            "/trace": self._trace,
            "/healthz": self._healthz,
            "/flightz": self._flightz,
            "/telemetryz": self._telemetryz,
            "/alertz": self._alertz,
            "/": self._index,
        }

    # -- lazy component resolution (the process-global defaults are
    # created on first use; a server built before them must serve them)
    def _reg(self):
        if self._registry is not None:
            return self._registry
        from analytics_zoo_tpu.metrics.registry import get_registry

        return get_registry()

    def _trc(self):
        if self._tracer is not None:
            return self._tracer
        from analytics_zoo_tpu.metrics.tracing import get_tracer

        return get_tracer()

    def _hlt(self):
        if self._health is not None:
            return self._health
        from analytics_zoo_tpu.metrics.health import get_health

        return get_health()

    def _flt(self):
        if self._flight is not None:
            return self._flight
        from analytics_zoo_tpu.metrics.flight import get_flight_recorder

        return get_flight_recorder()

    # -- endpoints ------------------------------------------------------
    def _index(self):
        return 200, "application/json", json.dumps(
            {"endpoints": sorted(p for p in self._routes if p != "/")})

    def _metrics(self):
        if self.aggregator is None:
            text = prometheus_text(self._reg())
        else:
            # driver + per-source series through ONE renderer: a family
            # name present on both sides must produce ONE group with ONE
            # TYPE line, or the scraper rejects the whole body
            from analytics_zoo_tpu.metrics.merge import (
                registry_samples,
                samples_to_prometheus,
            )

            text = samples_to_prometheus(
                registry_samples(self._reg())
                + self.aggregator.labeled_samples())
        return 200, "text/plain; version=0.0.4", text

    def _varz(self):
        tracer = self._trc()
        doc = snapshot(self._reg())
        doc["health"] = self._hlt().status()
        doc["trace"] = {"dropped_spans": tracer.dropped,
                        "max_events": tracer.max_events}
        flight = self._flt()
        doc["flight"] = {"events": len(flight.events()),
                         "dropped": flight.dropped}
        # Autotune decision log (feature/autotune.py): consult
        # sys.modules only — a process that never turned the controller
        # on must not import the feature package from a scrape.
        import sys

        auto = sys.modules.get("analytics_zoo_tpu.feature.autotune")
        if auto is not None:
            doc["autotune"] = auto.varz_doc()
        # Fleet panel (serving/fleet.py): replica/scaler state + scale
        # decision log — same sys.modules-only contract.
        fleet = sys.modules.get("analytics_zoo_tpu.serving.fleet")
        if fleet is not None:
            doc["fleet"] = fleet.varz_doc()
        # Router panel (serving/router.py): per-model fleet state +
        # the prime/scale/stop decision log — same contract.
        router = sys.modules.get("analytics_zoo_tpu.serving.router")
        if router is not None:
            doc["router"] = router.varz_doc()
        # Admission panel (serving/admission.py): per-stream verdicts +
        # the accept/shed transition log — same contract.
        admission = sys.modules.get(
            "analytics_zoo_tpu.serving.admission")
        if admission is not None:
            doc["admission"] = admission.varz_doc()
        # Oracle panel (analysis/oracle.py): peak table, residual-fit
        # size and the predicted-vs-measured pairs per config.
        oracle = sys.modules.get("analytics_zoo_tpu.analysis.oracle")
        if oracle is not None:
            doc["oracle"] = oracle.varz_doc()
        # Elastic panel (elastic/supervisor.py): generation/world/member
        # state + the rejoin decision log — same sys.modules-only
        # contract.
        elastic = sys.modules.get("analytics_zoo_tpu.elastic.supervisor")
        if elastic is not None:
            doc["elastic"] = elastic.varz_doc()
        # SLO panel (metrics/slo.py): specs + alert state + the
        # firing/resolved decision log — same sys.modules-only contract.
        slo = sys.modules.get("analytics_zoo_tpu.metrics.slo")
        if slo is not None:
            doc["slo"] = slo.varz_doc()
        # Scraper panel (metrics/scrape.py): per-target fetch/staleness.
        scrape = sys.modules.get("analytics_zoo_tpu.metrics.scrape")
        if scrape is not None:
            doc["scrape"] = scrape.varz_doc()
        if self.aggregator is not None:
            agg = self.aggregator.merged(include_driver=False)
            doc["aggregate"] = {"sources": agg["sources"],
                                "totals": agg["totals"]}
        return 200, "application/json", json.dumps(doc)

    def _trace(self):
        return 200, "application/json", json.dumps(
            self._trc().to_chrome_trace())

    def _healthz(self):
        status = self._hlt().status()
        code = 200 if status["healthy"] else 503
        return code, "application/json", json.dumps(status)

    def _flightz(self):
        return 200, "application/json", json.dumps(
            self._flt().to_doc(reason="live"))

    def _telemetryz(self):
        from analytics_zoo_tpu.metrics.merge import telemetry_snapshot

        return 200, "application/json", json.dumps(
            telemetry_snapshot(self._reg(), health=self._hlt()))

    def _alertz(self):
        # sys.modules-only, like the /varz panels: serving /alertz on a
        # process with no SLO engine must not import the module.
        import sys
        import time

        slo = sys.modules.get("analytics_zoo_tpu.metrics.slo")
        if slo is None:
            doc = {"ts": time.time(), "engines": 0, "firing": [],
                   "alerts": []}
        else:
            doc = slo.alertz_doc()
        return 200, "application/json", json.dumps(doc)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        handler = type("BoundHandler", (_Handler,), {"server_ref": self})
        self._httpd = ThreadingHTTPServer(
            (self._host, self._want_port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="zoo-metrics-http")
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        """The BOUND port (resolves ``port=0`` after :meth:`start`)."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._want_port

    @property
    def url(self) -> str:
        host = "127.0.0.1" if self._host in ("0.0.0.0", "") else self._host
        return f"http://{host}:{self.port}"

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd, self._thread = None, None


# ---------------------------------------------------------------------------
# Env opt-in: one process-wide server, started by whichever production
# loop (serving, fit) reaches it first.
# ---------------------------------------------------------------------------

_env_server: MetricsServer | None = None  # guarded-by: _env_lock
_env_lock = threading.Lock()


def maybe_start_from_env(aggregator=None) -> MetricsServer | None:
    """Start the process's scrape server iff ``ZOO_METRICS_PORT`` is set
    (idempotent — later callers get the same instance; an ``aggregator``
    passed by a later caller is attached if none was).  Returns None when
    the env does not opt in or the port cannot be bound (a telemetry
    endpoint must never take the training/serving loop down)."""
    import logging
    import os

    global _env_server
    port = os.environ.get("ZOO_METRICS_PORT")
    if not port:
        return None
    with _env_lock:
        if _env_server is not None:
            if aggregator is not None and _env_server.aggregator is None:
                _env_server.aggregator = aggregator
            return _env_server
        try:
            srv = MetricsServer(
                port=int(port),
                host=os.environ.get("ZOO_METRICS_HOST", "127.0.0.1"),
                aggregator=aggregator).start()
        except (OSError, ValueError) as e:
            logging.getLogger("analytics_zoo_tpu").warning(
                "metrics server not started (ZOO_METRICS_PORT=%s): %s",
                port, e)
            return None
        _env_server = srv
        logging.getLogger("analytics_zoo_tpu").info(
            "metrics server on %s (/metrics /varz /trace /healthz "
            "/flightz /telemetryz /alertz)", srv.url)
        return srv
