"""``python -m analytics_zoo_tpu.elastic --worker ...``: one elastic
training worker (see supervisor._worker_main)."""

import sys

from .supervisor import _worker_main

if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(_worker_main(sys.argv[1:]))
