"""zooelastic supervisor: unattended pod-scale ``fit()``.

:class:`TrainSupervisor` treats training workers the way
:class:`~analytics_zoo_tpu.serving.fleet.FleetController` treats serving
replicas: it spawns N worker subprocesses (``python -m
analytics_zoo_tpu.elastic --worker`` against a cross-process broker
spec), respawns the dead, and coordinates everything else through the
membership ledger (elastic/membership.py) — no supervisor->worker RPC,
just broker hashes.

On any generation change (a worker died, a respawn rejoined) the
supervisor orchestrates the rejoin:

1. survivors yield at the next step barrier — the estimator
   safe-snapshots via the async checkpointer and raises
   :class:`~analytics_zoo_tpu.elastic.membership.GenerationChange`;
2. the config oracle re-picks ``(plan, K, remat)`` for the NEW world
   size with :meth:`~analytics_zoo_tpu.analysis.oracle.ConfigOracle.
   repick` — exactly once per generation, never a blind re-tune, and the
   round's measured throughput is fed back as the prediction's outcome;
3. the new cohort resumes from ``LATEST`` at the new world size through
   the partitioner's bit-exact resharding (the chief — lowest live
   worker id — runs the SPMD fit on a mesh refolded by
   :func:`~analytics_zoo_tpu.parallel.plan.fold_world_to_mesh`; the
   other members heartbeat as hot spares).

A :class:`~analytics_zoo_tpu.metrics.flight.StragglerBoard` over the
worker heartbeats drives micro-batch rebalancing: a slow worker's share
of the global batch shrinks by :func:`rebalance_shares` (the delta goes
to the fast workers, the global batch — and with it the RNG-folded
trajectory — is preserved exactly).

Every decision lands three ways (the fleet/autotune convention): the
``zoo_elastic_*`` metric family, an ``elastic`` flight-recorder event,
and a bounded structured decision log served in the ``elastic`` section
of ``/varz`` (rendered by ``tools/metrics_dump.py``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import pickle
import signal
import subprocess
import sys
import threading
import time
import weakref
from collections import deque

from ..analysis.oracle import ConfigOracle
from ..metrics import (
    ElasticMetrics,
    SloEngine,
    SloSpec,
    StragglerBoard,
    TimeSeriesStore,
    get_flight_recorder,
)
from ..parallel.plan import fold_world_to_mesh
from .chaos import ChaosSchedule
from .membership import (
    DEFAULT_PREFIX,
    ElasticSession,
    GenerationChange,
    MembershipLedger,
    fget,
)

__all__ = ["TrainSupervisor", "equal_shares", "rebalance_shares",
           "varz_doc"]

logger = logging.getLogger("analytics_zoo_tpu")

# ---------------------------------------------------------------------------
# Live-supervisor registry for /varz (metrics/http.py consults
# sys.modules only — a scrape-only process never imports this module).
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active: "weakref.WeakSet[TrainSupervisor]" = (  # guarded-by: _active_lock
    weakref.WeakSet())


def varz_doc() -> dict:
    """The ``elastic`` section of ``/varz``: every live supervisor's
    generation/membership state plus the merged decision log."""
    with _active_lock:
        sups = list(_active)
    docs = [s.to_doc() for s in sups]
    decisions = sorted((d for doc in docs for d in doc["decisions"]),
                       key=lambda d: d["ts"])
    return {"supervisors": docs, "decisions": decisions}


# ---------------------------------------------------------------------------
# Share arithmetic (pure — unit-tested directly)
# ---------------------------------------------------------------------------


def equal_shares(total: int, members) -> dict:
    """Split ``total`` micro-batch records evenly over ``members``
    (largest-remainder); always sums to ``total`` exactly."""
    wids = sorted(members)
    if not wids:
        return {}
    q, r = divmod(int(total), len(wids))
    return {w: q + (1 if i < r else 0) for i, w in enumerate(wids)}


def rebalance_shares(shares: dict, factors: dict,
                     min_share: int = 1) -> dict:
    """Shrink slow workers' micro-batch shares, grow fast workers'.

    ``factors`` are per-worker slowdowns from
    :meth:`~analytics_zoo_tpu.metrics.flight.StragglerBoard.factors`
    (1.0 = fleet median).  Each worker's weight is ``share / factor`` —
    capacity proportional to observed speed — and the GLOBAL batch
    ``sum(shares)`` is preserved EXACTLY via largest-remainder rounding,
    so the optimizer trajectory sees the same batches in the same order;
    only who computes which slice changes."""
    total = sum(int(v) for v in shares.values())
    n = len(shares)
    if n == 0 or total < min_share * n:
        return dict(shares)
    weights = {w: int(s) / max(float(factors.get(w, 1.0)), 1e-9)
               for w, s in shares.items()}
    wsum = sum(weights.values())
    if wsum <= 0:
        return dict(shares)
    spread = total - min_share * n
    exact = {w: spread * weights[w] / wsum for w in shares}
    out = {w: min_share + int(exact[w]) for w in shares}
    leftover = total - sum(out.values())
    order = sorted(shares, key=lambda w: (exact[w] - int(exact[w]), w),
                   reverse=True)
    for w in order[:leftover]:
        out[w] += 1
    return out


def _spec_param_bytes(spec: dict) -> int:
    """float32 parameter bytes of the worker's two-Dense synthetic model
    (the oracle repick's size input when no measured bytes exist)."""
    i = int(spec.get("in_dim", 8))
    h = int(spec.get("hidden", 16))
    c = int(spec.get("classes", 4))
    return 4 * (i * h + h + h * c + c)


def _peek_latest(ckpt_dir: str) -> dict | None:
    """Read (global_step, epoch) straight off the LATEST snapshot
    without touching jax — the supervisor's steps-lost accounting and
    the chief's resume-offset both use it."""
    try:
        with open(os.path.join(ckpt_dir, "LATEST")) as f:
            name = f.read().strip()
        with open(os.path.join(ckpt_dir, name), "rb") as f:
            payload = pickle.load(f)
        return {"global_step": int(payload["global_step"]),
                "epoch": int(payload["epoch"])}
    except (OSError, KeyError, ValueError, pickle.UnpicklingError,
            EOFError):
        return None


# ---------------------------------------------------------------------------
# Worker handle
# ---------------------------------------------------------------------------


class _WorkerProc:
    """One worker subprocess.  SIGTERM asks for the graceful leave (the
    flight recorder dumps — with the async checkpointer flushed by the
    pre-dump hook — then the worker's chained handler releases its
    membership slot); SIGKILL after a grace period.  An external
    ``kill -9`` is exactly the lease-expiry story."""

    def __init__(self, wid: str, proc: subprocess.Popen):
        self.wid = wid
        self.proc = proc

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self, grace_s: float = 10.0) -> None:
        if self.proc.poll() is not None:
            return
        self.proc.terminate()
        try:
            self.proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5.0)


# ---------------------------------------------------------------------------
# The supervisor
# ---------------------------------------------------------------------------


class TrainSupervisor:
    """Supervise N elastic training workers over one broker.

    ``spec`` describes the training job the cohort runs (see
    ``DEFAULT_SPEC``); it travels to the workers inside the assignment
    doc, so a worker needs nothing but the broker spec and its id.  The
    supervisor never holds its lock across broker or process calls (the
    fleet controller's lock-order hygiene)."""

    DEFAULT_SPEC = {
        "seed": 3, "n": 256, "in_dim": 8, "hidden": 16, "classes": 4,
        "batch_size": 32, "nb_epoch": 4, "plan": "fsdp", "k": 1,
        "poll_s": 0.02, "hb_s": 0.05, "devices": None,
    }

    def __init__(self, broker_spec, spec: dict, workers: int = 4,
                 prefix: str = DEFAULT_PREFIX,
                 lease_ms: int | None = None,
                 min_workers: int | None = None,
                 grace_ms: int | None = None,
                 interval: float = 0.1,
                 chaos: ChaosSchedule | None = None,
                 oracle: ConfigOracle | None = None,
                 registry=None, log_capacity: int = 256,
                 straggler_factor: float = 1.5,
                 rebalance_cooldown_s: float = 2.0,
                 respawn_delay_s: float = 0.0,
                 cohort_wait_s: float = 20.0,
                 worker_env: dict | None = None,
                 hb_slo: SloSpec | None = None,
                 hb_slo_kill: bool = True):
        if not isinstance(broker_spec, str):
            raise ValueError(
                "TrainSupervisor needs a cross-process broker spec "
                "(dir:<spool> or host:port) its subprocess workers can "
                "re-connect from, not a live broker object")
        if "ckpt_dir" not in spec:
            raise ValueError("spec needs a ckpt_dir (the durable resume "
                             "point every rejoin starts from)")
        env = os.environ
        self.broker_spec = broker_spec
        self.spec = dict(self.DEFAULT_SPEC, **spec)
        self.workers = int(workers)
        self.prefix = prefix
        self.lease_ms = int(lease_ms if lease_ms is not None
                            else env.get("ZOO_ELASTIC_LEASE_MS", "3000"))
        self.min_workers = int(
            min_workers if min_workers is not None
            else env.get("ZOO_ELASTIC_MIN_WORKERS", "1"))
        self.grace_ms = int(grace_ms if grace_ms is not None
                            else env.get("ZOO_ELASTIC_GRACE_MS", "5000"))
        self.interval = float(interval)
        self.chaos = chaos
        self.oracle = oracle if oracle is not None else ConfigOracle()
        self.straggler_factor = float(straggler_factor)
        self.rebalance_cooldown_s = float(rebalance_cooldown_s)
        self.respawn_delay_s = float(respawn_delay_s)
        self.cohort_wait_s = float(cohort_wait_s)
        self.worker_env = dict(worker_env or {})
        self.ledger = MembershipLedger(broker_spec, prefix=prefix,
                                       lease_ms=self.lease_ms)
        self.metrics = ElasticMetrics(registry=registry)
        self.board = StragglerBoard(window=64, min_steps=3)
        self._flight = get_flight_recorder()
        # Heartbeat SLO (ISSUE 17): per-worker hb AGE series feed a
        # private burn-rate engine.  The lease detects a dead
        # keepalive; this detects the inverse failure — a worker whose
        # lease keepalive thread lives while the training loop is
        # wedged (hb hash stops moving).  A firing alert on a SPARE is
        # actionable (SIGTERM -> normal respawn path); the chief only
        # gets a logged verdict — its first heartbeat legitimately
        # waits out compilation.
        hb_thr = max(0.5, self.lease_ms / 1e3)
        self.hb_slo = hb_slo if hb_slo is not None else SloSpec(
            "worker_heartbeat", "zoo_elastic_hb_age_seconds",
            threshold=hb_thr, objective=0.5, kind="ceiling",
            short_window=4.0 * hb_thr, long_window=8.0 * hb_thr,
            description="per-worker heartbeat freshness "
                        "(wedged-worker detector)")
        self.hb_slo_kill = bool(hb_slo_kill)
        self._hb_store = TimeSeriesStore(capacity=256)
        self._hb_engine = SloEngine(self._hb_store, registry=registry)

        self._lock = threading.Lock()
        self._procs: dict = {}  # guarded-by: _lock
        self._decisions: deque = (  # guarded-by: _lock
            deque(maxlen=int(log_capacity)))
        self._repicks: list = []  # guarded-by: _lock
        self._thread: threading.Thread | None = None  # guarded-by: _lock
        self._stop_evt = threading.Event()
        self._last_doc: dict | None = None  # guarded-by: _lock
        self._assignment: dict | None = None  # guarded-by: _lock
        self._pending_rejoin: dict | None = None  # guarded-by: _lock
        self._result: dict | None = None  # guarded-by: _lock
        self._outcomes_fed = 0  # guarded-by: _lock
        self._respawn_at: dict = {}  # guarded-by: _lock
        self._hb_seen: dict = {}  # guarded-by: _lock
        self._hb_alerted: dict = {}  # guarded-by: _lock
        self._last_rebalance = 0.0  # guarded-by: _lock
        self._t0 = time.monotonic()
        with _active_lock:
            _active.add(self)

    @classmethod
    def from_config(cls, cfg, broker_spec, spec, **kwargs):
        """Build from a :class:`~analytics_zoo_tpu.common.engine.
        ZooConfig` (the eagerly-validated ``ZOO_ELASTIC_*`` env tier)."""
        kwargs.setdefault("lease_ms", cfg.elastic_lease_ms)
        kwargs.setdefault("min_workers", cfg.elastic_min_workers)
        kwargs.setdefault("grace_ms", cfg.elastic_grace_ms)
        return cls(broker_spec, spec, **kwargs)

    # ------------------------------------------------------------------
    # worker lifecycle
    # ------------------------------------------------------------------
    def _spawn(self, wid: str) -> _WorkerProc:
        cmd = [sys.executable, "-m", "analytics_zoo_tpu.elastic",
               "--worker", "--broker", self.broker_spec,
               "--id", wid, "--prefix", self.prefix,
               "--lease-ms", str(self.lease_ms)]
        env = dict(os.environ)
        # workers must import THIS package regardless of the
        # supervisor's cwd or an uninstalled source tree
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        env.update(self.worker_env)
        proc = _WorkerProc(wid, subprocess.Popen(cmd, env=env))
        with self._lock:
            self._procs[wid] = proc
        self.metrics.respawns.inc()
        return proc

    def worker_ids(self) -> list:
        return [f"w{i}" for i in range(self.workers)]

    def pids(self) -> dict:
        with self._lock:
            return {wid: p.pid for wid, p in self._procs.items()}

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "TrainSupervisor":
        for wid in self.worker_ids():
            with self._lock:
                have = wid in self._procs
            if not have:
                self._spawn(wid)
        self._stop_evt.clear()
        self._t0 = time.monotonic()  # cohort_wait_s runs from START
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="zoo-elastic")
            t = self._thread
        t.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        while True:
            with self._lock:
                wid, proc = (self._procs.popitem() if self._procs
                             else (None, None))
            if proc is None:
                break
            proc.stop(grace_s=max(1.0, self.grace_ms / 1e3))

    def run(self, timeout_s: float = 120.0) -> dict | None:
        """Start, block until the cohort posts its round result (the
        chief finished the full ``nb_epoch`` target) or ``timeout_s``,
        stop, and return the result doc (None on timeout).  The
        unattended entry the bench and the acceptance test drive."""
        self.start()
        deadline = time.monotonic() + float(timeout_s)
        try:
            while time.monotonic() < deadline:
                if self.result() is not None:
                    # close the loop ourselves: the control thread may
                    # be stopped before its next tick would harvest
                    self._harvest_result()
                    break
                time.sleep(self.interval)
        finally:
            self.stop()
        return self.result()

    def result(self) -> dict | None:
        with self._lock:
            if self._result is not None:
                return dict(self._result)
        raw = fget(self.ledger.broker.hgetall(self.ledger.result_key),
                   "doc")
        doc = json.loads(raw) if raw else None
        if doc is not None and doc.get("done"):
            with self._lock:
                self._result = doc
            return dict(doc)
        return None

    def _run(self):
        while not self._stop_evt.wait(self.interval):
            try:
                self._tick()
            except Exception as e:
                # the supervisor must never take the cohort down; a
                # policy bug shows in the flight ring, not a crash
                self._flight.record_exception(e, where="elastic")

    # ------------------------------------------------------------------
    # one control window
    # ------------------------------------------------------------------
    def _tick(self):
        with self._lock:
            finished = bool(self._outcomes_fed)
        if finished:
            # the round is complete and its outcome fed back — stop
            # orchestrating the dissolving cohort (run() is about to
            # stop us anyway)
            return
        self._supervise()
        self._fire_chaos()
        doc, changed = self.ledger.scan()
        if changed:
            self._on_generation(doc)
        self._observe_rejoin(doc)
        self._feed_straggler(doc)
        self._check_heartbeat_slo(doc)
        self._harvest_result()

    def _supervise(self):
        """Drop dead workers and respawn them into their old slot (the
        respawn re-claims the slot stream the moment the dead lease
        expires — membership heals without identity churn)."""
        now = time.monotonic()
        with self._lock:
            dead = [(wid, p) for wid, p in self._procs.items()
                    if not p.alive()]
            for wid, _ in dead:
                del self._procs[wid]
                self._respawn_at.setdefault(
                    wid, now + self.respawn_delay_s)
            due = [wid for wid, t in self._respawn_at.items() if t <= now]
        for wid, p in dead:
            self.metrics.worker_deaths.inc()
            self._record_decision("death", "process_exit", worker=wid,
                                  pid=p.pid)
        for wid in due:
            if self._stop_evt.is_set():
                return
            with self._lock:
                self._respawn_at.pop(wid, None)
            self._spawn(wid)
            self._record_decision("respawn", "supervision", worker=wid)

    def _chief_step(self) -> int:
        with self._lock:
            assign = self._assignment
        if not assign:
            return 0
        hb = self.ledger.broker.hgetall(
            self.ledger.hb_key(assign["chief"]))
        try:
            return int(fget(hb, "step", 0))
        except (TypeError, ValueError):
            return 0

    def _fire_chaos(self):
        if self.chaos is None or self.chaos.done():
            return
        step = self._chief_step()
        for ev in self.chaos.due(step):
            ev.fired = True
            with self._lock:
                proc = self._procs.get(ev.target)
            if ev.action == "stall":
                self.ledger.broker.hset(
                    self.ledger.ctl_key(ev.target),
                    {"stall_s": str(ev.arg)})
            elif proc is not None and proc.alive():
                sig = (signal.SIGKILL if ev.action == "kill"
                       else signal.SIGTERM)
                os.kill(proc.pid, sig)
            self._record_decision(
                "chaos", ev.action, worker=ev.target, at_step=ev.at_step,
                fired_step=step, arg=ev.arg)

    def _on_generation(self, doc: dict):
        gen, world = int(doc["generation"]), int(doc["world"])
        members = list(doc["members"])
        with self._lock:
            prev = self._last_doc
            self._last_doc = doc
        prev_world = int(prev["world"]) if prev else 0
        reason = ("join" if world > prev_world
                  else "leave" if world < prev_world else "churn")
        self.metrics.generation.set(gen)
        self.metrics.world_size.set(world)
        # steps lost to this fault = chief progress past the last
        # durable snapshot (they are REPLAYED from LATEST, not dropped —
        # the trajectory stays exact; the bench reports the replay cost)
        last_step = self._chief_step()
        peek = _peek_latest(self.spec["ckpt_dir"])
        steps_lost = max(0, last_step - (peek["global_step"] if peek
                                         else 0)) if reason == "leave" \
            else 0
        if steps_lost:
            self.metrics.steps_lost.inc(steps_lost)
        if world < self.min_workers:
            self._record_decision(
                "hold", "below_min_workers", generation=gen, world=world,
                min_workers=self.min_workers)
            return
        with self._lock:
            first = self._assignment is None
        if first and world < self.workers \
                and time.monotonic() - self._t0 < self.cohort_wait_s:
            # cohort still forming: don't compile the first leg at a
            # partial world only to yield it seconds later when the
            # stragglers of the INITIAL spawn join (a fault mid-run is
            # different — then we rejoin with whoever survives)
            self._record_decision(
                "hold", "cohort_forming", generation=gen, world=world,
                target=self.workers)
            return
        mesh = fold_world_to_mesh(
            world, devices=self.spec.get("devices"))
        # exactly ONE oracle re-pick per generation change: plan + K +
        # remat from the roofline model at the NEW shard count, logged
        # as a prediction whose outcome is the round's measured
        # steps/sec (_harvest_result)
        pb = _spec_param_bytes(self.spec)
        pick = self.oracle.repick(pb, 2 * pb, n_shards=mesh)
        with self._lock:
            self._repicks.append({"generation": gen, "world": world,
                                  "mesh": mesh, "pick": {
                                      "plan": pick["plan"],
                                      "k": pick["k"],
                                      "remat": pick["remat"]}})
        # the spec may PIN plan/K (bit-exact trajectory tests); the
        # re-pick still runs and is logged — pinning is a spec choice,
        # not a skipped decision
        plan = self.spec.get("plan") or pick["plan"]
        k = int(self.spec.get("k") or pick["k"])
        assign = {
            "generation": gen, "world": world, "mesh": mesh,
            "chief": members[0], "members": members, "plan": plan,
            "k": k, "remat": pick["remat"],
            "shares": equal_shares(self.spec["batch_size"], members),
            "spec": self.spec, "assign_seq": 0,
        }
        self.ledger.publish_assignment(assign)
        with self._lock:
            self._assignment = assign
            self._pending_rejoin = {
                "generation": gen, "t0": time.monotonic(),
                "wall_t0": time.time(), "chief": members[0],
                "from_step": last_step}
        self.metrics.rejoins.labels(reason=reason).inc()
        self._record_decision(
            "rejoin", reason, generation=gen, old_world=prev_world,
            world=world, mesh=mesh, chief=members[0], plan=plan, k=k,
            remat=pick["remat"], steps_lost=steps_lost)

    def _observe_rejoin(self, doc: dict):
        with self._lock:
            pending = self._pending_rejoin
        if not pending:
            return
        hb = self.ledger.broker.hgetall(
            self.ledger.hb_key(pending["chief"]))
        try:
            ts = float(fget(hb, "ts", 0.0))
            step = int(fget(hb, "step", 0))
        except (TypeError, ValueError):
            return
        if ts > pending["wall_t0"] and step > 0:
            secs = time.monotonic() - pending["t0"]
            self.metrics.rejoin_seconds.observe(secs)
            self._record_decision(
                "rejoined", "chief_stepping",
                generation=pending["generation"],
                seconds=round(secs, 3), resumed_step=step)
            with self._lock:
                self._pending_rejoin = None

    def _feed_straggler(self, doc: dict):
        """Heartbeat step times -> StragglerBoard -> share rebalance.

        Only same-workload peers are comparable, so the board ingests
        the SPARE heartbeats (identical nominal loop period); the
        chief's SPMD step time feeds the estimator's own
        StragglerDetector instead."""
        members = list(doc.get("members", []))
        for wid in members:
            hb = self.ledger.broker.hgetall(self.ledger.hb_key(wid))
            if fget(hb, "role") != "spare":
                continue
            ts = fget(hb, "ts")
            with self._lock:
                seen = self._hb_seen.get(wid)
                self._hb_seen[wid] = ts
            if ts is None or ts == seen:
                continue  # not a fresh sample
            try:
                self.board.observe(wid, float(fget(hb, "step_s", 0.0)))
            except (TypeError, ValueError):
                pass
        factors = {w: f for w, f in self.board.factors().items()
                   if w in members}
        if not factors or max(factors.values()) < self.straggler_factor:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._last_rebalance < self.rebalance_cooldown_s:
                return
            assign = self._assignment
        if not assign or sorted(assign["shares"]) != sorted(members):
            return
        new = rebalance_shares(assign["shares"], factors)
        if new == assign["shares"]:
            return
        slowest = max(factors, key=factors.get)
        assign = dict(assign, shares=new,
                      assign_seq=int(assign["assign_seq"]) + 1)
        self.ledger.publish_assignment(assign)
        with self._lock:
            self._assignment = assign
            self._last_rebalance = now
        self.metrics.rebalances.inc()
        self._record_decision(
            "rebalance", "straggler", worker=slowest,
            factor=round(factors[slowest], 3), shares=new,
            global_batch=sum(new.values()))

    def _check_heartbeat_slo(self, doc: dict):
        """Feed per-worker heartbeat AGE into the burn-rate engine and
        consume firing verdicts (ISSUE 17).

        Workers that have never heartbeat contribute nothing (cohort
        startup must not burn budget); a firing alert on a live SPARE
        is converted into a SIGTERM (reason ``hb_slo``) so the normal
        death/respawn path replaces the wedged process — the chief and
        already-dead workers only get the logged verdict."""
        members = list(doc.get("members", []))
        now_wall = time.time()
        roles = {}
        for wid in members:
            hb = self.ledger.broker.hgetall(self.ledger.hb_key(wid))
            try:
                ts = float(fget(hb, "ts", 0.0) or 0.0)
            except (TypeError, ValueError):
                ts = 0.0
            if ts <= 0.0:
                continue  # never heartbeat yet — not a freshness fact
            roles[wid] = fget(hb, "role")
            self._hb_store.observe(
                "zoo_elastic_hb_age_seconds", max(0.0, now_wall - ts),
                labels={"worker": wid})
            name = f"worker_heartbeat:{wid}"
            if name not in {s.name for s in self._hb_engine.specs()}:
                self._hb_engine.add_spec(dataclasses.replace(
                    self.hb_slo, name=name,
                    labels=(("worker", wid),)))
        for alert in self._hb_engine.evaluate():
            wid = alert["slo"].split(":", 1)[-1]
            with self._lock:
                # one decision per firing EPISODE, not per tick
                if self._hb_alerted.get(wid) == alert["since"]:
                    continue
                self._hb_alerted[wid] = alert["since"]
                proc = self._procs.get(wid)
            kill = (self.hb_slo_kill and roles.get(wid) == "spare"
                    and proc is not None and proc.alive())
            self._record_decision(
                "hb_slo", "heartbeat_burn", worker=wid,
                short_burn=alert["short_burn"],
                long_burn=alert["long_burn"],
                threshold=alert["threshold"],
                verdict="kill" if kill else "log")
            if kill:
                try:
                    os.kill(proc.pid, signal.SIGTERM)
                except OSError:
                    pass  # lost the race with an organic death

    def _harvest_result(self):
        doc = self.result()
        if doc is None:
            return
        with self._lock:
            if self._outcomes_fed:
                return
            self._outcomes_fed = 1
            repicks = list(self._repicks)
        # close the prediction->outcome loop on the LAST re-pick (the
        # config the finishing leg actually ran under)
        if repicks and doc.get("steps_per_sec"):
            last = repicks[-1]
            cfg = (self.spec.get("plan") or last["pick"]["plan"],
                   last["pick"]["remat"])
            try:
                self.oracle.record_outcome(
                    cfg, float(doc["steps_per_sec"]), consumer="elastic")
            except Exception:
                logger.exception("elastic: outcome feedback failed")
        self._record_decision(
            "done", "round_complete", generation=doc.get("generation"),
            final_step=doc.get("final_step"),
            steps_per_sec=round(float(doc.get("steps_per_sec", 0.0)), 3))

    def _record_decision(self, action, reason, **fields):
        with self._lock:
            self._decisions.append(dict(
                {"ts": time.time(), "action": action, "reason": reason},
                **fields))
        self._flight.record("elastic", event=action, reason=reason,
                            **fields)

    # ------------------------------------------------------------------
    # introspection (/varz, metrics_dump, benches)
    # ------------------------------------------------------------------
    def decision_log(self) -> list:
        with self._lock:
            return list(self._decisions)

    def repick_log(self) -> list:
        with self._lock:
            return [dict(r) for r in self._repicks]

    def current(self) -> dict:
        with self._lock:
            doc = self._last_doc or {}
            assign = self._assignment or {}
            procs = {wid: {"pid": p.pid, "alive": p.alive()}
                     for wid, p in self._procs.items()}
            repicks = len(self._repicks)
        return {
            "generation": doc.get("generation", 0),
            "world": doc.get("world", 0),
            "members": doc.get("members", []),
            "chief": assign.get("chief"),
            "mesh": assign.get("mesh"),
            "plan": assign.get("plan"),
            "k": assign.get("k"),
            "shares": assign.get("shares", {}),
            "target_workers": self.workers,
            "min_workers": self.min_workers,
            "workers": procs,
            "repicks": repicks,
        }

    def to_doc(self) -> dict:
        return {"current": self.current(),
                "decisions": self.decision_log(),
                "repicks": self.repick_log()}


# ---------------------------------------------------------------------------
# Subprocess worker entry point:
#   python -m analytics_zoo_tpu.elastic --worker --broker dir:... --id w0
# ---------------------------------------------------------------------------


def _worker_main(argv) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="analytics_zoo_tpu.elastic",
        description="run ONE elastic training worker against a shared "
                    "broker")
    p.add_argument("--worker", action="store_true", required=True)
    p.add_argument("--broker", required=True,
                   help="cross-process broker spec (dir:<spool>, "
                        "host:port)")
    p.add_argument("--id", required=True, help="membership slot, e.g. w0")
    p.add_argument("--prefix", default=DEFAULT_PREFIX)
    p.add_argument("--lease-ms", type=int, default=None)
    a = p.parse_args(argv)

    ledger = MembershipLedger(a.broker, prefix=a.prefix,
                              lease_ms=a.lease_ms)
    stop = threading.Event()

    def on_term(signum, frame):
        stop.set()
        raise SystemExit(0)

    # Handler ordering is the SIGTERM story: our handler goes in FIRST,
    # then flight.install() chains OVER it — so a SIGTERM runs the
    # pre-dump hooks (async checkpointer flushed, final ``ckpt`` event
    # recorded), writes the flight dump, THEN unwinds through on_term's
    # SystemExit into the finally below, which releases the membership
    # slot for a fast (no lease-expiry) rejoin of the survivors.
    signal.signal(signal.SIGTERM, on_term)
    flight = get_flight_recorder().install()
    handle = ledger.join(a.id)
    flight.record("elastic", event="join", worker=a.id, pid=os.getpid())
    # Federation discovery (ISSUE 17): a worker whose env opted into a
    # metrics server (ZOO_METRICS_PORT, typically via the supervisor's
    # worker_env) advertises the bound /telemetryz URL in its hb hash —
    # scrape.elastic_varz_targets() turns those into scrape targets.
    from analytics_zoo_tpu.metrics.http import maybe_start_from_env

    _msrv = maybe_start_from_env()
    if _msrv is not None:
        ledger.broker.hset(ledger.hb_key(a.id), {"varz": _msrv.url})
    try:
        _round_loop(ledger, a.id, stop, flight)
    finally:
        flight.record("elastic", event="leave", worker=a.id)
        handle.leave()
    return 0


def _round_loop(ledger: MembershipLedger, wid: str, stop, flight):
    """Assignment-driven worker rounds: chief runs the actual SPMD fit
    leg; everyone else heartbeats as a hot spare until the next
    generation."""
    while not stop.is_set():
        assign = ledger.assignment()
        if assign is None:
            time.sleep(0.05)
            continue
        if assign.get("chief") == wid:
            if _chief_leg(ledger, wid, assign, flight):
                return  # round complete: result posted
            _wait_past_generation(ledger, int(assign["generation"]), stop)
        else:
            _spare_leg(ledger, wid, assign, stop)


def _wait_past_generation(ledger, gen: int, stop):
    while not stop.is_set():
        a = ledger.assignment()
        if a is not None and int(a["generation"]) > gen:
            return
        time.sleep(0.05)


def _spare_leg(ledger: MembershipLedger, wid: str, assign: dict, stop):
    """Hot spare: keep the membership lease warm (the MemberHandle
    thread does that) and publish heartbeats the supervisor's straggler
    board can compare — all spares run the same nominal loop period, so
    an injected (or real) stall shows as a genuine slowdown factor."""
    spec = assign.get("spec", {})
    period = float(spec.get("hb_s", 0.05))
    gen = int(assign["generation"])
    step = 0
    while not stop.is_set():
        t0 = time.monotonic()
        a = ledger.assignment()
        if a is None or int(a["generation"]) != gen \
                or a.get("chief") == wid:
            return
        stall = fget(ledger.broker.hgetall(ledger.ctl_key(wid)),
                     "stall_s")
        if stall:
            ledger.broker.delete(ledger.ctl_key(wid))
            time.sleep(float(stall))
        time.sleep(period)
        step += 1
        ledger.broker.hset(ledger.hb_key(wid), {
            "step": str(step),
            "step_s": "%.6f" % (time.monotonic() - t0),
            "ts": "%.3f" % time.time(),
            "role": "spare",
        })


def _chief_leg(ledger: MembershipLedger, wid: str, assign: dict,
               flight) -> bool:
    """One training leg at this assignment's (mesh, plan, K): resume
    from LATEST through the partitioner, fit until done or the next
    GenerationChange.  Returns True when the nb_epoch target is reached
    (result posted)."""
    # keras-stack imports deferred to the one role that traces/compiles
    import numpy as np

    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    spec = dict(assign["spec"])
    gen = int(assign["generation"])
    mesh = int(assign["mesh"])
    os.environ["ZOO_STEPS_PER_DISPATCH"] = str(int(assign.get("k", 1)))
    zoo.init_zoo_context(seed=int(spec["seed"]),
                         mesh_shape={"data": mesh})
    m = Sequential()
    m.add(Dense(int(spec["hidden"]), activation="relu",
                input_shape=(int(spec["in_dim"]),)))
    m.add(Dense(int(spec["classes"]), activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.set_checkpoint(spec["ckpt_dir"])
    rng = np.random.default_rng(int(spec["seed"]))
    x = rng.standard_normal(
        (int(spec["n"]), int(spec["in_dim"]))).astype(np.float32)
    y = rng.integers(0, int(spec["classes"]),
                     size=(int(spec["n"]),)).astype(np.int32)
    peek = _peek_latest(spec["ckpt_dir"])
    start_step = peek["global_step"] if peek else 0
    session = ElasticSession(
        ledger.broker, prefix=ledger.prefix, generation=gen,
        worker_id=wid, start_step=start_step,
        min_poll_s=float(spec.get("poll_s", 0.02)),
        throttle_s=float(spec.get("throttle_s", 0.0)))
    flight.record("elastic", event="leg", worker=wid, generation=gen,
                  mesh=mesh, plan=assign.get("plan"),
                  start_step=start_step)
    t0 = time.monotonic()
    try:
        m.fit(x, y, batch_size=int(spec["batch_size"]),
              nb_epoch=int(spec["nb_epoch"]), plan=assign.get("plan"),
              elastic=session)
    except GenerationChange as gc:
        flight.record("elastic", event="yielded", worker=wid,
                      old_generation=gen,
                      generation=gc.doc.get("generation"))
        return False
    est = m._estimator
    elapsed = max(time.monotonic() - t0, 1e-9)
    result = {
        "done": 1, "generation": gen, "worker": wid,
        "final_step": int(est.global_step), "epoch": int(est.epoch),
        # loss is None for a zero-dispatch epoch: a resume that lands
        # exactly on an epoch boundary (next_batch == n_batches) replays
        # nothing before the boundary sync
        "history": [{"epoch": int(h["epoch"]),
                     "loss": None if h["loss"] is None
                     else float(h["loss"])}
                    for h in est.history],
        "steps_per_sec": (est.global_step - start_step) / elapsed,
        "ts": time.time(),
    }
    ledger.broker.hset(ledger.result_key, {"doc": json.dumps(result)})
    flight.record("elastic", event="done", worker=wid, generation=gen,
                  final_step=result["final_step"])
    return True


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(_worker_main(sys.argv[1:]))
