"""zooelastic: the elastic training runtime.

Unattended pod-scale ``fit()``: a lease-based membership ledger on the
serving broker's claim protocol (membership.py), a worker supervisor
that respawns the dead and orchestrates oracle-guided rejoins at new
world sizes (supervisor.py), and a deterministic chaos harness that
proves it all under scripted ``kill -9`` / SIGTERM / stalls (chaos.py).
See docs/elastic-training.md.
"""

from .chaos import ChaosEvent, ChaosSchedule
from .membership import (
    DEFAULT_PREFIX,
    ElasticSession,
    GenerationChange,
    MemberHandle,
    MembershipLedger,
)
from .supervisor import (
    TrainSupervisor,
    equal_shares,
    rebalance_shares,
    varz_doc,
)

__all__ = [
    "ChaosEvent",
    "ChaosSchedule",
    "DEFAULT_PREFIX",
    "ElasticSession",
    "GenerationChange",
    "MemberHandle",
    "MembershipLedger",
    "TrainSupervisor",
    "equal_shares",
    "rebalance_shares",
    "varz_doc",
]
