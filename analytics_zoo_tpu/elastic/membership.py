"""zooelastic membership: the lease-based worker ledger.

Elastic training needs ONE fact agreed on by everybody: *who is in the
cohort right now*.  This module derives that fact from the serving
broker's exactly-once claim protocol (serving/broker.py
``claim``/``extend``/``release`` — per-record leases) instead of
inventing a second coordination service:

- Each worker owns a single-record stream ``<prefix>-m-<worker_id>``
  and CLAIMS its own record under ``ZOO_ELASTIC_LEASE_MS``; a daemon
  keepalive thread extends the lease at a third of its period.
  Liveness is exactly :meth:`~analytics_zoo_tpu.serving.broker.Broker.
  lease_held`: a ``kill -9`` just stops the keepalive, and the member
  drops out after one lease period with no cleanup code running.
- The **generation doc** ``{"generation", "world", "members", "ts"}``
  lives in broker hash ``<prefix>:generation`` (field ``doc``, json).
  Its single writer is the supervisor's :meth:`MembershipLedger.scan`,
  which bumps ``generation`` whenever the live-member set changes (any
  join OR leave).  Every worker reads the doc at the estimator's step
  barrier through :class:`ElasticSession` — the single source of truth
  the ISSUE demands.

The same prefix namespaces the runtime's other mailboxes (all plain
broker hashes, documented here so the layout has one home):

============================  ==============================================
key                           contents
============================  ==============================================
``<prefix>-m-<wid>``          the member's single-record lease stream
``<prefix>:roster:<wid>``     per-worker hash (owner/pid/ts) — one
                              writer each, so joins never race
``<prefix>:generation``       field ``doc``: the generation doc (json)
``<prefix>:assign``           field ``doc``: supervisor's assignment doc
``<prefix>:hb:<wid>``         worker heartbeat: step / step_s / ts / role
``<prefix>:ctl:<wid>``        chaos control: field ``stall_s`` injects a stall
``<prefix>:result``           field ``doc``: chief's round result (json)
============================  ==============================================
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

from analytics_zoo_tpu.serving.broker import connect_broker

__all__ = [
    "DEFAULT_PREFIX",
    "ElasticSession",
    "GenerationChange",
    "MemberHandle",
    "MembershipLedger",
    "fget",
]

DEFAULT_PREFIX = "zoo-elastic"


def fget(mapping, key, default=None):
    """Broker-hash field access that tolerates the redis transport's
    bytes keys/values (FileBroker/InMemoryBroker return str)."""
    if not mapping:
        return default
    val = mapping.get(key, mapping.get(
        key.encode() if isinstance(key, str) else key, default))
    if isinstance(val, bytes):
        val = val.decode()
    return val


class GenerationChange(Exception):
    """Raised by the estimator's step barrier when the cluster
    generation moved under a running ``fit()``.

    This is control flow, not a failure: ``_train_with_retries`` lets it
    through un-retried, the elastic worker's round loop catches it,
    rejoins, and the next leg resumes from ``LATEST`` at the new world
    size (``_elastic_yield`` made the snapshot durable before raising).
    Carries the NEW generation doc as ``.doc``."""

    def __init__(self, doc: dict):
        self.doc = dict(doc)
        super().__init__(
            "generation -> %s (world %s)"
            % (doc.get("generation"), doc.get("world")))


class MembershipLedger:
    """(generation, world size, member list) on broker leases.

    Worker side: :meth:`join`.  Supervisor side (single writer of the
    generation doc): :meth:`scan`.  Read side (everyone):
    :meth:`members` / :meth:`generation_doc`.  Works over all three
    brokers — the memory broker for units, ``dir:`` spools for
    kill-resilient subprocess cohorts, redis for real clusters."""

    def __init__(self, broker, prefix: str = DEFAULT_PREFIX,
                 lease_ms: int | None = None):
        self.broker = connect_broker(broker)
        self.prefix = str(prefix)
        if lease_ms is None:
            lease_ms = int(os.environ.get("ZOO_ELASTIC_LEASE_MS", "3000"))
        self.lease_ms = int(lease_ms)

    # -- key layout -----------------------------------------------------
    def member_stream(self, worker_id: str) -> str:
        return f"{self.prefix}-m-{worker_id}"

    # one roster hash PER worker (single writer each): a shared roster
    # hash would be a cross-process read-modify-write race on brokers
    # whose hset merges by read+rewrite (FileBroker) — concurrent joins
    # would silently drop each other
    @property
    def roster_prefix(self) -> str:
        return f"{self.prefix}:roster:"

    def roster_key(self, worker_id: str) -> str:
        return f"{self.roster_prefix}{worker_id}"

    @property
    def generation_key(self) -> str:
        return f"{self.prefix}:generation"

    @property
    def assign_key(self) -> str:
        return f"{self.prefix}:assign"

    @property
    def result_key(self) -> str:
        return f"{self.prefix}:result"

    def hb_key(self, worker_id: str) -> str:
        return f"{self.prefix}:hb:{worker_id}"

    def ctl_key(self, worker_id: str) -> str:
        return f"{self.prefix}:ctl:{worker_id}"

    # -- worker side ----------------------------------------------------
    def join(self, worker_id: str,
             timeout_ms: int | None = None) -> "MemberHandle":
        """Claim the membership slot ``worker_id`` and start its
        keepalive.  A respawn reuses its predecessor's slot: if the dead
        incarnation's lease is still ticking (``kill -9`` leaves no
        release), we wait it out — the claim succeeds the moment the
        broker expires it, which is exactly the takeover story serving
        replicas already live by."""
        stream = self.member_stream(worker_id)
        owner = "%s@%s-%d" % (worker_id, socket.gethostname(), os.getpid())
        if timeout_ms is None:
            timeout_ms = self.lease_ms * 2 + 1_000
        deadline = time.monotonic() + timeout_ms / 1e3
        if self.broker.xlen(stream) == 0:
            self.broker.xadd(stream, {"worker": worker_id})
        # a crashed join could have raced a second record in; one record
        # per slot is the lease_held invariant
        self.broker.xtrim(stream, 1)
        while True:
            got = self.broker.claim(stream, owner, 1, self.lease_ms)
            if got:
                rid = got[0][0]
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{worker_id}: membership slot still leased by a "
                    f"previous incarnation after {timeout_ms}ms")
            time.sleep(min(0.05, self.lease_ms / 4e3))
        self.broker.hset(self.roster_key(worker_id), {
            "owner": owner, "pid": str(os.getpid()),
            "ts": "%.3f" % time.time()})
        return MemberHandle(self, worker_id, owner, rid)

    # -- read side ------------------------------------------------------
    def members(self) -> list:
        """Sorted worker ids whose membership lease is LIVE right now."""
        pfx = self.roster_prefix
        keys = (k.decode() if isinstance(k, bytes) else k
                for k in self.broker.keys(pfx))
        wids = sorted(k[len(pfx):] for k in keys)
        return [w for w in wids
                if self.broker.lease_held(self.member_stream(w))]

    def generation_doc(self) -> dict | None:
        raw = fget(self.broker.hgetall(self.generation_key), "doc")
        return json.loads(raw) if raw else None

    def assignment(self) -> dict | None:
        raw = fget(self.broker.hgetall(self.assign_key), "doc")
        return json.loads(raw) if raw else None

    # -- supervisor side (the generation doc's single writer) -----------
    def scan(self) -> tuple:
        """Recompute live membership; bump the generation iff the member
        set changed.  Returns ``(doc, changed)``.  Called only by the
        supervisor — single-writer is what makes the counter a counter."""
        live = self.members()
        doc = self.generation_doc()
        if doc is not None and doc.get("members") == live:
            return doc, False
        gen = 1 if doc is None else int(doc.get("generation", 0)) + 1
        doc = {"generation": gen, "world": len(live), "members": live,
               "ts": time.time()}
        self.broker.hset(self.generation_key, {"doc": json.dumps(doc)})
        return doc, True

    def publish_assignment(self, doc: dict) -> None:
        self.broker.hset(self.assign_key, {"doc": json.dumps(doc)})


class MemberHandle:
    """One live membership slot: the keepalive thread plus the graceful
    exit.  Process death (any signal, any abruptness) degrades to lease
    expiry — that is the whole point."""

    def __init__(self, ledger: MembershipLedger, worker_id: str,
                 owner: str, rid: str):
        self.ledger = ledger
        self.worker_id = worker_id
        self.owner = owner
        self.rid = rid
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._keepalive, daemon=True,
            name=f"zoo-elastic-keepalive-{worker_id}")
        self._thread.start()

    def alive(self) -> bool:
        return not self._stop.is_set()

    def _keepalive(self):
        period = max(0.01, self.ledger.lease_ms / 3e3)
        stream = self.ledger.member_stream(self.worker_id)
        while not self._stop.wait(period):
            try:
                self.ledger.broker.extend(
                    stream, self.owner, [self.rid], self.ledger.lease_ms)
            except Exception:
                # a broker hiccup must not kill the worker; a lost
                # extend at worst costs one lease period of membership
                pass

    def leave(self) -> None:
        """Graceful departure: stop the keepalive and ACK the slot
        record, so the NEXT supervisor scan sees the member gone instead
        of waiting a full lease period for expiry (the SIGTERM path's
        fast rejoin)."""
        self._stop.set()
        try:
            self.ledger.broker.release(
                self.ledger.member_stream(self.worker_id), self.owner,
                [self.rid], done=True)
        except Exception:
            pass  # at worst the slot expires like a crash


class ElasticSession:
    """The worker-side handle threaded into ``fit(elastic=...)``.

    ``estimator._train_loop`` calls :meth:`poll` once per optimizer
    dispatch — the STEP BARRIER.  The call is rate-limited to
    ``min_poll_s`` so the hot path pays a couple of hash reads at most a
    few times a second, not per step.  On a generation bump it returns
    the NEW doc (the estimator then snapshots and raises
    :class:`GenerationChange`); otherwise ``None``.

    Each rate-limit tick also:

    - publishes the worker heartbeat ``<prefix>:hb:<wid>``
      (``step``/``step_s``/``ts``/``role``) — the supervisor's
      straggler board and the chaos schedule's ``at_step`` anchor both
      read it;
    - honours chaos stall injection: field ``stall_s`` of
      ``<prefix>:ctl:<wid>`` sleeps that long once (consumed), which
      shows up in ``step_s`` exactly like a real straggler would.
    """

    def __init__(self, broker, prefix: str = DEFAULT_PREFIX,
                 generation: int = 0, worker_id: str | None = None,
                 start_step: int = 0, min_poll_s: float = 0.2,
                 throttle_s: float = 0.0):
        self.ledger = MembershipLedger(broker, prefix=prefix)
        self.generation = int(generation)
        self.worker_id = worker_id
        self.start_step = int(start_step)
        self.min_poll_s = float(min_poll_s)
        # per-step host-side sleep: stands in for a real model's step
        # time in tests/benches so faults land at the step they target
        self.throttle_s = float(throttle_s)
        self._steps = 0
        self._last_step_t: float | None = None
        self._step_s = 0.0
        self._last_poll = 0.0

    def step(self) -> int:
        """Global step as this session counts it (start offset + polls
        seen — one poll per dispatch)."""
        return self.start_step + self._steps

    def poll(self) -> dict | None:
        if self.throttle_s > 0:
            time.sleep(self.throttle_s)
        now = time.monotonic()
        self._steps += 1
        if self._last_step_t is not None:
            self._step_s = now - self._last_step_t
        self._last_step_t = now
        if now - self._last_poll < self.min_poll_s:
            return None
        self._last_poll = now
        b = self.ledger.broker
        if self.worker_id is not None:
            ctl_key = self.ledger.ctl_key(self.worker_id)
            stall = fget(b.hgetall(ctl_key), "stall_s")
            if stall:
                b.delete(ctl_key)  # consume: a stall fires once
                time.sleep(float(stall))
                self._step_s += float(stall)
                self._last_step_t = time.monotonic()
            b.hset(self.ledger.hb_key(self.worker_id), {
                "step": str(self.step()),
                "step_s": "%.6f" % self._step_s,
                "ts": "%.3f" % time.time(),
                "role": "chief",
            })
        doc = self.ledger.generation_doc()
        if doc is not None and int(doc.get("generation", 0)) > self.generation:
            return doc
        return None
