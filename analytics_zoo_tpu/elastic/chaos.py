"""zooelastic chaos: deterministic fault injection for unattended runs.

A :class:`ChaosSchedule` scripts faults against worker ids at *step
boundaries* of the training trajectory — not wall-clock — so a run is
reproducible from its seed: the supervisor reads the chief's heartbeat
step and fires every event whose ``at_step`` has been reached.

Three fault kinds, covering the failure taxonomy the ISSUE's acceptance
run must survive without a human:

- ``kill``  — ``SIGKILL``: no cleanup runs, the membership lease
  expires, survivors take over (the pod-preemption shape).
- ``term``  — ``SIGTERM``: the worker's handler leaves the membership
  gracefully after the flight recorder's pre-dump hooks flushed the
  async checkpointer (the maintenance-drain shape).
- ``stall`` — field ``stall_s`` written into the worker's control hash;
  its :class:`~analytics_zoo_tpu.elastic.membership.ElasticSession`
  consumes it as a one-shot sleep, which the straggler board then sees
  as a genuine slow step (the slow-host shape).

Schedules come from :meth:`ChaosSchedule.from_seed` (seeded RNG) or
:meth:`ChaosSchedule.parse` (``"kill@12:w1,term@20:w2,stall@16:w3:1.5"``)
so a bench artifact can state exactly what it injected.
"""

from __future__ import annotations

import dataclasses
import random

__all__ = ["ChaosEvent", "ChaosSchedule", "ACTIONS"]

ACTIONS = ("kill", "term", "stall")


@dataclasses.dataclass
class ChaosEvent:
    at_step: int
    action: str  # kill | term | stall
    target: str  # worker id, e.g. "w1"
    arg: float = 0.0  # stall seconds (stall only)
    fired: bool = False

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"chaos action must be one of {ACTIONS}, got "
                f"{self.action!r}")
        self.at_step = int(self.at_step)
        self.arg = float(self.arg)

    def to_doc(self) -> dict:
        return {"at_step": self.at_step, "action": self.action,
                "target": self.target, "arg": self.arg,
                "fired": self.fired}


class ChaosSchedule:
    """An ordered, one-shot script of :class:`ChaosEvent`.

    The supervisor polls :meth:`due` with the chief's current step and
    marks each event fired after executing it; :meth:`done` is true when
    the script is exhausted."""

    def __init__(self, events):
        self.events = sorted(events, key=lambda e: (e.at_step, e.target))

    @classmethod
    def from_seed(cls, seed: int, workers, total_steps: int,
                  n_events: int = 2, actions=ACTIONS,
                  stall_s: float = 1.0) -> "ChaosSchedule":
        """Deterministic schedule: ``n_events`` faults over distinct
        targets, landing in the middle half of the run (``[total/4,
        3*total/4]``) so every fault interrupts real progress instead of
        warmup or the final step."""
        workers = list(workers)
        rng = random.Random(int(seed))
        lo = max(1, total_steps // 4)
        hi = max(lo + 1, (3 * total_steps) // 4)
        targets = rng.sample(workers, k=min(int(n_events), len(workers)))
        events = [
            ChaosEvent(at_step=rng.randint(lo, hi),
                       action=actions[i % len(actions)], target=t,
                       arg=stall_s)
            for i, t in enumerate(targets)
        ]
        return cls(events)

    @classmethod
    def parse(cls, spec: str) -> "ChaosSchedule":
        """``"kill@12:w1,term@20:w2,stall@16:w3:1.5"`` — the bench /
        test notation (``action@step:target[:arg]``)."""
        events = []
        for part in filter(None, (p.strip() for p in spec.split(","))):
            head, _, rest = part.partition("@")
            bits = rest.split(":")
            if len(bits) < 2:
                raise ValueError(
                    f"chaos event needs action@step:target, got {part!r}")
            events.append(ChaosEvent(
                at_step=int(bits[0]), action=head.strip(),
                target=bits[1].strip(),
                arg=float(bits[2]) if len(bits) > 2 else 0.0))
        return cls(events)

    def due(self, step: int) -> list:
        return [e for e in self.events
                if not e.fired and e.at_step <= int(step)]

    def done(self) -> bool:
        return all(e.fired for e in self.events)

    def to_doc(self) -> list:
        return [e.to_doc() for e in self.events]
