"""Parallelism strategies over the device mesh.

The reference's only inter-node strategy is synchronous data parallelism on
Spark (SURVEY.md §2.4); TP/PP/SP/EP are absent.  Here every strategy is a
first-class mesh axis (common/engine.py axes: data/model/seq/expert/pipe):

- :mod:`plan` — the unified partitioner: :class:`~analytics_zoo_tpu.
  parallel.plan.ShardingPlan` rule tables (regex → PartitionSpec over
  logical tree paths), canned plans (``data_parallel``/``zero1``/
  ``zero2``/``zero3``/``fsdp``/``tensor_parallel``/``pipeline_plan``/
  ``mixed_precision``/``int8_serving``), remat policy as plan rules
  (``with_remat``/``apply_remat``), dtype policy as plan rules
  (``with_dtype``/``with_dtype_policy``/``resolve_dtype_rules``), the
  hybrid ICI×DCN mesh builder, and ``compile_step`` — the ONE compile
  choke point every strategy lowers through (persistent cache + HLO
  lint + compile metering).
- :mod:`strategies` — explicit shard_map train steps (psum = the
  AllReduceParameter replacement), tensor-parallel dense helpers; thin
  wrappers over :mod:`plan`'s choke point.
- :mod:`ring_attention` — sequence/context parallelism via ppermute ring —
  the long-context capability the reference lacks.
- :mod:`pipeline` — GPipe microbatch pipeline parallelism over the ``pipe``
  axis (scan + ppermute schedule; grad = automatic reverse pipeline).
- :mod:`multihost` — jax.distributed bootstrap (the RayOnSpark role).
"""

from analytics_zoo_tpu.parallel.multihost import (  # noqa: F401
    hybrid_mesh,
    init_distributed,
)
from analytics_zoo_tpu.parallel.partition import (  # noqa: F401
    leaf_path_name,
    match_partition_rules,
    shard_params,
    tree_shardings,
)
from analytics_zoo_tpu.parallel.plan import (  # noqa: F401
    ShardingPlan,
    apply_remat,
    build_mesh,
    compile_step,
    data_parallel,
    fsdp,
    int8_serving,
    live_bytes,
    mixed_precision,
    per_chip_bytes,
    pipeline_plan,
    resolve_dtype_rules,
    resolve_plan,
    resolve_remat,
    tensor_parallel,
    with_dtype,
    with_dtype_policy,
    with_remat,
    zero1,
    zero2,
    zero3,
)
from analytics_zoo_tpu.parallel.pipeline import (  # noqa: F401
    gpipe,
    gpipe_1f1b_grads,
    gpipe_hetero_1f1b_grads,
    stack_stage_params,
    transformer_gpipe,
)
from analytics_zoo_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    zigzag_ring_attention,
)
from analytics_zoo_tpu.parallel.strategies import (  # noqa: F401
    column_parallel_dense,
    make_shard_map_train_step,
    make_zero1_train_step,
    reshard_zero1_opt_state,
    row_parallel_dense,
)
