"""Multi-host bootstrap — the RayOnSpark role.

The reference launches Ray clusters inside Spark executors to orchestrate
multi-node python (pyzoo/zoo/ray/util/raycontext.py:192-393, barrier-mode
stage + JVMGuard pid cleanup).  On TPU pods the runtime equivalent is
``jax.distributed.initialize``: one process per host, all hosts run the same
SPMD program, and the mesh spans every chip on the pod (ICI) and across
slices (DCN).
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np

logger = logging.getLogger("analytics_zoo_tpu")


_initialized = False


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None):
    """Initialise multi-host JAX (idempotent).

    Must be the first JAX call in the process — ``jax.distributed.initialize``
    refuses to run after the backend exists, so this function deliberately
    touches no other jax API before it.  On Cloud TPU VMs all three args are
    auto-detected from the metadata server; elsewhere pass them explicitly
    (reference analogue: RayContext.init's head/worker bootstrap,
    raycontext.py:192-393).

    With explicit args, failures propagate (a mis-bootstrapped pod must not
    silently train as N independent hosts).  With no args, failed
    auto-detection is treated as single-host and logged at WARNING.
    """
    global _initialized
    if _initialized or jax.distributed.is_initialized():
        return  # ours or an external launcher's init — both fine
    explicit = (coordinator_address is not None or num_processes is not None
                or process_id is not None)
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
        _initialized = True
        logger.info("jax.distributed initialised: process %d/%d, %d local "
                    "devices", jax.process_index(), jax.process_count(),
                    jax.local_device_count())
    except Exception as e:
        if explicit:
            raise
        logger.warning(
            "jax.distributed auto-init failed (%s); running single-host. "
            "On a pod, call init_distributed(...) with explicit args before "
            "any other JAX usage.", e)


def process_local_batch_slice(global_batch_size: int,
                              process_shard: tuple[int, int] | None = None
                              ) -> slice:
    """Which slice of the global batch this host should load — the per-chip
    host infeed contract (each host feeds only its own chips, replacing the
    reference's RDD partition locality, FeatureSet.scala:240-289).

    Consumed per-batch by ``FeatureSet.batches(process_shard=...)`` so each
    host materializes only its rows; ``ZooContext.shard_batch`` then
    reassembles the global array via
    ``jax.make_array_from_process_local_data``.  ``process_shard`` is an
    explicit ``(process_index, process_count)`` override for callers that
    already know their coordinates (and for single-process tests).
    """
    pid, nproc = (process_shard if process_shard is not None
                  else (jax.process_index(), jax.process_count()))
    per_proc = global_batch_size // nproc
    start = per_proc * pid
    return slice(start, start + per_proc)


def hybrid_mesh(ici_shape: dict, dcn_shape: dict, axes=None, devices=None,
                slice_groups=None, allow_idle=False):
    """Mesh spanning multiple TPU slices: the DCN-crossing axis outermost,
    ICI axes inner (SURVEY §2.4 — collectives for the inner axes then ride
    ICI; only the outermost axis' all-reduce crosses the data-center
    network).  The multi-slice analogue of the reference's scale-out story
    (its only inter-node axis, Spark DP, maps to the DCN axis here).

    Args:
      ici_shape: per-slice mesh extents, e.g. ``{"data": 2, "model": 2}``.
      dcn_shape: extents ACROSS slices.  Exactly one axis may cross the
        DCN, and it must be the outermost of the resulting mesh — the
        standard multi-slice layout (DP over DCN, everything else on ICI).
      axes: axis order (default: the axes appearing in ici/dcn shapes, in
        canonical ``data/model/seq/expert/pipe`` order).
      devices: flat device list (default ``jax.devices()``).
      slice_groups: explicit list of equal-size device groups, one per
        slice — used by CI (CPU devices carry no ``slice_index``) and for
        exotic topologies.  On real multi-slice TPU the default groups by
        ``device.slice_index``.

    Returns a ``jax.sharding.Mesh`` whose total extent per axis is
    ``dcn * ici``.
    """
    from jax.sharding import Mesh

    from analytics_zoo_tpu.common.engine import ALL_AXES

    dcn_axes = [a for a, n in dcn_shape.items() if n > 1]
    if len(dcn_axes) > 1:
        raise ValueError(
            f"only one axis may cross the DCN, got {dcn_axes}")
    if axes is None:
        axes = tuple(a for a in ALL_AXES
                     if a in ici_shape or a in dcn_shape)
    # a typo'd axis key would otherwise fall through .get(a, 1) below and
    # yield a degenerate size-1 mesh with at most an idle-devices warning
    unknown = (set(ici_shape) | set(dcn_shape)) - set(axes)
    if unknown:
        raise ValueError(
            f"ici_shape/dcn_shape keys {sorted(unknown)} not in mesh axes "
            f"{tuple(axes)}")
    n_slices = dcn_shape.get(dcn_axes[0], 1) if dcn_axes else 1
    if dcn_axes and axes[0] != dcn_axes[0]:
        raise ValueError(
            f"DCN axis {dcn_axes[0]!r} must be outermost, axes={axes}")

    if slice_groups is None:
        # device discovery only when actually needed: jax.devices() forces
        # backend init, which is slow/can fail when the TPU is unreachable
        devices = list(jax.devices()) if devices is None else list(devices)
        by_slice: dict = {}
        for d in devices:
            by_slice.setdefault(getattr(d, "slice_index", 0), []).append(d)
        slice_groups = [by_slice[k] for k in sorted(by_slice)]
    if len(slice_groups) != n_slices:
        raise ValueError(
            f"{n_slices} slices requested but {len(slice_groups)} device "
            "groups found")

    per_slice = [ici_shape.get(a, 1) for a in axes]
    need = int(np.prod(per_slice))
    arrays = []
    for g in slice_groups:
        if len(g) < need:
            raise ValueError(
                f"slice has {len(g)} devices, mesh needs {need}")
        if len(g) > need:
            # on real multi-slice hardware a wrong per-slice shape would
            # otherwise silently train on a subset of each slice
            if not allow_idle:
                raise ValueError(
                    f"slice has {len(g)} devices but the ICI mesh uses only "
                    f"{need}; pass allow_idle=True to leave "
                    f"{len(g) - need} devices per slice idle")
            logger.warning(
                "hybrid_mesh: slice has %d devices but the ICI mesh uses "
                "only %d — %d devices per slice will sit idle",
                len(g), need, len(g) - need)
        arrays.append(np.asarray(g[:need]).reshape(per_slice))
    # stack slices on the (outermost) DCN axis and merge
    dev = np.concatenate(arrays, axis=0) if dcn_axes else arrays[0]
    return Mesh(dev, tuple(axes))
