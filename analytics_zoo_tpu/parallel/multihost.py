"""Multi-host bootstrap — the RayOnSpark role.

The reference launches Ray clusters inside Spark executors to orchestrate
multi-node python (pyzoo/zoo/ray/util/raycontext.py:192-393, barrier-mode
stage + JVMGuard pid cleanup).  On TPU pods the runtime equivalent is
``jax.distributed.initialize``: one process per host, all hosts run the same
SPMD program, and the mesh spans every chip on the pod (ICI) and across
slices (DCN).
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger("analytics_zoo_tpu")


_initialized = False


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None):
    """Initialise multi-host JAX (idempotent).

    Must be the first JAX call in the process — ``jax.distributed.initialize``
    refuses to run after the backend exists, so this function deliberately
    touches no other jax API before it.  On Cloud TPU VMs all three args are
    auto-detected from the metadata server; elsewhere pass them explicitly
    (reference analogue: RayContext.init's head/worker bootstrap,
    raycontext.py:192-393).

    With explicit args, failures propagate (a mis-bootstrapped pod must not
    silently train as N independent hosts).  With no args, failed
    auto-detection is treated as single-host and logged at WARNING.
    """
    global _initialized
    if _initialized or jax.distributed.is_initialized():
        return  # ours or an external launcher's init — both fine
    explicit = (coordinator_address is not None or num_processes is not None
                or process_id is not None)
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
        _initialized = True
        logger.info("jax.distributed initialised: process %d/%d, %d local "
                    "devices", jax.process_index(), jax.process_count(),
                    jax.local_device_count())
    except Exception as e:
        if explicit:
            raise
        logger.warning(
            "jax.distributed auto-init failed (%s); running single-host. "
            "On a pod, call init_distributed(...) with explicit args before "
            "any other JAX usage.", e)


def process_local_batch_slice(global_batch_size: int,
                              process_shard: tuple[int, int] | None = None
                              ) -> slice:
    """Which slice of the global batch this host should load — the per-chip
    host infeed contract (each host feeds only its own chips, replacing the
    reference's RDD partition locality, FeatureSet.scala:240-289).

    Consumed per-batch by ``FeatureSet.batches(process_shard=...)`` so each
    host materializes only its rows; ``ZooContext.shard_batch`` then
    reassembles the global array via
    ``jax.make_array_from_process_local_data``.  ``process_shard`` is an
    explicit ``(process_index, process_count)`` override for callers that
    already know their coordinates (and for single-process tests).
    """
    pid, nproc = (process_shard if process_shard is not None
                  else (jax.process_index(), jax.process_count()))
    per_proc = global_batch_size // nproc
    start = per_proc * pid
    return slice(start, start + per_proc)
