# zoolint: disable-file=raw-jit -- this module IS the compile choke point: the jax.jit here is the one every plan routes through (timed_compile telemetry, persistent cache, HLO lint)
"""zooplan — the unified partitioner: sharding plans + ONE compile entry.

Before this module, sharding decisions were scattered per strategy:
``parallel/strategies.py`` hand-wrote shard_map specs, the zero1
resharder re-laid optimizer state ad hoc, and the estimator's
``ZOO_SHARD_OPTIMIZER`` path picked its own NamedShardings.  FSDP/TP
were bespoke programs.  Here they are CONFIGURATIONS:

- A :class:`ShardingPlan` carries ordered regex rules → ``PartitionSpec``
  over the logical parameter / optimizer-state tree paths (T5X-style;
  ``match_partition_rules`` in :mod:`.partition` does the matching) plus
  the compile contract (jit + GSPMD constraints, or explicit shard_map).
  Specs are CLAMPED per leaf to what the mesh can actually divide, so a
  rule table written for one topology stays valid on another.
- Canned plans: :func:`data_parallel` (replicate everything — today's
  default), :func:`zero1` (optimizer state sharded over ``data``, the
  ZeRO-1 memory win), :func:`fsdp` (params AND optimizer state sharded
  over ``data`` — XLA all-gathers params on use and reduce-scatters
  grads, the ZeRO-2/3 direction of arXiv:2004.13336), and
  :func:`tensor_parallel` (user rules over the ``model`` axis).
- :func:`build_mesh` — one mesh builder: a plain ``Mesh`` on a single
  slice, a hybrid ICI×DCN mesh (DCN-crossing axis outermost, riding
  :func:`~analytics_zoo_tpu.parallel.multihost.hybrid_mesh`) for
  multi-pod; ``ZOO_DCN_AXIS`` names the crossing axis.
- :func:`compile_step` — THE compile choke point.  Every strategy's
  step function (plain DP, fsdp, zero1, TP, explicit shard_map) lowers
  through :func:`~analytics_zoo_tpu.common.compile_cache.timed_compile`
  here, so every compiled program shares the persistent compile cache,
  AOT warmup, ``zoo_compile_seconds`` metering, and the HLO graph
  lint / analytic cost features (``zoo_hlo_*``) — none of which the
  explicit strategies saw before.

Loss trajectories are placement-invariant: a plan changes WHERE bytes
live and which collectives XLA inserts, never the math — the fsdp plan
trains bit-identically to replicated DP (pinned by
``tests/test_partitioner.py`` and ``bench.py --partition``).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.common.engine import (
    ALL_AXES,
    DATA_AXIS,
    MODEL_AXIS,
    logger,
)
from analytics_zoo_tpu.parallel.partition import (
    match_partition_rules,
    tree_shardings,
)

__all__ = [
    "ShardingPlan", "data_parallel", "fsdp", "zero1", "tensor_parallel",
    "resolve_plan", "build_mesh", "compile_step", "PlannedStep",
    "per_chip_bytes", "serialize_specs", "deserialize_specs",
    "PLAN_NAMES",
]

#: names ``ZOO_SHARDING_PLAN`` / ``resolve_plan`` accept (tensor
#: parallelism needs a rule table, so it is constructed in code, not
#: named from the environment)
PLAN_NAMES = ("dp", "data_parallel", "none", "fsdp", "zero1")

_REPLICATE_ALL = ((r".*", P()),)


def _freeze_rules(rules):
    out = []
    for pat, spec in rules:
        if isinstance(spec, str):
            # P(*"model") would silently splat into per-character axes
            # ('m','o','d','e','l') that all clamp to replicate — the
            # exact quiet failure the partitioner exists to prevent
            raise TypeError(
                f"rule {pat!r}: spec must be a PartitionSpec (or a "
                f"tuple of axis entries), got the bare string {spec!r} "
                f"— write P({spec!r}) to shard dim 0 over that axis")
        out.append((str(pat), spec if isinstance(spec, P) else P(*spec)))
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """Ordered regex rules → PartitionSpec over logical tree paths, plus
    the compile contract.

    ``param_rules`` / ``opt_rules`` match against
    :func:`~analytics_zoo_tpu.parallel.partition.leaf_path_name` paths
    (``opt_rules=None`` reuses ``param_rules`` — optimizer moments
    mirror the parameter paths under their state prefix, and
    ``re.search`` matching makes the same regexes hit).  ``batch_axes``
    is the mesh axes the leading (batch) dimension shards over.
    ``mode`` picks the compile formulation in :func:`compile_step`:
    ``"jit"`` (GSPMD — XLA inserts collectives from the shardings) or
    ``"shard_map"`` (explicit per-shard program with hand-written
    collectives; requires ``in_specs``/``out_specs`` at compile time).
    """

    name: str
    param_rules: tuple = _REPLICATE_ALL
    opt_rules: tuple | None = None
    batch_axes: tuple = (DATA_AXIS,)
    mode: str = "jit"
    description: str = ""

    def __post_init__(self):
        if self.mode not in ("jit", "shard_map"):
            raise ValueError(
                f"plan mode must be 'jit' or 'shard_map', got {self.mode!r}")
        object.__setattr__(self, "param_rules",
                           _freeze_rules(self.param_rules))
        if self.opt_rules is not None:
            object.__setattr__(self, "opt_rules",
                               _freeze_rules(self.opt_rules))
        object.__setattr__(self, "batch_axes", tuple(self.batch_axes))

    # -- identity ------------------------------------------------------
    def cache_key(self) -> tuple:
        """Hashable identity for compiled-step caches: two plans with
        the same rules compile the same program."""
        return (self.name, self.param_rules, self.opt_rules,
                self.batch_axes, self.mode)

    @property
    def effective_opt_rules(self) -> tuple:
        return self.opt_rules if self.opt_rules is not None \
            else self.param_rules

    def _is_replicated(self, rules) -> bool:
        return all(spec == P() for _, spec in rules)

    @property
    def shards_params(self) -> bool:
        return not self._is_replicated(self.param_rules)

    @property
    def shards_opt(self) -> bool:
        return not self._is_replicated(self.effective_opt_rules)

    # -- spec resolution ----------------------------------------------
    def param_specs(self, params, mesh, *, report_unused: bool = False):
        """Clamped PartitionSpec tree for ``params`` on ``mesh``."""
        return self._specs(self.param_rules, params, mesh,
                           report_unused=report_unused)

    def opt_specs(self, opt_state, mesh):
        """Clamped PartitionSpec tree for an optimizer state on
        ``mesh`` (scalar step counts replicate via the scalar rule in
        ``match_partition_rules``)."""
        return self._specs(self.effective_opt_rules, opt_state, mesh)

    def _specs(self, rules, tree, mesh, *, report_unused: bool = False):
        out = match_partition_rules(rules, tree,
                                    report_unused=report_unused)
        specs, unused = out if report_unused else (out, None)
        clamped = jax.tree_util.tree_map(
            lambda leaf, spec: _clamp_spec(spec, np.shape(leaf), mesh),
            tree, specs)
        return (clamped, unused) if report_unused else clamped

    def batch_spec(self, ndim: int, stacked: bool = False) -> P:
        """Spec for one batch leaf: batch dim over ``batch_axes``.

        ``stacked=True`` is the fused-dispatch [K, batch, ...] layout —
        axis 0 is the inner-step index (replicated), axis 1 the batch.
        """
        entry = self.batch_axes[0] if len(self.batch_axes) == 1 \
            else tuple(self.batch_axes)
        min_ndim = 2 if stacked else 1
        if ndim < min_ndim:
            return P()
        lead = (None, entry) if stacked else (entry,)
        return P(*lead, *([None] * (ndim - len(lead))))

    # -- placement -----------------------------------------------------
    def param_shardings(self, params, mesh):
        return tree_shardings(mesh, self.param_specs(params, mesh))

    def opt_shardings(self, opt_state, mesh):
        return tree_shardings(mesh, self.opt_specs(opt_state, mesh))

    def place_params(self, params, mesh):
        """device_put ``params`` into this plan's layout."""
        return jax.device_put(params, self.param_shardings(params, mesh))

    def place_opt_state(self, opt_state, mesh):
        """device_put an optimizer state into this plan's layout — the
        ONE resharding path elastic resume uses: a checkpoint stores
        global logical arrays, so restoring onto any mesh size is this
        device_put (no layout surgery; contrast
        :func:`~analytics_zoo_tpu.parallel.strategies.
        reshard_zero1_opt_state`, which the explicit padded-flat-vector
        layout still needs)."""
        return jax.device_put(opt_state,
                              self.opt_shardings(opt_state, mesh))

    # -- in-graph constraints -----------------------------------------
    def constrain_params(self, params, mesh):
        """``with_sharding_constraint`` the updated params to the plan
        layout (inside the jitted step) — pins the OUTPUT layout so
        donation reuses the plan's buffers, XLA cannot 'helpfully'
        replicate an fsdp plan's weights, AND a partially-sharded plan
        cannot leak its sharding into replicated outputs (zero1's
        sharded moments would otherwise propagate onto the updated
        params, silently changing the step's signature).  A fully
        replicated plan (dp) constrains nothing."""
        if not (self.shards_params or self.shards_opt):
            return params
        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, params,
            self.param_shardings(params, mesh))

    def constrain_opt(self, opt_state, mesh):
        if not (self.shards_params or self.shards_opt):
            return opt_state
        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, opt_state,
            self.opt_shardings(opt_state, mesh))


def _clamp_spec(spec: P, shape: tuple, mesh) -> P:
    """Clamp a rule's spec to what ``mesh`` can divide on this leaf:
    axes missing from the mesh drop to None, a dim the axis product does
    not divide evenly drops to None, entries beyond the leaf's rank are
    truncated.  A rule table written for ``{data: 8, model: 4}`` then
    stays valid on ``{data: 2}`` — undividable dims just replicate."""
    if spec == P():
        return spec
    entries = list(spec)[: len(shape)]
    out = []
    for dim, entry in zip(shape, entries):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        sizes = [dict(mesh.shape).get(a) for a in axes]
        if any(s is None for s in sizes):
            out.append(None)
            continue
        total = math.prod(sizes)
        if total <= 1 or dim % total != 0:
            out.append(None)
            continue
        out.append(tuple(axes) if len(axes) > 1 else axes[0])
    while out and out[-1] is None:
        out.pop()
    return P(*out)


# ---------------------------------------------------------------------------
# Canned plans — FSDP/TP/ZeRO as rule sets instead of bespoke programs.
# ---------------------------------------------------------------------------


def data_parallel() -> ShardingPlan:
    """Replicated parameters + optimizer state, batch over ``data`` —
    the historical default, now spelled as a plan."""
    return ShardingPlan(
        name="dp",
        description="replicated params/opt state, batch over data")


def zero1(axis: str = DATA_AXIS) -> ShardingPlan:
    """Params replicated, optimizer state sharded over ``axis``
    (ZeRO-1: 1/n moment memory + update compute per chip).  Subsumes the
    old ``ZOO_SHARD_OPTIMIZER`` GSPMD path."""
    return ShardingPlan(
        name="zero1",
        param_rules=_REPLICATE_ALL,
        opt_rules=((r".*", P(axis)),),
        description=f"replicated params, opt state sharded over {axis}")


def fsdp(axis: str = DATA_AXIS) -> ShardingPlan:
    """Params AND optimizer state sharded over ``axis``: XLA all-gathers
    weights where the forward uses them and reduce-scatters gradients
    into each chip's shard — per-chip param+opt bytes drop ~1/n at an
    unchanged (bit-identical) loss trajectory.  The whole-weight-update
    sharding of arXiv:2004.13336 as a two-line rule set."""
    rules = ((r".*", P(axis)),)
    return ShardingPlan(
        name="fsdp", param_rules=rules, opt_rules=rules,
        description=f"params + opt state sharded over {axis} "
                    "(gather-on-use / reduce-scatter)")


def tensor_parallel(rules, axis: str = MODEL_AXIS,
                    name: str = "tp") -> ShardingPlan:
    """Megatron-style TP from a user rule table over the ``model`` axis
    (e.g. ``[("kernel", P(None, "model"))]``); anything unmatched
    replicates via an appended catch-all."""
    rules = _freeze_rules(rules)
    if not any(pat in (r".*", ".*") for pat, _ in rules):
        rules = rules + _REPLICATE_ALL
    return ShardingPlan(
        name=name, param_rules=rules,
        description=f"tensor parallel over {axis} by rule table")


def resolve_plan(value=None, config=None) -> ShardingPlan:
    """Resolve a plan argument: a :class:`ShardingPlan` passes through,
    a name string maps to its canned plan, ``None`` falls back to
    ``ZOO_SHARDING_PLAN`` (``config.sharding_plan``), then the legacy
    ``ZOO_SHARD_OPTIMIZER`` flag (→ :func:`zero1`), then
    :func:`data_parallel`."""
    if isinstance(value, ShardingPlan):
        return value
    if value is None and config is not None:
        value = getattr(config, "sharding_plan", None)
        if value is None and getattr(config, "shard_optimizer", False):
            return zero1()
    if value is None:
        return data_parallel()
    name = str(value).strip().lower()
    if name == "auto":
        raise ValueError(
            'plan="auto" is resolved by the estimator (the config '
            "oracle picks among dp/zero1/fsdp from predicted per-chip "
            "bytes vs the HBM budget — analysis/oracle.py); pass a "
            "concrete plan or name here")
    if name in ("dp", "data_parallel", "none", ""):
        return data_parallel()
    if name == "fsdp":
        return fsdp()
    if name == "zero1":
        return zero1()
    raise ValueError(
        f"unknown sharding plan {value!r}; valid names: "
        f"{', '.join(PLAN_NAMES)} (tensor_parallel(...) takes a rule "
        "table, so it is built in code, not named)")


# ---------------------------------------------------------------------------
# Mesh builder — plain single-slice, or hybrid ICI×DCN for multi-pod.
# ---------------------------------------------------------------------------


def build_mesh(mesh_shape: Mapping[str, int] | None = None,
               dcn_shape: Mapping[str, int] | int | None = None,
               axes: Sequence[str] | None = None,
               devices=None, slice_groups=None, allow_idle: bool = False,
               dcn_axis: str | None = None) -> Mesh:
    """One mesh builder for every plan.

    Single slice (``dcn_shape`` unset): today's ``Mesh`` — missing axes
    get size 1, leftover devices fold into ``data``.  Multi-pod: the
    DCN-crossing axis goes OUTERMOST and the per-slice (ICI) extents
    come from ``mesh_shape``, via
    :func:`~analytics_zoo_tpu.parallel.multihost.hybrid_mesh` (the
    ``create_hybrid_device_mesh`` layout: inner-axis collectives ride
    ICI, only the outer axis crosses the data-center network).

    ``dcn_shape`` may be a mapping (``{"data": 2}``) or a bare slice
    count — then the crossing axis is ``dcn_axis`` > ``ZOO_DCN_AXIS`` >
    ``"data"``; an axis name not already in ``axes`` (e.g. ``"dcn"``)
    is prepended as a NEW outermost axis, so a plan can shard the batch
    over ``("dcn", "data")`` while keeping model axes ICI-only.
    """
    if dcn_shape is None:
        from analytics_zoo_tpu.common.engine import _infer_mesh_shape

        devices = list(jax.devices()) if devices is None else list(devices)
        axes = tuple(axes) if axes is not None else tuple(
            a for a in ALL_AXES if a in (mesh_shape or {})) or (DATA_AXIS,)
        shape = _infer_mesh_shape(devices, axes, mesh_shape)
        n_used = math.prod(shape.values())
        dev = np.asarray(devices[:n_used]).reshape(
            [shape[a] for a in axes])
        return Mesh(dev, axes)

    from analytics_zoo_tpu.parallel.multihost import hybrid_mesh

    ici = dict(mesh_shape or {})
    if isinstance(dcn_shape, int):
        axis = dcn_axis or os.environ.get("ZOO_DCN_AXIS") or DATA_AXIS
        dcn_shape = {axis: int(dcn_shape)}
    else:
        dcn_shape = dict(dcn_shape)
    if axes is None:
        named = [a for a in ALL_AXES if a in ici or a in dcn_shape]
        extra = [a for a in dcn_shape if a not in named]
        axes = tuple(extra + named)
    else:
        axes = tuple(axes)
        missing = [a for a in dcn_shape if a not in axes]
        axes = tuple(missing) + axes
    return hybrid_mesh(ici, dcn_shape, axes=axes, devices=devices,
                       slice_groups=slice_groups, allow_idle=allow_idle)


# ---------------------------------------------------------------------------
# compile_step — THE choke point.
# ---------------------------------------------------------------------------


class PlannedStep:
    """A step function compiled through the choke point.

    Call it like the function it wraps: the first call per input
    signature lowers and compiles through
    :func:`~analytics_zoo_tpu.common.compile_cache.timed_compile`
    (persistent-cache hit/miss counters, ``zoo_compile_seconds``, the
    HLO graph lint + ``zoo_hlo_*`` cost features), caches the
    executable, and later calls dispatch it directly — so the in-loop
    cost is one pytree signature probe + the XLA execute.  Signatures
    key on tree structure, leaf shape/dtype/weak-type AND sharding (a
    resharded input is a different program; python scalars key on
    their type).  The probe is a Python-level tree_flatten per call —
    microseconds against a training dispatch, and the fused scan-K
    path (ZOO_STEPS_PER_DISPATCH) amortizes it K-fold; the dispatch
    quick-tier bench guards pin that the trade holds.
    """

    _MAX_EXES = 32  # tail-batch shape churn bound; oldest evicted

    def __init__(self, jitted, label: str, plan: ShardingPlan,
                 meta: dict | None = None):
        self._jitted = jitted
        self.label = label
        self.plan = plan
        # compile context forwarded into the zoo-hlo-report/2 rows
        # (plan name, mesh axis shape, steps_per_dispatch K)
        self.meta = dict(meta) if meta else {"plan": plan.name}
        self._exes: dict = {}

    def _sig(self, args) -> tuple:
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                sig.append((leaf.shape, leaf.dtype,
                            getattr(leaf, "weak_type", False),
                            leaf.sharding))
            elif hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                sig.append((tuple(leaf.shape), leaf.dtype, False, None))
            else:
                # python scalars: the TYPE is the signature — an int and
                # a float at the same position are different programs
                # (int32 vs f32 weak avals), and the AOT executable
                # rejects a mismatched aval instead of recompiling
                sig.append(type(leaf))
        return treedef, tuple(sig)

    def lower(self, *args):
        """The underlying ``jit(...).lower`` — for callers that need the
        lowered module (HLO inspection); normal use just calls the
        step."""
        return self._jitted.lower(*args)

    def __call__(self, *args):
        from analytics_zoo_tpu.common.compile_cache import timed_compile

        key = self._sig(args)
        exe = self._exes.get(key)
        if exe is None:
            exe = timed_compile(self._jitted.lower(*args), self.label,
                                meta=self.meta)
            while len(self._exes) >= self._MAX_EXES:
                self._exes.pop(next(iter(self._exes)))
            self._exes[key] = exe
        return exe(*args)


def compile_step(step_fn, plan: ShardingPlan | None = None, mesh=None, *,
                 donate_argnums=(), label: str | None = None,
                 in_specs=None, out_specs=None, check_vma: bool = False,
                 meta: dict | None = None) -> PlannedStep:
    """Compile a step function under a plan — the ONE entry every
    strategy uses (SNIPPETS [2] Titanax shape).

    ``mode="jit"`` plans run GSPMD: the caller device_puts inputs into
    the plan layout (:meth:`ShardingPlan.place_params` /
    ``place_opt_state``) and constrains outputs in-graph
    (:meth:`ShardingPlan.constrain_params`); XLA inserts the
    collectives.  ``mode="shard_map"`` plans wrap ``step_fn`` in
    ``jax.shard_map`` with the given ``in_specs``/``out_specs`` — the
    explicit-collectives formulation the legacy strategies use.  Either
    way the result lowers through ``timed_compile``: persistent cache,
    AOT warmup, compile metering and the HLO lint/feature pipe apply to
    EVERY plan.

    ``label`` names the program in ``zoo_compile_seconds{label=}`` /
    ``zoo_hlo_*{label=}`` (default ``<plan.name>_step``); ``meta``
    adds compile context (mesh axis shape, steps_per_dispatch) to the
    plan name in each ``zoo-hlo-report/2`` row.
    """
    plan = resolve_plan(plan)
    if plan.mode == "shard_map" or in_specs is not None:
        if in_specs is None or out_specs is None:
            raise ValueError(
                "shard_map-mode plans need explicit in_specs/out_specs")
        if mesh is None:
            from analytics_zoo_tpu.common.engine import get_zoo_context

            mesh = get_zoo_context().mesh
        step_fn = jax.shard_map(step_fn, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_vma=check_vma)
    jitted = jax.jit(step_fn, donate_argnums=donate_argnums)
    full_meta = {"plan": plan.name, **(meta or {})}
    if "mesh_shape" not in full_meta and mesh is not None:
        full_meta["mesh_shape"] = dict(mesh.shape)
    return PlannedStep(jitted, label or f"{plan.name}_step", plan,
                       meta=full_meta)


# ---------------------------------------------------------------------------
# Introspection + checkpoint serialization helpers.
# ---------------------------------------------------------------------------


def per_chip_bytes(tree, device=None) -> int:
    """Bytes of ``tree`` resident on ONE device (default: the first
    device of the first leaf's sharding) — the quantity an fsdp/zero1
    plan shrinks.  Replicated leaves count full size; sharded leaves
    count one shard."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        shards = leaf.addressable_shards
        if not shards:
            continue
        if device is None:
            device = shards[0].device
        total += sum(s.data.nbytes for s in shards if s.device == device)
    return total


def serialize_specs(spec_tree) -> list:
    """PartitionSpec tree → plain-builtin leaves list (tree_leaves
    order) for checkpoint payloads: each spec becomes a list whose
    entries are None / axis name / list of axis names — survives
    ``safe_load`` without any custom-type allowlisting."""
    flat = jax.tree_util.tree_leaves(
        spec_tree, is_leaf=lambda s: isinstance(s, P))
    return [[list(e) if isinstance(e, (tuple, list)) else e
             for e in spec] for spec in flat]


def deserialize_specs(serialized: list) -> list:
    """Inverse of :func:`serialize_specs` (a flat list of
    PartitionSpecs, paired by position with the tree's leaves)."""
    return [P(*[tuple(e) if isinstance(e, list) else e for e in entries])
            for entries in serialized]
