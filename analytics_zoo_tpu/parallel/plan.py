# zoolint: disable-file=raw-jit,raw-remat -- this module IS the compile choke point: the jax.jit here is the one every plan routes through (timed_compile telemetry, persistent cache, HLO lint), and apply_remat is the one jax.checkpoint site every remat rule resolves to
"""zooplan — the unified partitioner: sharding plans + ONE compile entry.

Before this module, sharding decisions were scattered per strategy:
``parallel/strategies.py`` hand-wrote shard_map specs, the zero1
resharder re-laid optimizer state ad hoc, and the estimator's
``ZOO_SHARD_OPTIMIZER`` path picked its own NamedShardings.  FSDP/TP
were bespoke programs.  Here they are CONFIGURATIONS:

- A :class:`ShardingPlan` carries ordered regex rules → ``PartitionSpec``
  over the logical parameter / optimizer-state tree paths (T5X-style;
  ``match_partition_rules`` in :mod:`.partition` does the matching) plus
  the compile contract (jit + GSPMD constraints, or explicit shard_map).
  Specs are CLAMPED per leaf to what the mesh can actually divide, so a
  rule table written for one topology stays valid on another.
- Canned plans: :func:`data_parallel` (replicate everything — today's
  default), :func:`zero1` (optimizer state sharded over ``data``, the
  ZeRO-1 memory win), :func:`fsdp` (params AND optimizer state sharded
  over ``data`` — XLA all-gathers params on use and reduce-scatters
  grads, the ZeRO-2/3 direction of arXiv:2004.13336), and
  :func:`tensor_parallel` (user rules over the ``model`` axis).
- :func:`build_mesh` — one mesh builder: a plain ``Mesh`` on a single
  slice, a hybrid ICI×DCN mesh (DCN-crossing axis outermost, riding
  :func:`~analytics_zoo_tpu.parallel.multihost.hybrid_mesh`) for
  multi-pod; ``ZOO_DCN_AXIS`` names the crossing axis.
- :func:`compile_step` — THE compile choke point.  Every strategy's
  step function (plain DP, fsdp, zero1, TP, explicit shard_map) lowers
  through :func:`~analytics_zoo_tpu.common.compile_cache.timed_compile`
  here, so every compiled program shares the persistent compile cache,
  AOT warmup, ``zoo_compile_seconds`` metering, and the HLO graph
  lint / analytic cost features (``zoo_hlo_*``) — none of which the
  explicit strategies saw before.

Loss trajectories are placement-invariant: a plan changes WHERE bytes
live and which collectives XLA inserts, never the math — the fsdp plan
trains bit-identically to replicated DP (pinned by
``tests/test_partitioner.py`` and ``bench.py --partition``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import os
import re
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.common.engine import (
    ALL_AXES,
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    logger,
)
from analytics_zoo_tpu.parallel.partition import (
    match_partition_rules,
    tree_shardings,
)

__all__ = [
    "ShardingPlan", "data_parallel", "fsdp", "zero1", "zero2", "zero3",
    "tensor_parallel", "pipeline_plan", "with_remat",
    "with_dtype", "with_dtype_policy", "mixed_precision", "int8_serving",
    "resolve_dtype_rules", "DTYPE_ROLES", "DTYPE_POLICY_NAMES",
    "with_kernels", "resolve_kernel", "KERNEL_NAMES",
    "DEFAULT_KERNEL_RULES",
    "resolve_plan", "build_mesh", "compile_step", "PlannedStep",
    "apply_remat", "resolve_remat", "REMAT_POLICIES",
    "per_chip_bytes", "live_bytes", "record_mem_gauges",
    "record_dtype_gauges", "record_kernel_gauges",
    "serialize_specs", "deserialize_specs",
    "PLAN_NAMES", "DEFAULT_BUCKET_BYTES", "default_bucket_bytes",
    "grad_bucket_indices", "fold_world_to_mesh",
]

#: names ``ZOO_SHARDING_PLAN`` / ``resolve_plan`` accept (tensor
#: parallelism needs a rule table, so it is constructed in code, not
#: named from the environment)
PLAN_NAMES = ("dp", "data_parallel", "none", "fsdp", "zero1", "zero2",
              "zero3")

#: remat policy names a plan's ``remat_rules`` may map a path to —
#: ``"full"`` recomputes everything in the matched scope, ``"dots"``
#: keeps contraction outputs (``dots_with_no_batch_dims_saveable``),
#: ``"attn"`` keeps only tensors tagged ``checkpoint_name(
#: "attn_context")``; any other string resolves as an attribute of
#: ``jax.checkpoint_policies``
REMAT_POLICIES = ("full", "dots", "attn")

#: dtype ROLES a plan's ``dtype_rules`` may map a path to.  A role is
#: not a raw dtype: it names the leaf's job in the precision plane.
#: ``"f32"`` = master/accumulation precision (keep the stored f32 copy —
#: the default for every unmatched leaf); ``"bf16"`` / ``"f16"`` =
#: low-precision COMPUTE copy (the stored master stays f32; the step
#: casts down on use and the f32 cast-up happens before the optimizer
#: update, so optimizer state is bitwise-stable); ``"int8"`` =
#: weight-only quantized serving copy (training computes in bf16, the
#: serving replica routes through ``pipeline/inference/quantize.py``).
DTYPE_ROLES = ("f32", "bf16", "f16", "int8")

#: canned policy names ``ZOO_DTYPE_POLICY`` / :func:`resolve_dtype_rules`
#: accept (besides a ``<regex>=<role>,...`` rule string, and ``auto``
#: which the estimator resolves through the config oracle)
DTYPE_POLICY_NAMES = ("f32", "bf16_mixed", "int8_serving")

#: kernel names a plan's ``kernel_rules`` may map a scope to.  ``"xla"``
#: is the explicit opt-out — the scope runs whatever fusion XLA emits
#: (every kernel's jnp fallback path); the rest name modules under
#: ``ops/pallas/``.  Scopes are logical op names, not leaf paths:
#: ``"attention"``, ``"optimizer.adam"``, ``"loss.softmax_xent"``,
#: ``"serving.int8_matmul"``.
KERNEL_NAMES = ("xla", "flash", "fused_adam", "fused_softmax_xent",
                "int8_matmul")

#: the full kernel table :func:`with_kernels` applies by default — one
#: rule per kernel the plane ships.  ``ZOO_USE_PALLAS=1`` overlays this
#: on the resolved plan (a plan with its OWN kernel_rules wins).
DEFAULT_KERNEL_RULES = (
    (r"^attention$", "flash"),
    (r"^optimizer\.adam$", "fused_adam"),
    (r"^loss\.softmax_xent$", "fused_softmax_xent"),
    (r"^serving\.int8_matmul$", "int8_matmul"),
)

#: the compute dtype each role casts floating leaves to inside the step
#: (``None`` = keep the stored dtype).  The ``"int8"`` role computes in
#: bf16 during TRAINING — int8 is a weight-only serving transform, not
#: a training number format.
_ROLE_COMPUTE_DTYPES = {"f32": None, None: None,
                        "bf16": "bfloat16", "f16": "float16",
                        "int8": "bfloat16"}

#: default gradient-overlap bucket size (bytes) when a canned plan is
#: built with ``overlap=True`` — override per process with
#: ``ZOO_OVERLAP_BUCKET_BYTES`` or per plan with ``overlap=<bytes>``.
#: ~4 MiB groups enough small leaves to amortize a collective's latency
#: without deferring the first reduce behind the whole backward.
DEFAULT_BUCKET_BYTES = 4 << 20

_REPLICATE_ALL = ((r".*", P()),)


def fold_world_to_mesh(world: int, devices: int | None = None) -> int:
    """Largest usable data-axis extent for an elastic cohort of
    ``world`` workers: the biggest power of two <= min(world, devices).

    An elastic generation change can leave ANY world size (lose one of
    four workers -> 3), but mesh extents must divide the device count
    (``_infer_mesh_shape``) and real pod topologies only expose
    power-of-two slices — so the cohort folds down to the largest
    feasible slice and the spare workers stand by as hot spares until
    the next generation.  The checkpoint stores global logical arrays,
    so folding 4 -> 2 -> 4 reshards bit-exactly through the plan's
    placement (tests/test_elastic_resume.py)."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if devices is None:
        devices = len(jax.devices())
    cap = min(int(world), max(int(devices), 1))
    return 1 << (cap.bit_length() - 1)


def default_bucket_bytes() -> int:
    """The overlap bucket size ``overlap=True`` resolves to:
    ``ZOO_OVERLAP_BUCKET_BYTES`` (validated > 0) over
    :data:`DEFAULT_BUCKET_BYTES`."""
    raw = os.environ.get("ZOO_OVERLAP_BUCKET_BYTES")
    if not raw:
        return DEFAULT_BUCKET_BYTES
    try:
        out = int(raw)
    except ValueError:
        raise ValueError(
            f"ZOO_OVERLAP_BUCKET_BYTES must be a positive integer byte "
            f"count, got {raw!r}") from None
    if out < 1:
        raise ValueError(
            f"ZOO_OVERLAP_BUCKET_BYTES must be >= 1, got {out}")
    return out


def grad_bucket_indices(leaves, bucket_bytes: int) -> list:
    """Group leaf INDICES into ~``bucket_bytes`` buckets in REVERSE
    traversal order — the order the backward pass completes gradients
    (last forward layer first), so bucket k's collective can be issued
    while bucket k+1's backward segment is still computing.  Every
    bucket holds at least one leaf (a single leaf larger than the
    bucket is its own bucket)."""
    buckets, cur, size = [], [], 0
    for idx in reversed(range(len(leaves))):
        leaf = leaves[idx]
        nbytes = int(getattr(leaf, "nbytes", 0) or
                     np.size(leaf) * np.dtype(
                         getattr(leaf, "dtype", np.float32)).itemsize)
        cur.append(idx)
        size += nbytes
        if size >= bucket_bytes:
            buckets.append(cur)
            cur, size = [], 0
    if cur:
        buckets.append(cur)
    return buckets


def _chain_buckets(leaves, buckets):
    """Pin the buckets' schedule with an ``optimization_barrier`` chain:
    bucket k+1's values pass through a barrier together with a token
    aliased from bucket k's output, so XLA cannot collapse the bucketed
    collectives back into one post-backward group.  Identity on values
    (bitwise — the trajectory cannot change), and only used OUTSIDE
    differentiated regions (``optimization_barrier`` has no AD rule;
    the differentiable spelling is :func:`_sched_barrier`)."""
    out = list(leaves)
    token = None
    for bucket in buckets:
        vals = tuple(out[i] for i in bucket)
        if token is None:
            vals = jax.lax.optimization_barrier(vals)
        else:
            chained = jax.lax.optimization_barrier(vals + (token,))
            vals = chained[:-1]
        for i, v in zip(bucket, vals):
            out[i] = v
        token = vals[0]
    return out


@jax.custom_vjp
def _sched_barrier(values: tuple):
    """Differentiable schedule barrier: identity on ``values`` with an
    ``optimization_barrier`` in BOTH directions — the forward barrier
    pins the prefetch-gather order, and the transpose barrier pins the
    matching reduce order in the backward pass."""
    return jax.lax.optimization_barrier(values)


def _sched_barrier_fwd(values):
    return jax.lax.optimization_barrier(values), None


def _sched_barrier_bwd(_, cts):
    return (jax.lax.optimization_barrier(tuple(cts)),)


_sched_barrier.defvjp(_sched_barrier_fwd, _sched_barrier_bwd)


def _freeze_rules(rules):
    out = []
    for pat, spec in rules:
        if isinstance(spec, str):
            # P(*"model") would silently splat into per-character axes
            # ('m','o','d','e','l') that all clamp to replicate — the
            # exact quiet failure the partitioner exists to prevent
            raise TypeError(
                f"rule {pat!r}: spec must be a PartitionSpec (or a "
                f"tuple of axis entries), got the bare string {spec!r} "
                f"— write P({spec!r}) to shard dim 0 over that axis")
        out.append((str(pat), spec if isinstance(spec, P) else P(*spec)))
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """Ordered regex rules → PartitionSpec over logical tree paths, plus
    the compile contract.

    ``param_rules`` / ``opt_rules`` match against
    :func:`~analytics_zoo_tpu.parallel.partition.leaf_path_name` paths
    (``opt_rules=None`` reuses ``param_rules`` — optimizer moments
    mirror the parameter paths under their state prefix, and
    ``re.search`` matching makes the same regexes hit).  ``batch_axes``
    is the mesh axes the leading (batch) dimension shards over.
    ``mode`` picks the compile formulation in :func:`compile_step`:
    ``"jit"`` (GSPMD — XLA inserts collectives from the shardings) or
    ``"shard_map"`` (explicit per-shard program with hand-written
    collectives; requires ``in_specs``/``out_specs`` at compile time).

    ``grad_rules`` extends the rule table to the GRADIENTS inside the
    step (``None`` = unconstrained, today's behavior): zero2/zero3 pin
    grads to per-chip shards so XLA reduce-scatters instead of
    all-reducing — the weight-update sharding of arXiv:2004.13336.
    ``remat_rules`` maps logical scope names (layer names, ``"blocks"``)
    to a :data:`REMAT_POLICIES` entry; :func:`resolve_remat` consults
    the plan active during tracing, so activation checkpointing is plan
    configuration, not a per-layer flag.

    ``dtype_rules`` is the FOURTH rule table — the precision plane:
    ordered ``(regex, role)`` pairs over the same logical leaf paths,
    where the role is a :data:`DTYPE_ROLES` name.  The stored params
    stay the MASTER copy (f32); a ``"bf16"``/``"f16"`` role makes the
    step cast that leaf down on use (:meth:`cast_params_for_compute`),
    and because the cast is in-graph, the vjp's cast-up hands f32
    gradients back to the f32 masters — gradient/collective
    accumulation and the optimizer update stay in f32 (bitwise-stable
    optimizer state, arXiv:2004.13336's sharded-master shape).  The
    ``"int8"`` role marks weight-only serving leaves for
    ``pipeline/inference/quantize.py``.  Scalars and unmatched leaves
    keep their stored dtype.  Participates in :meth:`cache_key`, so
    the persistent compile cache and per-plan labels distinguish
    precision variants.

    ``kernel_rules`` is the FIFTH rule table — the kernel plane:
    ordered ``(regex, kernel)`` pairs over logical OP scopes
    (``"attention"``, ``"optimizer.adam"``, ``"loss.softmax_xent"``,
    ``"serving.int8_matmul"``), where the kernel is a
    :data:`KERNEL_NAMES` entry.  Consumers ask
    :func:`resolve_kernel` during tracing (the plan is active inside
    ``compile_step``, like ``remat_rules``): a named kernel routes the
    scope to its ``ops/pallas/`` module, ``"xla"`` explicitly pins the
    jnp/XLA fallback (a table with every scope at ``"xla"`` is
    trajectory-identical to no table), and no match leaves the
    consumer's own heuristics in charge.  Participates in
    :meth:`cache_key`; :func:`with_kernels` appends the default table
    and the ``+kernels`` name suffix.

    ``bucket_bytes`` turns on bucketed gradient overlap (the latency-
    hiding plane): inside the step, gradients are grouped into
    ~bucket-sized chunks in backward-completion order and each group's
    reduction collective is pinned (via an ``optimization_barrier``
    chain) to issue as soon as that group's backward segment completes,
    instead of all collectives queuing behind the full backward.
    Identity on values — the trajectory stays bitwise equal to the
    unbucketed plan.  ``prefetch`` adds the fsdp gather-on-use
    schedule: sharded params are explicitly gathered bucket-by-bucket
    ahead of use (double-buffered order pin via
    :func:`_sched_barrier`), so layer k+1's all-gather can overlap
    layer k's compute under a latency-hiding scheduler.
    """

    name: str
    param_rules: tuple = _REPLICATE_ALL
    opt_rules: tuple | None = None
    batch_axes: tuple = (DATA_AXIS,)
    mode: str = "jit"
    description: str = ""
    grad_rules: tuple | None = None
    remat_rules: tuple = ()
    bucket_bytes: int | None = None
    prefetch: bool = False
    dtype_rules: tuple = ()
    kernel_rules: tuple = ()

    def __post_init__(self):
        if self.mode not in ("jit", "shard_map"):
            raise ValueError(
                f"plan mode must be 'jit' or 'shard_map', got {self.mode!r}")
        if self.bucket_bytes is not None:
            bb = int(self.bucket_bytes)
            if bb < 1:
                raise ValueError(
                    f"bucket_bytes must be a positive byte count, "
                    f"got {self.bucket_bytes!r}")
            object.__setattr__(self, "bucket_bytes", bb)
        object.__setattr__(self, "param_rules",
                           _freeze_rules(self.param_rules))
        if self.opt_rules is not None:
            object.__setattr__(self, "opt_rules",
                               _freeze_rules(self.opt_rules))
        if self.grad_rules is not None:
            object.__setattr__(self, "grad_rules",
                               _freeze_rules(self.grad_rules))
        remat = []
        for pat, policy in self.remat_rules:
            if policy is not None and not isinstance(policy, str):
                raise TypeError(
                    f"remat rule {pat!r}: policy must be a name from "
                    f"REMAT_POLICIES (or a jax.checkpoint_policies "
                    f"attribute name, or None), got {policy!r}")
            remat.append((str(pat), policy))
        object.__setattr__(self, "remat_rules", tuple(remat))
        dtyped = []
        for pat, role in self.dtype_rules:
            if role is not None and role not in DTYPE_ROLES:
                raise ValueError(
                    f"dtype rule {pat!r}: role must be one of "
                    f"{DTYPE_ROLES} (or None to keep the stored dtype), "
                    f"got {role!r}")
            dtyped.append((str(pat), role))
        object.__setattr__(self, "dtype_rules", tuple(dtyped))
        kerneled = []
        for pat, kernel in self.kernel_rules:
            if kernel is not None and kernel not in KERNEL_NAMES:
                raise ValueError(
                    f"kernel rule {pat!r}: kernel must be one of "
                    f"{KERNEL_NAMES} (or None to defer to later rules), "
                    f"got {kernel!r}")
            kerneled.append((str(pat), kernel))
        object.__setattr__(self, "kernel_rules", tuple(kerneled))
        object.__setattr__(self, "batch_axes", tuple(self.batch_axes))

    # -- identity ------------------------------------------------------
    def cache_key(self) -> tuple:
        """Hashable identity for compiled-step caches: two plans with
        the same rules compile the same program."""
        return (self.name, self.param_rules, self.opt_rules,
                self.batch_axes, self.mode, self.grad_rules,
                self.remat_rules, self.bucket_bytes, self.prefetch,
                self.dtype_rules, self.kernel_rules)

    @property
    def effective_opt_rules(self) -> tuple:
        return self.opt_rules if self.opt_rules is not None \
            else self.param_rules

    def _is_replicated(self, rules) -> bool:
        return all(spec == P() for _, spec in rules)

    @property
    def shards_params(self) -> bool:
        return not self._is_replicated(self.param_rules)

    @property
    def shards_opt(self) -> bool:
        return not self._is_replicated(self.effective_opt_rules)

    # -- spec resolution ----------------------------------------------
    def param_specs(self, params, mesh, *, report_unused: bool = False):
        """Clamped PartitionSpec tree for ``params`` on ``mesh``."""
        return self._specs(self.param_rules, params, mesh,
                           report_unused=report_unused)

    def opt_specs(self, opt_state, mesh):
        """Clamped PartitionSpec tree for an optimizer state on
        ``mesh`` (scalar step counts replicate via the scalar rule in
        ``match_partition_rules``)."""
        return self._specs(self.effective_opt_rules, opt_state, mesh)

    def _specs(self, rules, tree, mesh, *, report_unused: bool = False):
        out = match_partition_rules(rules, tree,
                                    report_unused=report_unused)
        specs, unused = out if report_unused else (out, None)
        clamped = jax.tree_util.tree_map(
            lambda leaf, spec: _clamp_spec(spec, np.shape(leaf), mesh),
            tree, specs)
        return (clamped, unused) if report_unused else clamped

    def batch_spec(self, ndim: int, stacked: bool = False) -> P:
        """Spec for one batch leaf: batch dim over ``batch_axes``.

        ``stacked=True`` is the fused-dispatch [K, batch, ...] layout —
        axis 0 is the inner-step index (replicated), axis 1 the batch.
        """
        entry = self.batch_axes[0] if len(self.batch_axes) == 1 \
            else tuple(self.batch_axes)
        min_ndim = 2 if stacked else 1
        if ndim < min_ndim:
            return P()
        lead = (None, entry) if stacked else (entry,)
        return P(*lead, *([None] * (ndim - len(lead))))

    # -- precision plane ----------------------------------------------
    def dtype_policy_str(self) -> str:
        """Canonical ``<regex>=<role>,...`` rendering of ``dtype_rules``
        (empty string = no policy) — the form compile meta, checkpoint
        plan records and the hlo dtype-policy lint carry; round-trips
        through :func:`resolve_dtype_rules`."""
        return ",".join(
            f"{pat}={role if role is not None else 'keep'}"
            for pat, role in self.dtype_rules)

    def dtype_roles(self, tree) -> dict:
        """Leaf path → matched dtype role, for every non-scalar leaf a
        rule hits (first ``re.search`` over the same
        :func:`~analytics_zoo_tpu.parallel.partition.leaf_path_name`
        paths the other three tables use).  Unmatched leaves are absent
        — they keep master precision."""
        from analytics_zoo_tpu.parallel.partition import (
            leaf_path_name,
        )

        out = {}

        def visit(path, leaf):
            if np.ndim(leaf) == 0 or np.size(leaf) == 1:
                return leaf
            name = leaf_path_name(path)
            for pat, role in self.dtype_rules:
                if re.search(pat, name):
                    if role is not None:
                        out[name] = role
                    break
            return leaf

        jax.tree_util.tree_map_with_path(visit, tree)
        return out

    # -- kernel plane --------------------------------------------------
    def kernel_policy_str(self) -> str:
        """Canonical ``<regex>=<kernel>,...`` rendering of
        ``kernel_rules`` (empty string = no table) — the form compile
        meta and checkpoint plan records carry."""
        return ",".join(
            f"{pat}={kernel if kernel is not None else 'defer'}"
            for pat, kernel in self.kernel_rules)

    def kernel_for(self, scope: str, default: str | None = None):
        """Kernel name for a logical op scope (``"attention"``,
        ``"optimizer.adam"``, ...): first ``kernel_rules``
        ``re.search`` match wins; ``"xla"`` is the explicit fallback
        pick, no match returns ``default``."""
        for pat, kernel in self.kernel_rules:
            if re.search(pat, scope):
                if kernel is not None:
                    return kernel
        return default

    def compute_cast_dtype(self):
        """The dominant low-precision compute dtype this plan's rules
        declare (``jnp.bfloat16`` / ``jnp.float16``), or ``None`` for a
        pure-f32 plan — what batch inputs cast to so the matmuls lower
        in the compute dtype, not a silent f32 upcast."""
        for _, role in self.dtype_rules:
            name = _ROLE_COMPUTE_DTYPES.get(role)
            if name is not None:
                return jax.numpy.dtype(name)
        return None

    def cast_params_for_compute(self, params):
        """The cast-down half of the accumulation contract: a COMPUTE
        copy of ``params`` with each floating leaf whose dtype role is
        ``bf16``/``f16`` (or ``int8`` — weight-only serving leaves
        train in bf16) cast to its role's compute dtype.  The argument
        tree is untouched: it remains the f32 master copy the
        optimizer updates.  In-graph use means the vjp inserts the
        matching cast-up, so gradients arrive f32 at the masters and
        collectives accumulate in f32."""
        if not self.dtype_rules:
            return params
        from analytics_zoo_tpu.parallel.partition import (
            match_rule_values,
        )

        jnp = jax.numpy
        roles = match_rule_values(self.dtype_rules, params, default="f32")

        def cast(leaf, role):
            name = _ROLE_COMPUTE_DTYPES.get(role)
            if name is None or not hasattr(leaf, "dtype") \
                    or not jnp.issubdtype(leaf.dtype, jnp.floating):
                return leaf
            return leaf.astype(jnp.dtype(name))

        return jax.tree_util.tree_map(cast, params, roles)

    # -- placement -----------------------------------------------------
    def param_shardings(self, params, mesh):
        return tree_shardings(mesh, self.param_specs(params, mesh))

    def opt_shardings(self, opt_state, mesh):
        return tree_shardings(mesh, self.opt_specs(opt_state, mesh))

    def place_params(self, params, mesh):
        """device_put ``params`` into this plan's layout."""
        return jax.device_put(params, self.param_shardings(params, mesh))

    def place_opt_state(self, opt_state, mesh):
        """device_put an optimizer state into this plan's layout — the
        ONE resharding path elastic resume uses: a checkpoint stores
        global logical arrays, so restoring onto any mesh size is this
        device_put.  Even the explicit padded-flat-vector layout
        (:func:`~analytics_zoo_tpu.parallel.strategies.
        reshard_zero1_opt_state`) routes its final placement here after
        its host-side pad surgery."""
        return jax.device_put(opt_state,
                              self.opt_shardings(opt_state, mesh))

    # -- in-graph constraints -----------------------------------------
    def constrain_params(self, params, mesh):
        """``with_sharding_constraint`` the updated params to the plan
        layout (inside the jitted step) — pins the OUTPUT layout so
        donation reuses the plan's buffers, XLA cannot 'helpfully'
        replicate an fsdp plan's weights, AND a partially-sharded plan
        cannot leak its sharding into replicated outputs (zero1's
        sharded moments would otherwise propagate onto the updated
        params, silently changing the step's signature).  A fully
        replicated plan (dp) constrains nothing."""
        if not (self.shards_params or self.shards_opt):
            return params
        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, params,
            self.param_shardings(params, mesh))

    def constrain_opt(self, opt_state, mesh):
        if not (self.shards_params or self.shards_opt):
            return opt_state
        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, opt_state,
            self.opt_shardings(opt_state, mesh))

    def constrain_grads(self, grads, mesh):
        """Pin the gradients inside the step to ``grad_rules`` — the
        zero2/zero3 hook: constraining grads to per-chip shards forces
        XLA to lower the gradient sum as a reduce-scatter (each chip
        keeps only its shard) instead of a full all-reduce, so the
        optimizer update runs on 1/n of every leaf.  ``grad_rules=None``
        (dp/zero1/fsdp) leaves the gradients to GSPMD's own choice.

        With ``bucket_bytes`` set, the constrained gradients are
        additionally grouped into ~bucket-sized chunks in backward-
        completion order and schedule-pinned with an
        ``optimization_barrier`` chain (:func:`_chain_buckets`): each
        bucket's reduce-scatter/all-reduce is issued as its backward
        segment completes instead of queueing behind the full backward.
        Values are untouched — the trajectory is bitwise equal to the
        unbucketed plan (the per-leaf reduction grouping is unchanged).
        """
        if self.grad_rules is not None:
            specs = self._specs(self.grad_rules, grads, mesh)
            grads = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, grads,
                tree_shardings(mesh, specs))
        if not self.bucket_bytes:
            return grads
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        arrays = [i for i, leaf in enumerate(leaves)
                  if hasattr(leaf, "dtype")]
        if len(arrays) < 2:
            return grads  # nothing to bucket
        buckets = grad_bucket_indices(
            [leaves[i] for i in arrays], self.bucket_bytes)
        chained = _chain_buckets(
            [leaves[i] for i in arrays],
            buckets)
        for pos, val in zip(arrays, chained):
            leaves[pos] = val
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def prefetch_params(self, params, mesh):
        """The fsdp gather-prefetch schedule: explicitly all-gather
        sharded params bucket-by-bucket IN FORWARD ORDER, each bucket's
        gather chained behind the previous one through the
        differentiable :func:`_sched_barrier` — a double-buffered
        gather-on-use order pin, so bucket k+1's all-gather can issue
        while bucket k's layer computes (XLA's latency-hiding scheduler
        does the overlap; the chain keeps it from collapsing the
        gathers into one prologue group).  The transpose of the
        explicit gather is a reduce-scatter of the cotangent, barriered
        in the matching reverse order — so the backward inherits the
        bucketed reduction schedule for free.  No-op unless the plan
        sets ``prefetch`` and shards params."""
        if not (self.prefetch and self.shards_params):
            return params
        leaves, treedef = jax.tree_util.tree_flatten(params)
        arrays = [i for i, leaf in enumerate(leaves)
                  if hasattr(leaf, "dtype")]
        if not arrays:
            return params
        repl = NamedSharding(mesh, P())
        gathered = [jax.lax.with_sharding_constraint(leaves[i], repl)
                    for i in arrays]
        bucket_bytes = self.bucket_bytes or default_bucket_bytes()
        # forward traversal order: gather the buckets the forward
        # consumes first, first
        buckets = [list(reversed(b)) for b in reversed(
            grad_bucket_indices(gathered, bucket_bytes))]
        token = None
        for bucket in buckets:
            vals = tuple(gathered[i] for i in bucket)
            if token is None:
                vals = _sched_barrier(vals)
            else:
                chained = _sched_barrier(vals + (token,))
                vals = chained[:-1]
            for i, v in zip(bucket, vals):
                gathered[i] = v
            token = vals[0]
        for pos, val in zip(arrays, gathered):
            leaves[pos] = val
        return jax.tree_util.tree_unflatten(treedef, leaves)


def _clamp_spec(spec: P, shape: tuple, mesh) -> P:
    """Clamp a rule's spec to what ``mesh`` can divide on this leaf:
    axes missing from the mesh drop to None, a dim the axis product does
    not divide evenly drops to None, entries beyond the leaf's rank are
    truncated.  A rule table written for ``{data: 8, model: 4}`` then
    stays valid on ``{data: 2}`` — undividable dims just replicate."""
    if spec == P():
        return spec
    entries = list(spec)[: len(shape)]
    out = []
    for dim, entry in zip(shape, entries):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        sizes = [dict(mesh.shape).get(a) for a in axes]
        if any(s is None for s in sizes):
            out.append(None)
            continue
        total = math.prod(sizes)
        if total <= 1 or dim % total != 0:
            out.append(None)
            continue
        out.append(tuple(axes) if len(axes) > 1 else axes[0])
    while out and out[-1] is None:
        out.pop()
    return P(*out)


# ---------------------------------------------------------------------------
# Remat policy — the ONE jax.checkpoint site (zoolint raw-remat keeps it
# that way), plus the active-plan context resolve_remat consults.
# ---------------------------------------------------------------------------

# plans entered by compile_step for the duration of tracing, innermost
# last — resolve_remat walks it top-down so the plan being compiled wins
_ACTIVE_PLANS: list = []


@contextlib.contextmanager
def _active_plan(plan: "ShardingPlan"):
    _ACTIVE_PLANS.append(plan)
    try:
        yield plan
    finally:
        _ACTIVE_PLANS.pop()


def resolve_remat(path: str, default: str | None = None) -> str | None:
    """Remat policy for a logical scope name (a layer name, ``"blocks"``)
    under the plan currently being compiled: first ``remat_rules`` match
    (``re.search``, innermost active plan first) wins; no active plan or
    no match falls back to ``default`` — so a plan's rules SUBSUME the
    per-layer ``remat=`` flag without breaking it."""
    for plan in reversed(_ACTIVE_PLANS):
        for pat, policy in plan.remat_rules:
            if re.search(pat, path):
                return policy
    return default


def resolve_kernel(scope: str, default: str | None = None) -> str | None:
    """Kernel pick for a logical op scope under the plan currently
    being compiled (the kernel-plane twin of :func:`resolve_remat`):
    first ``kernel_rules`` match on the innermost active plan wins.
    ``"xla"`` is an explicit pick — the consumer must take its jnp/XLA
    fallback path; no active plan or no match returns ``default``
    (``None`` = the consumer's own routing heuristics apply, e.g.
    flash's eligibility check).  Consumers: ``ops/attention.py``
    (``"attention"``), the estimator's optimizer swap
    (``"optimizer.adam"``), ``objectives.py``
    (``"loss.softmax_xent"``), ``pipeline/inference/quantize.py``
    (``"serving.int8_matmul"``)."""
    for plan in reversed(_ACTIVE_PLANS):
        kernel = plan.kernel_for(scope)
        if kernel is not None:
            return kernel
    return default


def apply_remat(fn, policy: str | None, *, static_argnums=()):
    """Wrap ``fn`` in ``jax.checkpoint`` under a named policy — the one
    remat site every layer and pipeline schedule routes through.

    ``None`` returns ``fn`` unchanged; ``"full"`` recomputes the whole
    scope in the backward pass (max memory saving, ~1/3 extra FLOPs);
    ``"dots"`` keeps contraction outputs
    (``dots_with_no_batch_dims_saveable``); ``"attn"`` keeps only
    tensors tagged ``checkpoint_name(..., "attn_context")``; any other
    name resolves as an attribute of ``jax.checkpoint_policies``."""
    if policy in (None, "", "none"):
        return fn
    if policy == "full":
        return jax.checkpoint(fn, static_argnums=static_argnums)
    if policy == "dots":
        ckpt_policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    elif policy == "attn":
        ckpt_policy = jax.checkpoint_policies.save_only_these_names(
            "attn_context")
    else:
        try:
            ckpt_policy = getattr(jax.checkpoint_policies, policy)
        except AttributeError:
            raise ValueError(
                f"unknown remat policy {policy!r}; expected one of "
                f"{REMAT_POLICIES} or a jax.checkpoint_policies "
                "attribute name") from None
    return jax.checkpoint(fn, policy=ckpt_policy,
                          static_argnums=static_argnums)


# ---------------------------------------------------------------------------
# Canned plans — FSDP/TP/ZeRO as rule sets instead of bespoke programs.
# ---------------------------------------------------------------------------


def data_parallel() -> ShardingPlan:
    """Replicated parameters + optimizer state, batch over ``data`` —
    the historical default, now spelled as a plan."""
    return ShardingPlan(
        name="dp",
        description="replicated params/opt state, batch over data")


def _overlap_fields(overlap) -> dict:
    """Resolve a canned plan's ``overlap=`` argument: ``False`` → no
    overlap (today's serial schedule), ``True`` → bucketed gradient
    overlap at :func:`default_bucket_bytes`, an int → that bucket size.
    The plan name gains a ``+overlap`` suffix so compile labels, the
    estimator's step cache and the cost model's exposed-fraction lookup
    all see the bucketed variant as a distinct program."""
    if not overlap:
        return {}
    bb = default_bucket_bytes() if overlap is True else int(overlap)
    return {"bucket_bytes": bb}


def zero1(axis: str = DATA_AXIS, overlap=False) -> ShardingPlan:
    """Params replicated, optimizer state sharded over ``axis``
    (ZeRO-1: 1/n moment memory + update compute per chip).  Subsumes the
    old ``ZOO_SHARD_OPTIMIZER`` GSPMD path.  ``overlap`` turns on
    bucketed gradient overlap (``True`` = default bucket size, an int =
    that many bytes per bucket; trajectory stays bitwise)."""
    extra = _overlap_fields(overlap)
    return ShardingPlan(
        name="zero1+overlap" if extra else "zero1",
        param_rules=_REPLICATE_ALL,
        opt_rules=((r".*", P(axis)),),
        description=f"replicated params, opt state sharded over {axis}",
        **extra)


def fsdp(axis: str = DATA_AXIS, overlap=False) -> ShardingPlan:
    """Params AND optimizer state sharded over ``axis``: XLA all-gathers
    weights where the forward uses them and reduce-scatters gradients
    into each chip's shard — per-chip param+opt bytes drop ~1/n at an
    unchanged (bit-identical) loss trajectory.  The whole-weight-update
    sharding of arXiv:2004.13336 as a two-line rule set.  ``overlap``
    adds bucketed gradient overlap AND the double-buffered gather
    prefetch (:meth:`ShardingPlan.prefetch_params` — layer k+1's
    all-gather issues while layer k computes)."""
    rules = ((r".*", P(axis)),)
    extra = _overlap_fields(overlap)
    return ShardingPlan(
        name="fsdp+overlap" if extra else "fsdp",
        param_rules=rules, opt_rules=rules,
        prefetch=bool(extra),
        description=f"params + opt state sharded over {axis} "
                    "(gather-on-use / reduce-scatter)",
        **extra)


def zero2(axis: str = DATA_AXIS, overlap=False) -> ShardingPlan:
    """ZeRO-2 (arXiv:2004.13336): optimizer state sharded AND grads
    reduce-scattered into per-chip shards over ``axis``; params stay
    replicated, so the update all-gathers the new weights once per step
    (grad_rules pin the scatter, constrain_params pins the gather-at-
    update).  Same math as DP — per-chip persistent state matches
    zero1, and the transient gradient buffer drops to 1/n.  ``overlap``
    buckets the reduce-scatters into backward-completion-order groups
    (bitwise trajectory)."""
    shard = ((r".*", P(axis)),)
    extra = _overlap_fields(overlap)
    return ShardingPlan(
        name="zero2+overlap" if extra else "zero2",
        param_rules=_REPLICATE_ALL,
        opt_rules=shard,
        grad_rules=shard,
        description=f"replicated params, opt state + grads sharded over "
                    f"{axis} (reduce-scatter, gather at update)",
        **extra)


def zero3(axis: str = DATA_AXIS, overlap=False) -> ShardingPlan:
    """ZeRO-3: params, optimizer state AND grads all sharded over
    ``axis`` — XLA all-gathers each weight where the forward uses it
    and reduce-scatters its gradient straight into the owning chip's
    shard, so per-chip param+opt state is ~1/n (the fsdp layout with
    the gradient scatter pinned explicitly).  ``overlap`` buckets the
    gradient reduce-scatters and prefetch-gathers the params
    (bitwise trajectory)."""
    shard = ((r".*", P(axis)),)
    extra = _overlap_fields(overlap)
    return ShardingPlan(
        name="zero3+overlap" if extra else "zero3",
        param_rules=shard,
        opt_rules=shard,
        grad_rules=shard,
        prefetch=bool(extra),
        description=f"params + opt state + grads sharded over {axis} "
                    "(gather-on-use, reduce-scatter)",
        **extra)


def pipeline_plan(schedule: str, axis: str = PIPE_AXIS,
                  remat: str | None = None) -> ShardingPlan:
    """Stage assignment as a plan: stage-stacked params (leading dim =
    stage index) shard over the ``pipe`` axis, and the schedule lowers
    through :func:`compile_step` in shard_map mode — so gpipe/1F1B
    share the persistent compile cache, per-plan labels and the
    ``zoo_hlo_*`` feature pipe like every other plan.  ``remat`` adds a
    catch-all remat rule for the stage bodies."""
    return ShardingPlan(
        name=f"pipeline_{schedule}",
        param_rules=((r".*", P(axis)),),
        mode="shard_map",
        remat_rules=((r".*", remat),) if remat else (),
        description=f"{schedule} schedule over the {axis} axis")


def with_remat(plan: ShardingPlan, policy: str = "full",
               pattern: str = r".*") -> ShardingPlan:
    """A copy of ``plan`` with a remat rule appended (and the policy in
    the name, so compile labels and cost-model lookups see it):
    ``with_remat(zero3(), "full")`` → ``"zero3+remat_full"``."""
    return dataclasses.replace(
        plan,
        name=f"{plan.name}+remat_{policy}",
        remat_rules=plan.remat_rules + ((str(pattern), policy),))


def with_dtype(plan: ShardingPlan, role: str = "bf16",
               pattern: str = r".*") -> ShardingPlan:
    """A copy of ``plan`` with a dtype rule appended and the role in the
    name — ``with_dtype(fsdp(), "bf16")`` → ``"fsdp+bf16"``, so compile
    labels, the estimator's step cache and the cost model's
    dtype-dependent ceilings all see the precision variant as a
    distinct program (``_plan_key`` strips ``+`` segments, so sharding
    lookups still resolve)."""
    if role not in DTYPE_ROLES:
        raise ValueError(
            f"dtype role must be one of {DTYPE_ROLES}, got {role!r}")
    return dataclasses.replace(
        plan,
        name=f"{plan.name}+{role}",
        dtype_rules=plan.dtype_rules + ((str(pattern), role),))


def with_kernels(plan: ShardingPlan | str | None = None,
                 rules=DEFAULT_KERNEL_RULES) -> ShardingPlan:
    """A copy of ``plan`` with a ``kernel_rules`` table appended and
    ``+kernels`` suffixed to the name — the kernel-plane twin of
    :func:`with_dtype`.  Compile labels, the estimator's step cache and
    the persistent compile cache all see the kernel variant as a
    distinct program (:meth:`ShardingPlan.cache_key` includes the
    table); ``resolve_plan`` strips the suffix, so checkpoint plan
    records round-trip.  Default rules route every op the plane ships a
    kernel for (:data:`DEFAULT_KERNEL_RULES`); pass an explicit table
    to pick per scope (``(("optimizer.adam", "xla"),)`` forces the
    optax chain)."""
    plan = resolve_plan(plan)
    frozen = ShardingPlan(name="_kernel_probe",
                          kernel_rules=tuple(rules)).kernel_rules
    name = plan.name if plan.name.endswith("+kernels") \
        else f"{plan.name}+kernels"
    return dataclasses.replace(
        plan, name=name, kernel_rules=plan.kernel_rules + frozen)


def mixed_precision(plan: ShardingPlan | str | None = None) -> ShardingPlan:
    """The canned bf16 mixed-precision policy over any base plan:
    bf16 compute params + f32 master copies + f32 gradient/collective
    accumulation.  The stored params ARE the f32 masters; the step
    casts a compute copy down on use and the in-graph vjp casts
    gradients back up before the optimizer update, so optimizer state
    is bitwise-stable and elastic resume reshards the f32 masters
    bit-exact across world sizes (the master copies never leave the
    plan's normal placement path)."""
    return with_dtype(resolve_plan(plan), "bf16")


def int8_serving(plan: ShardingPlan | str | None = None) -> ShardingPlan:
    """The weight-only int8 SERVING policy: matmul-sized weights carry
    the ``"int8"`` role, and a serving replica quantizes exactly those
    leaves through :func:`~analytics_zoo_tpu.pipeline.inference.
    quantize.quantize_params_for_plan` (~4× weight bytes).  Training
    under this plan still computes in bf16 — int8 is a serving
    transform, not a training number format."""
    return with_dtype(resolve_plan(plan), "int8")


def resolve_dtype_rules(value) -> tuple:
    """``dtype_rules`` from a policy spec: ``None``/``""``/``"f32"`` →
    no rules, ``"bf16_mixed"`` → catch-all bf16 compute,
    ``"int8_serving"`` → catch-all int8 weight-only, a
    ``<regex>=<role>,...`` rule string → that table (role ``keep`` /
    ``none`` pins a path to its stored dtype, shadowing later rules),
    or an already-built rule sequence (validated).  ``"auto"`` is
    rejected here the way ``resolve_plan`` rejects ``plan="auto"`` —
    the estimator resolves it through the config oracle's dtype
    sweep."""
    if value is None:
        return ()
    if isinstance(value, (tuple, list)):
        return ShardingPlan(name="_dtype_probe",
                            dtype_rules=tuple(value)).dtype_rules
    name = str(value).strip()
    low = name.lower()
    if low in ("", "f32", "none"):
        return ()
    if low == "bf16_mixed":
        return ((r".*", "bf16"),)
    if low == "int8_serving":
        return ((r".*", "int8"),)
    if low == "auto":
        raise ValueError(
            'dtype policy "auto" is resolved by the estimator (the '
            "config oracle sweeps f32 vs bf16 with dtype-dependent "
            "roofline ceilings — analysis/oracle.py); pass a concrete "
            "policy here")
    rules = []
    for part in name.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"dtype policy rule {part!r} must be '<regex>=<role>' "
                f"with role in {DTYPE_ROLES} (or a policy name from "
                f"{DTYPE_POLICY_NAMES})")
        pat, role = part.rsplit("=", 1)
        role = role.strip().lower()
        if role in ("keep", "none"):
            role = None
        elif role not in DTYPE_ROLES:
            raise ValueError(
                f"dtype policy rule {part!r}: role must be one of "
                f"{DTYPE_ROLES} (or 'keep'), got {role!r}")
        rules.append((pat.strip(), role))
    return tuple(rules)


def with_dtype_policy(plan: ShardingPlan, policy) -> ShardingPlan:
    """Apply a dtype policy spec (anything :func:`resolve_dtype_rules`
    accepts) to ``plan`` — no-op for ``None``/``"f32"``; otherwise the
    rules are appended and the first concrete role suffixes the name
    (``"fsdp"`` + ``"bf16_mixed"`` → ``"fsdp+bf16"``)."""
    rules = resolve_dtype_rules(policy)
    if not rules:
        return plan
    roles = [role for _, role in rules if role is not None]
    name = f"{plan.name}+{roles[0]}" if roles else plan.name
    return dataclasses.replace(
        plan, name=name, dtype_rules=plan.dtype_rules + rules)


def tensor_parallel(rules, axis: str = MODEL_AXIS,
                    name: str = "tp") -> ShardingPlan:
    """Megatron-style TP from a user rule table over the ``model`` axis
    (e.g. ``[("kernel", P(None, "model"))]``); anything unmatched
    replicates via an appended catch-all."""
    rules = _freeze_rules(rules)
    if not any(pat in (r".*", ".*") for pat, _ in rules):
        rules = rules + _REPLICATE_ALL
    return ShardingPlan(
        name=name, param_rules=rules,
        description=f"tensor parallel over {axis} by rule table")


def resolve_plan(value=None, config=None) -> ShardingPlan:
    """Resolve a plan argument: a :class:`ShardingPlan` passes through,
    a name string maps to its canned plan, ``None`` falls back to
    ``ZOO_SHARDING_PLAN`` (``config.sharding_plan``), then the legacy
    ``ZOO_SHARD_OPTIMIZER`` flag (→ :func:`zero1`), then
    :func:`data_parallel`."""
    if isinstance(value, ShardingPlan):
        return value
    if value is None and config is not None:
        value = getattr(config, "sharding_plan", None)
        if value is None and getattr(config, "shard_optimizer", False):
            return zero1()
    if value is None:
        return data_parallel()
    name = str(value).strip().lower()
    if name == "auto":
        raise ValueError(
            'plan="auto" is resolved by the estimator (the config '
            "oracle sweeps dp/zero1/zero2/fsdp/zero3 × remat against "
            "predicted per-chip bytes vs the HBM budget — "
            "analysis/oracle.py); pass a concrete plan or name here")
    # +kernels is appended LAST by with_kernels, so it strips first —
    # then the dtype role, then +overlap (mirrors construction order)
    kernels = False
    if name.endswith("+kernels"):
        kernels = True
        name = name[: -len("+kernels")]
    dtype_role = None
    for role in DTYPE_ROLES:
        if name.endswith("+" + role):
            dtype_role = role
            name = name[: -len(role) - 1]
            break
    overlap = False
    if name.endswith("+overlap"):
        overlap = True
        name = name[: -len("+overlap")]

    def _dtyped(plan: ShardingPlan) -> ShardingPlan:
        # "+f32" names the explicit master-precision variant: same
        # rules-free plan, so it resolves to the base plan unchanged
        if dtype_role in (None, "f32"):
            plan = plan
        else:
            plan = with_dtype(plan, dtype_role)
        return with_kernels(plan) if kernels else plan

    if name in ("dp", "data_parallel", "none", ""):
        if overlap:
            raise ValueError(
                "dp has no collectives to overlap; bucket_bytes applies "
                "to zero1/zero2/zero3/fsdp")
        return _dtyped(data_parallel())
    if name == "fsdp":
        return _dtyped(fsdp(overlap=overlap))
    if name == "zero1":
        return _dtyped(zero1(overlap=overlap))
    if name == "zero2":
        return _dtyped(zero2(overlap=overlap))
    if name == "zero3":
        return _dtyped(zero3(overlap=overlap))
    raise ValueError(
        f"unknown sharding plan {value!r}; valid names: "
        f"{', '.join(PLAN_NAMES)}, optionally suffixed +overlap, "
        f"a dtype role and/or +kernels (e.g. 'fsdp+overlap', "
        f"'zero1+bf16', 'dp+kernels') "
        "(tensor_parallel(...) takes a rule "
        "table, so it is built in code, not named)")


# ---------------------------------------------------------------------------
# Mesh builder — plain single-slice, or hybrid ICI×DCN for multi-pod.
# ---------------------------------------------------------------------------


def build_mesh(mesh_shape: Mapping[str, int] | None = None,
               dcn_shape: Mapping[str, int] | int | None = None,
               axes: Sequence[str] | None = None,
               devices=None, slice_groups=None, allow_idle: bool = False,
               dcn_axis: str | None = None) -> Mesh:
    """One mesh builder for every plan.

    Single slice (``dcn_shape`` unset): today's ``Mesh`` — missing axes
    get size 1, leftover devices fold into ``data``.  Multi-pod: the
    DCN-crossing axis goes OUTERMOST and the per-slice (ICI) extents
    come from ``mesh_shape``, via
    :func:`~analytics_zoo_tpu.parallel.multihost.hybrid_mesh` (the
    ``create_hybrid_device_mesh`` layout: inner-axis collectives ride
    ICI, only the outer axis crosses the data-center network).

    ``dcn_shape`` may be a mapping (``{"data": 2}``) or a bare slice
    count — then the crossing axis is ``dcn_axis`` > ``ZOO_DCN_AXIS`` >
    ``"data"``; an axis name not already in ``axes`` (e.g. ``"dcn"``)
    is prepended as a NEW outermost axis, so a plan can shard the batch
    over ``("dcn", "data")`` while keeping model axes ICI-only.
    """
    if dcn_shape is None:
        from analytics_zoo_tpu.common.engine import _infer_mesh_shape

        devices = list(jax.devices()) if devices is None else list(devices)
        axes = tuple(axes) if axes is not None else tuple(
            a for a in ALL_AXES if a in (mesh_shape or {})) or (DATA_AXIS,)
        shape = _infer_mesh_shape(devices, axes, mesh_shape)
        n_used = math.prod(shape.values())
        dev = np.asarray(devices[:n_used]).reshape(
            [shape[a] for a in axes])
        return Mesh(dev, axes)

    from analytics_zoo_tpu.parallel.multihost import hybrid_mesh

    ici = dict(mesh_shape or {})
    if isinstance(dcn_shape, int):
        axis = dcn_axis or os.environ.get("ZOO_DCN_AXIS") or DATA_AXIS
        dcn_shape = {axis: int(dcn_shape)}
    else:
        dcn_shape = dict(dcn_shape)
    if axes is None:
        named = [a for a in ALL_AXES if a in ici or a in dcn_shape]
        extra = [a for a in dcn_shape if a not in named]
        axes = tuple(extra + named)
    else:
        axes = tuple(axes)
        missing = [a for a in dcn_shape if a not in axes]
        axes = tuple(missing) + axes
    return hybrid_mesh(ici, dcn_shape, axes=axes, devices=devices,
                       slice_groups=slice_groups, allow_idle=allow_idle)


# ---------------------------------------------------------------------------
# compile_step — THE choke point.
# ---------------------------------------------------------------------------


class PlannedStep:
    """A step function compiled through the choke point.

    Call it like the function it wraps: the first call per input
    signature lowers and compiles through
    :func:`~analytics_zoo_tpu.common.compile_cache.timed_compile`
    (persistent-cache hit/miss counters, ``zoo_compile_seconds``, the
    HLO graph lint + ``zoo_hlo_*`` cost features), caches the
    executable, and later calls dispatch it directly — so the in-loop
    cost is one pytree signature probe + the XLA execute.  Signatures
    key on tree structure, leaf shape/dtype/weak-type AND sharding (a
    resharded input is a different program; python scalars key on
    their type).  The probe is a Python-level tree_flatten per call —
    microseconds against a training dispatch, and the fused scan-K
    path (ZOO_STEPS_PER_DISPATCH) amortizes it K-fold; the dispatch
    quick-tier bench guards pin that the trade holds.
    """

    _MAX_EXES = 32  # tail-batch shape churn bound; oldest evicted

    def __init__(self, jitted, label: str, plan: ShardingPlan,
                 meta: dict | None = None):
        self._jitted = jitted
        self.label = label
        self.plan = plan
        # compile context forwarded into the zoo-hlo-report/2 rows
        # (plan name, mesh axis shape, steps_per_dispatch K)
        self.meta = dict(meta) if meta else {"plan": plan.name}
        self._exes: dict = {}

    def _sig(self, args) -> tuple:
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                sig.append((leaf.shape, leaf.dtype,
                            getattr(leaf, "weak_type", False),
                            leaf.sharding))
            elif hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                sig.append((tuple(leaf.shape), leaf.dtype, False, None))
            else:
                # python scalars: the TYPE is the signature — an int and
                # a float at the same position are different programs
                # (int32 vs f32 weak avals), and the AOT executable
                # rejects a mismatched aval instead of recompiling
                sig.append(type(leaf))
        return treedef, tuple(sig)

    def lower(self, *args):
        """The underlying ``jit(...).lower`` — for callers that need the
        lowered module (HLO inspection); normal use just calls the
        step."""
        return self._jitted.lower(*args)

    def __call__(self, *args):
        from analytics_zoo_tpu.common.compile_cache import timed_compile

        key = self._sig(args)
        exe = self._exes.get(key)
        if exe is None:
            exe = timed_compile(self._jitted.lower(*args), self.label,
                                meta=self.meta)
            while len(self._exes) >= self._MAX_EXES:
                self._exes.pop(next(iter(self._exes)))
            self._exes[key] = exe
        return exe(*args)


def compile_step(step_fn, plan: ShardingPlan | None = None, mesh=None, *,
                 donate_argnums=(), label: str | None = None,
                 in_specs=None, out_specs=None, check_vma: bool = False,
                 meta: dict | None = None) -> PlannedStep:
    """Compile a step function under a plan — the ONE entry every
    strategy uses (SNIPPETS [2] Titanax shape).

    ``mode="jit"`` plans run GSPMD: the caller device_puts inputs into
    the plan layout (:meth:`ShardingPlan.place_params` /
    ``place_opt_state``) and constrains outputs in-graph
    (:meth:`ShardingPlan.constrain_params`); XLA inserts the
    collectives.  ``mode="shard_map"`` plans wrap ``step_fn`` in
    ``jax.shard_map`` with the given ``in_specs``/``out_specs`` — the
    explicit-collectives formulation the legacy strategies use.  Either
    way the result lowers through ``timed_compile``: persistent cache,
    AOT warmup, compile metering and the HLO lint/feature pipe apply to
    EVERY plan.

    ``label`` names the program in ``zoo_compile_seconds{label=}`` /
    ``zoo_hlo_*{label=}`` (default ``<plan.name>_step``); ``meta``
    adds compile context (mesh axis shape, steps_per_dispatch) to the
    plan name in each ``zoo-hlo-report/2`` row.
    """
    # the choke point owns the compile plane end to end: a plan compiled
    # here gets the persistent cache whenever ZOO_COMPILE_CACHE is set,
    # even when no estimator entry point ran first (e.g. the eager
    # pipeline schedules).  Idempotent; no-op without the env knob.
    from analytics_zoo_tpu.common.compile_cache import (
        maybe_enable_persistent_cache,
    )

    maybe_enable_persistent_cache()
    plan = resolve_plan(plan)
    if plan.mode == "shard_map" or in_specs is not None:
        if in_specs is None or out_specs is None:
            raise ValueError(
                "shard_map-mode plans need explicit in_specs/out_specs")
        if mesh is None:
            from analytics_zoo_tpu.common.engine import get_zoo_context

            mesh = get_zoo_context().mesh
        step_fn = jax.shard_map(step_fn, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_vma=check_vma)
    if plan.remat_rules or plan.kernel_rules:
        # enter the plan for the duration of TRACING, so resolve_remat /
        # resolve_kernel inside any layer sees this plan's rule tables
        # (tracing happens under the jit call below, inside this
        # wrapper's with-block)
        inner = step_fn

        def step_fn(*args):
            with _active_plan(plan):
                return inner(*args)
    jitted = jax.jit(step_fn, donate_argnums=donate_argnums)
    full_meta = {"plan": plan.name, **(meta or {})}
    if "mesh_shape" not in full_meta and mesh is not None:
        full_meta["mesh_shape"] = dict(mesh.shape)
    if plan.dtype_rules and "dtype_policy" not in full_meta:
        # ride the compile meta into the zoo-hlo-report/2 rows AND the
        # hlo dtype-policy lint — the lowered program is checked against
        # the precision the plan declared
        full_meta["dtype_policy"] = plan.dtype_policy_str()
    if plan.kernel_rules and "kernel_policy" not in full_meta:
        full_meta["kernel_policy"] = plan.kernel_policy_str()
    return PlannedStep(jitted, label or f"{plan.name}_step", plan,
                       meta=full_meta)


# ---------------------------------------------------------------------------
# Introspection + checkpoint serialization helpers.
# ---------------------------------------------------------------------------


def per_chip_bytes(tree, device=None) -> int:
    """Bytes of ``tree`` resident on ONE device (default: the first
    device of the first leaf's sharding) — the quantity an fsdp/zero1
    plan shrinks.  Replicated leaves count full size; sharded leaves
    count one shard."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        shards = leaf.addressable_shards
        if not shards:
            continue
        if device is None:
            device = shards[0].device
        total += sum(s.data.nbytes for s in shards if s.device == device)
    return total


def live_bytes(device=None) -> dict:
    """Measured per-chip memory: ``{"live_bytes", "peak_bytes",
    "source"}`` for ONE device (default: the first).

    On accelerators with allocator stats the numbers come straight from
    ``device.memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use``).
    The CPU backend has no allocator stats, so the fallback sums the
    shard bytes of every live ``jax.Array`` resident on the device —
    live == peak there (what is referenced is what exists), which is
    exactly the persistent param+opt state the bench compares against
    :func:`~analytics_zoo_tpu.analysis.costmodel.predict_chip_bytes`."""
    if device is None:
        device = jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats and stats.get("bytes_in_use") is not None:
        in_use = int(stats["bytes_in_use"])
        return {"live_bytes": in_use,
                "peak_bytes": int(stats.get("peak_bytes_in_use", in_use)),
                "source": "memory_stats"}
    total = 0
    for arr in jax.live_arrays():
        try:
            for s in arr.addressable_shards:
                if s.device == device:
                    total += s.data.nbytes
        except Exception:  # deleted/donated buffers mid-iteration
            continue
    return {"live_bytes": int(total), "peak_bytes": int(total),
            "source": "live_arrays"}


def record_mem_gauges(label: str, predicted_bytes: int | None = None,
                      measured_bytes: int | None = None,
                      device=None) -> dict:
    """Publish the ``zoo_mem_*`` gauge family for one plan label —
    closing the memory loop the way ``zoo_oracle`` rel_error does for
    steps/sec: ``zoo_mem_live_bytes`` / ``zoo_mem_peak_bytes`` (from
    :func:`live_bytes`, or ``measured_bytes`` when the caller already
    measured, e.g. ``per_chip_bytes`` of the state it placed),
    ``zoo_mem_predicted_bytes`` and ``zoo_mem_rel_error`` when the cost
    model's prediction is given.  Returns the measured dict."""
    from analytics_zoo_tpu.metrics import get_registry

    if measured_bytes is not None:
        meas = {"live_bytes": int(measured_bytes),
                "peak_bytes": int(measured_bytes), "source": "caller"}
    else:
        meas = live_bytes(device)
    reg = get_registry()
    lab = ("label",)
    reg.gauge("zoo_mem_live_bytes",
              "measured per-chip bytes for a plan label",
              lab).labels(label=label).set(meas["live_bytes"])
    reg.gauge("zoo_mem_peak_bytes",
              "peak per-chip bytes for a plan label",
              lab).labels(label=label).set(meas["peak_bytes"])
    if predicted_bytes is not None:
        reg.gauge("zoo_mem_predicted_bytes",
                  "cost-model predicted per-chip bytes",
                  lab).labels(label=label).set(int(predicted_bytes))
        if predicted_bytes > 0:
            rel = abs(meas["live_bytes"] - predicted_bytes) / predicted_bytes
            reg.gauge("zoo_mem_rel_error",
                      "|measured - predicted| / predicted chip bytes",
                      lab).labels(label=label).set(rel)
    return meas


def record_dtype_gauges(label: str, plan: ShardingPlan, params) -> dict:
    """Publish the ``zoo_dtype_*`` gauge family for one plan label —
    the precision plane's observable: per-role leaf counts and COMPUTE
    bytes (what the role's compute dtype makes the leaf weigh in the
    step — bf16 halves, int8 quarters; role ``f32`` counts every
    unmatched/kept leaf at its stored size).  Returns
    ``{"roles": {role: {"leaves", "compute_bytes"}}, "master_bytes",
    "compute_bytes"}`` so benches can pin the bytes ratio."""
    from analytics_zoo_tpu.metrics import get_registry

    role_bytes = {"f32": 4, "bf16": 2, "f16": 2, "int8": 1}
    roles = plan.dtype_roles(params)
    per_role: dict = {}
    master_bytes = compute_bytes = 0
    from analytics_zoo_tpu.parallel.partition import leaf_path_name

    def visit(path, leaf):
        nonlocal master_bytes, compute_bytes
        if not hasattr(leaf, "dtype"):
            return leaf
        role = roles.get(leaf_path_name(path), "f32")
        size = int(np.size(leaf))
        stored = size * np.dtype(leaf.dtype).itemsize
        comp = size * role_bytes.get(role, 4) if role != "f32" else stored
        slot = per_role.setdefault(role,
                                   {"leaves": 0, "compute_bytes": 0})
        slot["leaves"] += 1
        slot["compute_bytes"] += comp
        master_bytes += stored
        compute_bytes += comp
        return leaf

    jax.tree_util.tree_map_with_path(visit, params)
    reg = get_registry()
    for role, slot in per_role.items():
        lab = ("label", "role")
        reg.gauge("zoo_dtype_leaves",
                  "param leaves per dtype role under a plan's "
                  "dtype_rules", lab).labels(
            label=label, role=role).set(slot["leaves"])
        reg.gauge("zoo_dtype_compute_bytes",
                  "compute-copy bytes per dtype role (master stays f32)",
                  lab).labels(
            label=label, role=role).set(slot["compute_bytes"])
    reg.gauge("zoo_dtype_bytes_ratio",
              "compute-copy bytes / master bytes for a plan label",
              ("label",)).labels(label=label).set(
        compute_bytes / master_bytes if master_bytes else 1.0)
    return {"roles": per_role, "master_bytes": int(master_bytes),
            "compute_bytes": int(compute_bytes)}


#: the logical op scopes the kernel plane routes (consumers listed in
#: :func:`resolve_kernel`) — what record_kernel_gauges resolves a plan's
#: table against
KERNEL_SCOPES = ("attention", "optimizer.adam", "loss.softmax_xent",
                 "serving.int8_matmul")


def record_kernel_gauges(label: str, plan: ShardingPlan) -> dict:
    """Publish the ``zoo_kernel_*`` selection/routing gauges for one
    plan label — the kernel plane's observable (the twin of
    :func:`record_dtype_gauges` for the fifth rule table):
    ``zoo_kernel_selections{label, scope, kernel}`` is what the plan's
    ``kernel_rules`` resolve to per known scope (kernel ``"xla"``
    included — a declined kernel is a decision, not an absence), and
    ``zoo_kernel_invocations{kernel, backend}`` re-exports each kernel
    module's pallas/fallback routing counters.  Returns
    ``{"selections": {scope: kernel}, "invocations": {...}}``."""
    from analytics_zoo_tpu.metrics import get_registry
    from analytics_zoo_tpu.ops.pallas import kernel_invocation_counts

    reg = get_registry()
    selections = {}
    for scope in KERNEL_SCOPES:
        kernel = plan.kernel_for(scope)
        if kernel is None:
            continue
        selections[scope] = kernel
        reg.gauge("zoo_kernel_selections",
                  "kernel a plan's kernel_rules resolve for an op scope "
                  "(1 = selected; 'xla' is the explicit fallback pick)",
                  ("label", "scope", "kernel")).labels(
            label=label, scope=scope, kernel=kernel).set(1)
    invocations = kernel_invocation_counts()
    for kernel, counts in invocations.items():
        for backend, n in counts.items():
            reg.gauge("zoo_kernel_invocations",
                      "per-kernel routing counter: compiles that took "
                      "the pallas path vs the jnp fallback",
                      ("kernel", "backend")).labels(
                kernel=kernel, backend=backend).set(n)
    return {"selections": selections, "invocations": invocations}


def serialize_specs(spec_tree) -> list:
    """PartitionSpec tree → plain-builtin leaves list (tree_leaves
    order) for checkpoint payloads: each spec becomes a list whose
    entries are None / axis name / list of axis names — survives
    ``safe_load`` without any custom-type allowlisting."""
    flat = jax.tree_util.tree_leaves(
        spec_tree, is_leaf=lambda s: isinstance(s, P))
    return [[list(e) if isinstance(e, (tuple, list)) else e
             for e in spec] for spec in flat]


def deserialize_specs(serialized: list) -> list:
    """Inverse of :func:`serialize_specs` (a flat list of
    PartitionSpecs, paired by position with the tree's leaves)."""
    return [P(*[tuple(e) if isinstance(e, list) else e for e in entries])
            for entries in serialized]
