"""Explicit shard_map strategies + tensor-parallel building blocks.

The default training path (pipeline/estimator) uses jit + NamedSharding and
lets XLA insert the gradient all-reduce.  This module is the *explicit*
formulation — ``psum`` written out — which (a) documents exactly where the
reference's AllReduceParameter shuffle+broadcast (docs/docs/wp-bigdl.md:
148-164) became one collective, and (b) gives manual control when XLA's
choices need overriding.

Also: Megatron-style column/row-parallel dense ops over the ``model`` axis —
the TP capability the reference never had (SURVEY.md §2.4 "rebuild
requirement: hooks for TP on the same mesh API").
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.common.engine import (
    DATA_AXIS,
    MODEL_AXIS,
    get_zoo_context,
)


def make_shard_map_train_step(model, loss_fn, optimizer, mesh=None,
                              grad_clip=None):
    """A train step as shard_map with explicit pmean — the literal
    TPU translation of the reference's two Spark jobs (local
    forward/backward, then gradient slice aggregation) into one SPMD
    program with a single collective.

    Now a thin wrapper over the unified partitioner: the per-shard body
    is unchanged, but it compiles through
    :func:`~analytics_zoo_tpu.parallel.plan.compile_step` (a
    ``mode="shard_map"`` plan), so the explicit strategy shares the
    persistent compile cache, ``zoo_compile_seconds`` and the HLO
    lint/feature pipe with every jit plan.
    """
    from analytics_zoo_tpu.parallel.plan import ShardingPlan, compile_step
    from analytics_zoo_tpu.pipeline.estimator.estimator import (
        _clip_grads,
        _normalize_grad_clip,
    )

    grad_clip = _normalize_grad_clip(grad_clip)
    mesh = mesh or get_zoo_context().mesh

    def local_step(params, opt_state, state, rng, batch):
        # per-shard forward/backward on the local batch slice
        # (= reference Spark job 1, Topology.scala:1178-1197)
        def loss_of(p):
            preds, new_state = model.forward(
                p, batch["x"], state=state, training=True, rng=rng
            )
            from analytics_zoo_tpu.ops.moe import collect_aux_cost

            l = loss_fn.mean(batch.get("y"), preds)
            # MoE stacks report their pre-weighted load-balancing cost
            # through the state channel; it must join every training loss
            return l + collect_aux_cost(new_state), new_state

        (l, new_state), grads = jax.value_and_grad(
            loss_of, has_aux=True
        )(params)
        # gradient all-reduce over ICI (= reference Spark job 2: gradient
        # shuffle to parameter slices + task-side broadcast)
        grads = jax.lax.pmean(grads, DATA_AXIS)
        l = jax.lax.pmean(l, DATA_AXIS)
        new_state = jax.lax.pmean(new_state, DATA_AXIS)
        grads = _clip_grads(grads, grad_clip)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_state, l

    repl = P()
    batch_spec = P(DATA_AXIS)
    plan = ShardingPlan(name="shard_map_dp", mode="shard_map",
                        description="explicit-psum data parallelism")
    return compile_step(
        local_step, plan, mesh,
        in_specs=(repl, repl, repl, repl, batch_spec),
        out_specs=(repl, repl, repl, repl),
        donate_argnums=(0, 1, 2), label="shard_map_step")


def _ring_reduce_scatter(flat, n, axis_name=DATA_AXIS):
    """Reduce-scatter spelled as an explicit ``ppermute`` ring: the flat
    vector (size divisible by ``n``) is viewed as ``n`` blocks, partial
    sums circulate the ring for ``n-1`` hops, and chip ``i`` ends holding
    block ``i`` fully summed.  The summation is LEFT-ASSOCIATIVE and
    sequential — a different reduction grouping from ``psum_scatter``'s
    tree, so trajectories are ulp-recorded, not bitwise (same caveat as
    zero1 vs dp)."""
    m = flat.size // n
    blocks = flat.reshape(n, m)
    idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j - 1) % n) for j in range(n)]
    acc = jax.lax.dynamic_index_in_dim(blocks, (idx + 1) % n, 0,
                                       keepdims=False)
    for r in range(1, n):
        acc = jax.lax.ppermute(acc, axis_name, perm)
        acc = acc + jax.lax.dynamic_index_in_dim(
            blocks, (idx + 1 + r) % n, 0, keepdims=False)
    return acc


def make_zero1_train_step(model, loss_fn, optimizer, mesh=None,
                          grad_clip=None, bucket_bytes=None, ring=False):
    """Data-parallel step with a SHARDED optimizer (ZeRO-1 spelled out):
    gradients are ``psum_scatter`` (reduce-scatter) onto each chip's 1/n
    slice of the flattened parameter vector, the optimizer update runs on
    that slice only (opt state lives at 1/n per chip — the memory win; an
    Adam state is 2× params), and one tiled ``all_gather`` restores the
    full parameters.  Communication volume equals the plain all-reduce
    (all-reduce ≡ reduce-scatter + all-gather); memory and update compute
    drop by the data-axis size.

    ``bucket_bytes`` turns the single whole-vector reduce-scatter into
    CHUNKED reduce-scatters: gradient leaves are grouped into
    ~bucket-sized contiguous flat-vector slices in backward-completion
    (reverse-traversal) order, and each bucket's collective is issued as
    its own op, chained by ``optimization_barrier`` tokens so the
    scheduler can overlap bucket k+1's backward segment with bucket k's
    scatter.  Per-element reduction grouping is unchanged, but the
    per-chunk padding changes each chip's slice composition — a
    different compiled program, so XLA fusion (fma contraction) may
    drift the trajectory by ~1 ulp vs the unbucketed step; record it
    like zero1 vs dp.  (The GSPMD spelling,
    ``plan.zero1(overlap=True)`` through the estimator, keeps the exact
    program and IS bitwise-pinned.)  ``ring=True``
    replaces ``psum_scatter`` with the explicit
    :func:`_ring_reduce_scatter` ``ppermute`` ring, whose left-assoc
    summation is ulp-recorded like zero1 vs dp.

    Returns ``(step, init_opt_state)``: the optimizer state is a
    per-shard pytree, so it must be created by ``init_opt_state(params)``
    (and checkpointed as-is — it is a different layout from the plain
    step's, and the bucketed layout differs again: per-chunk padding
    changes each chip's slice composition, which is why the bucketed
    variants compile/init under their own labels).

    Like :func:`make_shard_map_train_step`, this is now a thin wrapper
    over the partitioner's choke point: both the step AND
    ``init_opt_state`` compile through
    :func:`~analytics_zoo_tpu.parallel.plan.compile_step`.  (The GSPMD
    spelling of the same idea — and of full FSDP — is
    ``plan.zero1()`` / ``plan.fsdp()`` through the estimator; its
    bucketed spelling is ``plan.zero1(overlap=True)``.)
    """
    from jax.flatten_util import ravel_pytree

    from analytics_zoo_tpu.parallel.plan import (
        ShardingPlan,
        compile_step,
        grad_bucket_indices,
    )
    from analytics_zoo_tpu.pipeline.estimator.estimator import (
        _normalize_grad_clip,
    )

    # same grad_clip contract as make_shard_map_train_step / the Estimator
    _clip = _normalize_grad_clip(grad_clip)
    mesh = mesh or get_zoo_context().mesh
    n = mesh.shape[DATA_AXIS]
    if bucket_bytes is not None:
        bucket_bytes = int(bucket_bytes)
        if bucket_bytes < 1:
            raise ValueError(
                f"bucket_bytes must be a positive byte count, "
                f"got {bucket_bytes!r}")

    def _bucket_slices(tree):
        """Contiguous ``(lo, hi)`` flat-vector slices, one per gradient
        bucket, in backward-completion (tail-first) order; a single
        whole-vector slice when unbucketed."""
        leaves = jax.tree_util.tree_leaves(tree)
        sizes = [int(leaf.size) for leaf in leaves]
        offs = [0]
        for s in sizes:
            offs.append(offs[-1] + s)
        if bucket_bytes is None:
            return [(0, offs[-1])]
        buckets = grad_bucket_indices(leaves, bucket_bytes)
        # each bucket is a descending contiguous index run → one slice
        return [(offs[b[-1]], offs[b[0]] + sizes[b[0]]) for b in buckets]

    def _shard_of(flat, slices):
        """This chip's slice of each (padded) chunk, concatenated in
        bucket order — the unbucketed layout when ``slices`` is the
        single whole-vector slice."""
        idx = jax.lax.axis_index(DATA_AXIS)
        parts = []
        for lo, hi in slices:
            c = jnp.pad(flat[lo:hi], (0, (-(hi - lo)) % n))
            m = c.size // n
            parts.append(jax.lax.dynamic_slice(c, (idx * m,), (m,)))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def _local_init(params):
        flat, _ = ravel_pytree(params)
        return optimizer.init(_shard_of(flat, _bucket_slices(params)))

    repl = P()
    # optimizer-state layout: 1-D leaves mirror the flat param shard
    # (sharded over data); 0-D leaves (e.g. Adam's step count) replicate.
    # The structure is m-independent, so probe it with a dummy shard.
    proto = jax.eval_shape(optimizer.init,
                           jax.ShapeDtypeStruct((8,), jnp.float32))
    opt_specs = jax.tree_util.tree_map(
        lambda leaf: P(DATA_AXIS) if getattr(leaf, "ndim", 0) >= 1
        else repl, proto)

    variant = ("_bucketed" if bucket_bytes is not None else "") + \
              ("_ring" if ring else "")
    plan = ShardingPlan(name="zero1_explicit", mode="shard_map",
                        bucket_bytes=bucket_bytes,
                        description="explicit reduce-scatter/all-gather "
                                    "ZeRO-1 on the padded flat vector")

    def init_opt_state(params):
        fn = compile_step(_local_init, plan, mesh, in_specs=(repl,),
                          out_specs=opt_specs,
                          label=f"zero1{variant}_init_opt_state")
        return fn(params)

    def local_step(params, opt_state, state, rng, batch):
        def loss_of(p):
            preds, new_state = model.forward(
                p, batch["x"], state=state, training=True, rng=rng
            )
            from analytics_zoo_tpu.ops.moe import collect_aux_cost

            l = loss_fn.mean(batch.get("y"), preds)
            # MoE stacks report their pre-weighted load-balancing cost
            # through the state channel; it must join every training loss
            return l + collect_aux_cost(new_state), new_state

        (l, new_state), grads = jax.value_and_grad(
            loss_of, has_aux=True
        )(params)
        l = jax.lax.pmean(l, DATA_AXIS)
        new_state = jax.lax.pmean(new_state, DATA_AXIS)

        flat_g, _ = ravel_pytree(grads)
        slices = _bucket_slices(grads)
        # reduce-scatter: each chip ends with the MEAN of its own slice
        # (of each bucket's chunk, when bucketed — issued tail-first in
        # backward-completion order, barrier-chained to pin the schedule)
        shard_parts = []
        token = None
        for lo, hi in slices:
            c = jnp.pad(flat_g[lo:hi], (0, (-(hi - lo)) % n))
            if token is not None:
                c, token = jax.lax.optimization_barrier((c, token))
            red = (_ring_reduce_scatter(c, n) if ring else
                   jax.lax.psum_scatter(
                       c, DATA_AXIS, scatter_dimension=0, tiled=True)) / n
            token = red
            shard_parts.append(red)
        g_shard = (shard_parts[0] if len(shard_parts) == 1
                   else jnp.concatenate(shard_parts))
        if _clip is not None:
            if _clip[0] == "const":
                g_shard = jnp.clip(g_shard, _clip[1], _clip[2])
            else:  # l2norm: global norm from shard norms, one scalar psum
                gn = jnp.sqrt(jax.lax.psum(jnp.sum(g_shard ** 2), DATA_AXIS))
                scale = jnp.minimum(1.0, _clip[1] / jnp.maximum(gn, 1e-12))
                g_shard = g_shard * scale
        flat_p, unravel = ravel_pytree(params)
        p_shard = _shard_of(flat_p, slices)
        updates, opt_state = optimizer.update(g_shard, opt_state, p_shard)
        p_shard = optax.apply_updates(p_shard, updates)
        # all-gather the updated slices back into the full vector —
        # per chunk when bucketed, reassembled in forward (offset) order
        fulls = []
        off = 0
        for lo, hi in slices:
            m = ((hi - lo) + (-(hi - lo)) % n) // n
            part = p_shard[off:off + m] if len(slices) > 1 else p_shard
            off += m
            fulls.append((lo, jax.lax.all_gather(
                part, DATA_AXIS, tiled=True)[:hi - lo]))
        full = (fulls[0][1] if len(fulls) == 1 else jnp.concatenate(
            [f for _, f in sorted(fulls, key=lambda t: t[0])]))
        return unravel(full), opt_state, new_state, l

    batch_spec = P(DATA_AXIS)
    step = compile_step(
        local_step, plan, mesh,
        in_specs=(repl, opt_specs, repl, repl, batch_spec),
        out_specs=(repl, opt_specs, repl, repl),
        donate_argnums=(0, 1, 2), label=f"zero1{variant}_step")
    return step, init_opt_state


def reshard_zero1_opt_state(opt_state, params, mesh=None,
                            n_old: int | None = None,
                            dtype_policy: str | None = None):
    """Re-lay an explicit-ZeRO-1 optimizer state (the
    :func:`make_zero1_train_step` layout) for a DIFFERENT data-axis size —
    the elastic slice-down/up restart (SURVEY §5): save on ``{data: 8}``,
    resume on ``{data: 4}`` or vice versa.

    The layout's only mesh-shape dependence is the flat vector's zero-pad
    to a multiple of the data-axis size n: every 1-D leaf is (a moment
    mirror of) the padded flat param vector, so resharding = strip the old
    pad, re-pad for the new n, and place sharded over ``data`` on the new
    mesh.  0-D leaves (step counts) replicate unchanged.  Works on host
    numpy trees (a loaded checkpoint) or live jax.Arrays.

    The estimator's GSPMD ZeRO-1 path needs none of this: its checkpoint
    stores global logical arrays, so restoring onto a different mesh is
    just a device_put (tests/test_elastic_resume.py proves both paths).

    Flat-vector leaves are matched by EXACT padded length (ADVICE r05
    low), not by ``size >= param_size``: pass ``n_old`` (the data-axis
    size the state was saved under) for the exact expected length
    ``size + (-size) % n_old``; without it, the length is inferred as
    the smallest 1-D leaf length >= the param count that is SHARED by at
    least two leaves (the moment mirrors always agree on one padded
    length; a coincidental unrelated 1-D leaf is almost surely unique),
    falling back to the smallest overall for single-mirror states.
    Pass ``n_old`` when the state shape is unusual.  Leaves that do NOT
    match the flat-vector layout are left value-untouched and REPLICATED
    (the plan's rules name exactly the matched flat vectors by tree
    path) — never truncated, never force-sharded onto a dimension the
    new mesh cannot divide.

    Placement goes through :meth:`ShardingPlan.place_opt_state` — the
    same rule→spec→clamp path every canned plan uses — so the explicit
    layout shares one placement code path with the GSPMD plans.

    ``dtype_policy`` (a ``ShardingPlan.dtype_policy_str()`` rule string)
    is carried onto the explicit plan's ``dtype_rules`` so the resharded
    state's placement record keeps the precision contract it was trained
    under — resuming it under a different policy fails loudly at the
    estimator's resume guard instead of silently mixing master widths
    (docs/parallelism.md "Precision plane").
    """
    import re

    from jax.flatten_util import ravel_pytree

    import numpy as np

    from .partition import leaf_path_name
    from .plan import ShardingPlan, resolve_dtype_rules

    mesh = mesh or get_zoo_context().mesh
    n_new = dict(mesh.shape)[DATA_AXIS]
    size = ravel_pytree(params)[0].size
    pad_new = (-size) % n_new

    if n_old is not None:
        expected = size + ((-size) % int(n_old))
    else:
        cands = [np.size(l) for l in jax.tree_util.tree_leaves(opt_state)
                 if np.ndim(l) == 1 and np.size(l) >= size]
        # prefer a length SHARED by >=2 leaves: the moment mirrors (mu,
        # nu) always agree on the padded length, while a coincidental
        # unrelated 1-D leaf in [size, size+pad) is almost surely unique
        # — picking it would truncate it AND leave the real flat vectors
        # un-resharded
        shared = [c for c in cands if cands.count(c) >= 2]
        expected = min(shared) if shared else (
            min(cands) if cands else None)

    def is_flat_vec(leaf) -> bool:
        return np.ndim(leaf) == 1 and np.size(leaf) == expected

    def fix(leaf):
        # stay on the HOST until the final sharded device_put: jnp ops
        # here would transiently materialize every params-sized moment on
        # one device — the allocation ZeRO-1 exists to avoid
        leaf = np.asarray(leaf)
        if is_flat_vec(leaf):
            return np.pad(leaf[:size], (0, pad_new))
        return leaf

    out = jax.tree_util.tree_map(fix, opt_state)
    # placement through the partitioner: the rules name EXACTLY the
    # flat vectors is_flat_vec matched (by rendered tree path), so the
    # plan shards those over data and replicates every other leaf —
    # including a coincidental 1-D leaf whose length happens to divide
    # n_new, which a blanket catch-all rule would wrongly shard
    matched = {
        leaf_path_name(path)
        for path, leaf in jax.tree_util.tree_flatten_with_path(opt_state)[0]
        if is_flat_vec(leaf)
    }
    plan = ShardingPlan(
        name="zero1_explicit",
        opt_rules=tuple((rf"^{re.escape(name)}$", P(DATA_AXIS))
                        for name in sorted(matched))
        + ((r".*", P()),),
        dtype_rules=resolve_dtype_rules(dtype_policy))
    return plan.place_opt_state(out, mesh)


# ---------------------------------------------------------------------------
# Tensor-parallel dense blocks (model axis)
# ---------------------------------------------------------------------------


def column_parallel_dense(x, kernel, bias=None, axis_name=MODEL_AXIS):
    """Y_local = x @ W_local where W is column-sharded: no collective on the
    forward (outputs stay sharded on the feature dim)."""
    y = x @ kernel
    if bias is not None:
        y = y + bias
    return y


def row_parallel_dense(x_local, kernel, bias=None, axis_name=MODEL_AXIS):
    """Y = psum_over_model(x_local @ W_local): input feature dim is sharded,
    one psum restores the full output (Megatron row-parallel)."""
    y = jax.lax.psum(x_local @ kernel, axis_name)
    if bias is not None:
        y = y + bias
    return y


def tp_mlp(x, w1, b1, w2, b2, axis_name=MODEL_AXIS, activation=jax.nn.gelu):
    """Column-parallel up-projection + row-parallel down-projection: ONE
    psum per MLP block — the canonical TP transformer feed-forward."""
    h = activation(column_parallel_dense(x, w1, b1))
    return row_parallel_dense(h, w2, b2, axis_name=axis_name)


def moe_mlp_topk(x, gate_w, w1, b1, w2, b2, top_k=2, capacity_factor=1.25,
                 axis_name=None, renormalize=False, return_aux=False):
    """GShard/Switch-style **routed** MoE feed-forward: top-k routing with
    expert capacity and ``all_to_all`` dispatch over the ``expert`` mesh
    axis.  This is the scalable counterpart of :func:`ep_moe_mlp` (dense
    dispatch, kept as the correctness oracle: with ``top_k=E`` and
    ``capacity_factor`` >= 1 the two are numerically equal).

    Per shard: tokens pick their top-k experts from the full router; the
    assignment stream is priority-ordered (all 1st choices first, then 2nd
    choices, token order within a choice) and each expert accepts at most
    ``C = ceil(capacity_factor * top_k * T / E)`` assignments — the rest
    are dropped (output contribution zero, the standard Switch semantics).
    Kept tokens are scattered into a per-expert ``(E, C, D)`` buffer, an
    ``all_to_all`` ships each expert's buffer to its owning shard, the
    owner runs its experts' MLP on ``(E_local, n_shards*C, D)``, and the
    reverse ``all_to_all`` + gather + gate-weighted scatter-add rebuilds
    the token outputs.  EP FLOPs are O(top_k/E) of dense dispatch.

    Args (inside shard_map, all local views):
      x: (T, D) this shard's tokens (shard tokens over the expert axis; a
        replicated x is also correct, just redundant compute).
      gate_w: (D, E) the FULL router, replicated over the expert axis.
      w1: (E_local, D, F), b1: (E_local, F), w2: (E_local, F, D): this
        shard's experts.  b2: (D,) replicated.
      renormalize: rescale the k gate values to sum to 1 (GShard top-2
        convention); default False (Switch: raw softmax probs).
      return_aux: also return the load-balancing auxiliary loss
        (E * sum_e mean_prob_e * frac_first_choice_e, pmean'd over the
        expert axis — ~1.0 when perfectly balanced).
    Returns: (T, D) [, aux scalar].
    """
    import math

    from analytics_zoo_tpu.common.engine import EXPERT_AXIS

    axis_name = axis_name or EXPERT_AXIS
    t, d = x.shape
    e_local = w1.shape[0]
    e = gate_w.shape[1]
    if e % e_local:
        raise ValueError(
            f"router width E={e} must be a multiple of the local expert "
            f"count E_local={e_local} (w1 leading dim)")
    cap = int(math.ceil(capacity_factor * top_k * t / e))
    cap = max(1, min(cap, t))

    probs = jax.nn.softmax((x @ gate_w).astype(jnp.float32), axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, top_k)  # (T, k)
    if renormalize:
        top_vals = top_vals / jnp.sum(top_vals, -1, keepdims=True)
    # assignment stream, priority-ordered: k-major so every token's 1st
    # choice outranks any 2nd choice in the capacity race
    expert = top_idx.T.reshape(-1)                      # (kT,)
    gatev = top_vals.T.reshape(-1).astype(x.dtype)      # (kT,)
    tok = jnp.tile(jnp.arange(t), top_k)                # (kT,)
    oh = jax.nn.one_hot(expert, e, dtype=jnp.int32)     # (kT, E)
    slot = jnp.sum((jnp.cumsum(oh, 0) - 1) * oh, 1)     # slot within expert
    keep = slot < cap
    slot_c = jnp.where(keep, slot, 0)
    # scatter kept tokens into per-expert buffers; dropped assignments
    # scatter-add zeros (slot collisions impossible for kept: cumsum slots
    # are unique per expert)
    contrib = jnp.where(keep[:, None], x[tok], 0.0)
    buf = jnp.zeros((e, cap, d), x.dtype).at[expert, slot_c].add(contrib)
    # ship each expert's buffer to its owner shard; receive every shard's
    # buffer for OUR experts: (E, C, D) -> (E_local, n_sh*C, D)
    recv = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=1,
                              tiled=True)
    h = jax.nn.gelu(jnp.einsum("etd,edf->etf", recv, w1) + b1[:, None, :])
    y = jnp.einsum("etf,efd->etd", h, w2)  # (E_local, n_sh*C, D)
    # reverse path: give every shard back its slots
    back = jax.lax.all_to_all(y, axis_name, split_axis=1, concat_axis=0,
                              tiled=True)  # (E, C, D)
    got = back[expert, slot_c] * jnp.where(keep, gatev, 0.0)[:, None]
    out = jnp.zeros((t, d), x.dtype).at[tok].add(got) + b2
    if not return_aux:
        return out
    # GShard load-balance loss on global statistics (tokens are sharded
    # over the expert axis, so pmean the per-shard means)
    me = jax.lax.pmean(jnp.mean(probs, 0), axis_name)
    ce = jax.lax.pmean(
        jnp.mean(jax.nn.one_hot(top_idx[:, 0], e, dtype=jnp.float32), 0),
        axis_name)
    aux = e * jnp.sum(me * ce)
    return out, aux


def ep_moe_mlp(x, gate_w, w1, b1, w2, b2, axis_name=None):
    """Expert-parallel dense-dispatch MoE feed-forward.

    Experts are SHARDED over the mesh ``expert`` axis: each shard holds
    ``E_local`` experts' weights and computes the gated contribution of its
    experts for EVERY token; one ``psum`` over the expert axis sums the
    contributions (and the gate's softmax denominator).  No all_to_all /
    token routing: tokens stay data/seq-local, weights stay expert-local —
    the EP capability hook the reference never had (SURVEY.md §2.4).

    Args (inside shard_map, all local views):
      x: (..., D) tokens (replicated over the expert axis).
      gate_w: (D, E_local) this shard's columns of the global gate.
      w1: (E_local, D, F), b1: (E_local, F)
      w2: (E_local, F, D), b2: (D,) replicated.
    Returns: (..., D), replicated over the expert axis.
    """
    from analytics_zoo_tpu.common.engine import EXPERT_AXIS

    axis_name = axis_name or EXPERT_AXIS
    # numerically-stable global softmax over experts, computed shard-wise:
    logits = x @ gate_w  # (..., E_local)
    # max-subtraction is gradient-neutral; stop_gradient keeps autodiff out
    # of pmax (which has no differentiation rule)
    local_max = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
    global_max = jax.lax.pmax(local_max, axis_name)
    expg = jnp.exp(logits - global_max[..., None])
    denom = jax.lax.psum(jnp.sum(expg, axis=-1), axis_name)
    gates = expg / denom[..., None]  # (..., E_local), sums to 1 globally
    # per-expert MLP, gated and summed over the local experts
    h = jax.nn.gelu(jnp.einsum("...d,edf->...ef", x, w1) + b1)
    y_e = jnp.einsum("...ef,efd->...ed", h, w2)
    local = jnp.einsum("...ed,...e->...d", y_e, gates)
    return jax.lax.psum(local, axis_name) + b2
