"""Explicit shard_map strategies + tensor-parallel building blocks.

The default training path (pipeline/estimator) uses jit + NamedSharding and
lets XLA insert the gradient all-reduce.  This module is the *explicit*
formulation — ``psum`` written out — which (a) documents exactly where the
reference's AllReduceParameter shuffle+broadcast (docs/docs/wp-bigdl.md:
148-164) became one collective, and (b) gives manual control when XLA's
choices need overriding.

Also: Megatron-style column/row-parallel dense ops over the ``model`` axis —
the TP capability the reference never had (SURVEY.md §2.4 "rebuild
requirement: hooks for TP on the same mesh API").
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.common.engine import (
    DATA_AXIS,
    MODEL_AXIS,
    get_zoo_context,
)


def make_shard_map_train_step(model, loss_fn, optimizer, mesh=None,
                              grad_clip=None):
    """A train step as shard_map with explicit pmean — the literal
    TPU translation of the reference's two Spark jobs (local
    forward/backward, then gradient slice aggregation) into one SPMD
    program with a single collective."""
    from analytics_zoo_tpu.pipeline.estimator.estimator import _clip_grads

    mesh = mesh or get_zoo_context().mesh

    def local_step(params, opt_state, state, rng, batch):
        # per-shard forward/backward on the local batch slice
        # (= reference Spark job 1, Topology.scala:1178-1197)
        def loss_of(p):
            preds, new_state = model.forward(
                p, batch["x"], state=state, training=True, rng=rng
            )
            return loss_fn.mean(batch.get("y"), preds), new_state

        (l, new_state), grads = jax.value_and_grad(
            loss_of, has_aux=True
        )(params)
        # gradient all-reduce over ICI (= reference Spark job 2: gradient
        # shuffle to parameter slices + task-side broadcast)
        grads = jax.lax.pmean(grads, DATA_AXIS)
        l = jax.lax.pmean(l, DATA_AXIS)
        new_state = jax.lax.pmean(new_state, DATA_AXIS)
        grads = _clip_grads(grads, grad_clip)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_state, l

    repl = P()
    batch_spec = P(DATA_AXIS)
    step = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(repl, repl, repl, repl, batch_spec),
        out_specs=(repl, repl, repl, repl),
        check_vma=False,
    )
    return jax.jit(step, donate_argnums=(0, 1, 2))


# ---------------------------------------------------------------------------
# Tensor-parallel dense blocks (model axis)
# ---------------------------------------------------------------------------


def column_parallel_dense(x, kernel, bias=None, axis_name=MODEL_AXIS):
    """Y_local = x @ W_local where W is column-sharded: no collective on the
    forward (outputs stay sharded on the feature dim)."""
    y = x @ kernel
    if bias is not None:
        y = y + bias
    return y


def row_parallel_dense(x_local, kernel, bias=None, axis_name=MODEL_AXIS):
    """Y = psum_over_model(x_local @ W_local): input feature dim is sharded,
    one psum restores the full output (Megatron row-parallel)."""
    y = jax.lax.psum(x_local @ kernel, axis_name)
    if bias is not None:
        y = y + bias
    return y


def tp_mlp(x, w1, b1, w2, b2, axis_name=MODEL_AXIS, activation=jax.nn.gelu):
    """Column-parallel up-projection + row-parallel down-projection: ONE
    psum per MLP block — the canonical TP transformer feed-forward."""
    h = activation(column_parallel_dense(x, w1, b1))
    return row_parallel_dense(h, w2, b2, axis_name=axis_name)


def ep_moe_mlp(x, gate_w, w1, b1, w2, b2, axis_name=None):
    """Expert-parallel dense-dispatch MoE feed-forward.

    Experts are SHARDED over the mesh ``expert`` axis: each shard holds
    ``E_local`` experts' weights and computes the gated contribution of its
    experts for EVERY token; one ``psum`` over the expert axis sums the
    contributions (and the gate's softmax denominator).  No all_to_all /
    token routing: tokens stay data/seq-local, weights stay expert-local —
    the EP capability hook the reference never had (SURVEY.md §2.4).

    Args (inside shard_map, all local views):
      x: (..., D) tokens (replicated over the expert axis).
      gate_w: (D, E_local) this shard's columns of the global gate.
      w1: (E_local, D, F), b1: (E_local, F)
      w2: (E_local, F, D), b2: (D,) replicated.
    Returns: (..., D), replicated over the expert axis.
    """
    from analytics_zoo_tpu.common.engine import EXPERT_AXIS

    axis_name = axis_name or EXPERT_AXIS
    # numerically-stable global softmax over experts, computed shard-wise:
    logits = x @ gate_w  # (..., E_local)
    # max-subtraction is gradient-neutral; stop_gradient keeps autodiff out
    # of pmax (which has no differentiation rule)
    local_max = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
    global_max = jax.lax.pmax(local_max, axis_name)
    expg = jnp.exp(logits - global_max[..., None])
    denom = jax.lax.psum(jnp.sum(expg, axis=-1), axis_name)
    gates = expg / denom[..., None]  # (..., E_local), sums to 1 globally
    # per-expert MLP, gated and summed over the local experts
    h = jax.nn.gelu(jnp.einsum("...d,edf->...ef", x, w1) + b1)
    y_e = jnp.einsum("...ef,efd->...ed", h, w2)
    local = jnp.einsum("...ed,...e->...d", y_e, gates)
    return jax.lax.psum(local, axis_name) + b2
