"""Regex partition rules: name-pattern → PartitionSpec for whole pytrees.

The estimator's default is data parallelism with replicated params; TP/PP
users need per-parameter shardings.  Writing a PartitionSpec pytree by hand
for a 100-layer model is the failure mode; the idiomatic TPU approach
(T5X/fmengine-style) is a small ordered rule table matched against the
parameter's tree path:

    rules = [
        (r"dense_\\d+/kernel", P(None, "model")),
        (r"embedding", P("model", None)),
        (r".*", P()),                      # default: replicate
    ]
    specs = match_partition_rules(rules, params)
    shardings = tree_shardings(mesh, specs)

Scalars and size-1 leaves are never partitioned (a spec would be wasted on
them and some optimizers carry scalar state).

These primitives are consumed by :mod:`analytics_zoo_tpu.parallel.plan`
(the unified partitioner): a :class:`~analytics_zoo_tpu.parallel.plan.
ShardingPlan` is an ordered rule table plus the compile contract around it.
"""

from __future__ import annotations

import logging
import re
from typing import Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

logger = logging.getLogger("analytics_zoo_tpu")


def leaf_path_name(path) -> str:
    """Render a jax tree path as a '/'-joined name.

    The rendering is the STABLE rule-matching contract (regexes in
    partition rules match against it), so every key type is rendered
    explicitly rather than through its jax ``repr`` (which has moved
    across jax versions):

    - ``DictKey(k)``   → ``str(k)`` (mapping keys)
    - ``SequenceKey(i)`` → ``str(i)`` (list/tuple positions)
    - ``GetAttrKey(n)`` → ``str(n)`` (dataclass / namedtuple fields)
    - ``FlattenedIndexKey(i)`` → ``str(i)`` (leaves of opaque custom
      nodes, e.g. some optax states flatten positionally)

    Nested containers join with '/': ``{"a": [{"w": ...}]}`` renders its
    leaf as ``a/0/w``; tests/test_partition_rules.py pins the rendering
    for dict/list/tuple/dataclass/flattened trees.
    """
    parts = []
    tu = jax.tree_util
    for k in path:
        if isinstance(k, tu.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, tu.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, tu.GetAttrKey):
            parts.append(str(k.name))
        elif isinstance(k, getattr(tu, "FlattenedIndexKey", ())):
            parts.append(str(k.key))
        else:  # future key types: fall back to their payload, not repr
            parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return "/".join(parts)


def match_partition_rules(
    rules: Sequence[Tuple[str, P]], params, *, report_unused: bool = False
):
    """PartitionSpec pytree for ``params``: first rule whose regex
    ``re.search``-matches the leaf's '/'-joined path wins.

    Raises ValueError naming the unmatched parameter if no rule matches —
    add a catch-all ``(r".*", P())`` as the last rule to default-replicate.

    ``report_unused=True`` returns ``(specs, unused)`` where ``unused``
    is the list of rule patterns that matched ZERO leaves — a typo'd
    regex otherwise silently falls through to the catch-all and
    replicates (or mis-shards) the whole model; unused rules are also
    logged at WARNING.  Deliberate ``.*`` catch-alls are exempt (a
    catch-all that everything outranked cannot be a typo), and the
    audit runs only when asked — spec resolution happens several times
    per fit (placement, constraints, the checkpoint record), and a
    legitimately rule-free tree (an all-scalar optimizer state) must
    not cry wolf on each one.  The estimator audits its plan's param
    rules once per fit.
    """
    rules = list(rules)
    hit_counts = [0] * len(rules)

    def spec_for(path, leaf):
        name = leaf_path_name(path)
        if np.ndim(leaf) == 0 or np.size(leaf) == 1:
            return P()
        for i, (pattern, spec) in enumerate(rules):
            if re.search(pattern, name):
                hit_counts[i] += 1
                return spec
        raise ValueError(f"no partition rule matches parameter {name!r}")

    specs = jax.tree_util.tree_map_with_path(spec_for, params)
    if not report_unused:
        return specs
    unused = [pattern for (pattern, _), n in zip(rules, hit_counts)
              if n == 0 and pattern not in (r".*", ".*")]
    if unused:
        logger.warning(
            "partition rules matched zero leaves (typo'd regex?): %s",
            unused)
    return specs, unused


def match_rule_values(rules, tree, *, default=None, skip_scalars=True):
    """First-match rule table over leaf paths → arbitrary VALUES — the
    generic sibling of :func:`match_partition_rules` for rule tables
    whose right-hand side is not a PartitionSpec (a plan's
    ``dtype_rules`` map paths to dtype-role names).

    Unlike partition matching, an unmatched leaf is NOT an error: a
    value table is an overlay (leaves without a rule get ``default``),
    not a layout that must cover the tree.  ``skip_scalars`` keeps
    scalar / size-1 leaves at ``default`` — a loss scale or step count
    must never be down-cast by a catch-all rule.
    """
    rules = [(str(pat), val) for pat, val in rules]

    def value_for(path, leaf):
        if skip_scalars and (np.ndim(leaf) == 0 or np.size(leaf) == 1):
            return default
        name = leaf_path_name(path)
        for pattern, val in rules:
            if re.search(pattern, name):
                return val
        return default

    return jax.tree_util.tree_map_with_path(value_for, tree)


def tree_shardings(mesh, specs):
    """NamedSharding pytree from a PartitionSpec pytree (for device_put /
    jit in_shardings)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P),
    )


def shard_params(mesh, rules, params):
    """device_put ``params`` according to ``rules`` — one call from an
    unsharded pytree to a mesh-laid-out one."""
    specs = match_partition_rules(rules, params)
    return jax.device_put(params, tree_shardings(mesh, specs))
