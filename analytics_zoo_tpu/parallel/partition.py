"""Regex partition rules: name-pattern → PartitionSpec for whole pytrees.

The estimator's default is data parallelism with replicated params; TP/PP
users need per-parameter shardings.  Writing a PartitionSpec pytree by hand
for a 100-layer model is the failure mode; the idiomatic TPU approach
(T5X/fmengine-style) is a small ordered rule table matched against the
parameter's tree path:

    rules = [
        (r"dense_\\d+/kernel", P(None, "model")),
        (r"embedding", P("model", None)),
        (r".*", P()),                      # default: replicate
    ]
    specs = match_partition_rules(rules, params)
    shardings = tree_shardings(mesh, specs)

Scalars and size-1 leaves are never partitioned (a spec would be wasted on
them and some optimizers carry scalar state).
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def leaf_path_name(path) -> str:
    """Render a jax tree path as a '/'-joined name (dict keys, sequence
    indices, dataclass field names)."""
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(str(k.name))
        else:  # FlattenedIndexKey and anything else
            parts.append(str(getattr(k, "key", k)))
    return "/".join(parts)


def match_partition_rules(
    rules: Sequence[Tuple[str, P]], params
):
    """PartitionSpec pytree for ``params``: first rule whose regex
    ``re.search``-matches the leaf's '/'-joined path wins.

    Raises ValueError naming the unmatched parameter if no rule matches —
    add a catch-all ``(r".*", P())`` as the last rule to default-replicate.
    """

    def spec_for(path, leaf):
        name = leaf_path_name(path)
        if np.ndim(leaf) == 0 or np.size(leaf) == 1:
            return P()
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        raise ValueError(f"no partition rule matches parameter {name!r}")

    return jax.tree_util.tree_map_with_path(spec_for, params)


def tree_shardings(mesh, specs):
    """NamedSharding pytree from a PartitionSpec pytree (for device_put /
    jit in_shardings)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P),
    )


def shard_params(mesh, rules, params):
    """device_put ``params`` according to ``rules`` — one call from an
    unsharded pytree to a mesh-laid-out one."""
    specs = match_partition_rules(rules, params)
    return jax.device_put(params, tree_shardings(mesh, specs))
